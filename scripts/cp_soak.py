#!/usr/bin/env python
"""Control-plane churn soak: N shard servers vs thousands of raw clients.

Scenario coverage no unit test reaches (ROADMAP "Durable control plane"):
up to 5-10k lightweight raw clients — no JAX anywhere in this harness —
hammering heartbeats, locks, fetch_add counters, and deposit/drain cycles
against a SHARDED, WAL-REPLICATED control plane while the harness SIGKILLs
a shard server mid-run, optionally RESTARTS it in place (``--rejoin``:
snapshot catch-up + even liveness generation — then kills and rejoins the
restarted shard's ring PREDECESSOR too, so both sides of the ring cross a
death/restart boundary and a stale replication fence cannot hide), and
(with ``--churn``) rolls clients through incarnation-bumped reattach
cycles. Asserted invariants:

* **health convergence** — after a kill, every client's router converges
  on the same dead-shard set; after a rejoin, back to the full ring;
* **exactly-once counters** — each client's private counter hands out
  contiguous pre-add values. With replication (the default) contiguity
  must hold ACROSS the failover and rejoin boundaries — the successor
  continues the replicated value, so a dedup slip, a double-applied
  failover retry, or a stale rejoin snapshot all surface as a gap;
* **zero lost deposit mass** — with replication, bytes acked == bytes
  drained, period: an acked deposit lives on the successor before the ack
  leaves the primary. ``--no-replication`` restores the r14 allowance of
  one lossy cycle per client per kill;
* **bounded server memory** — surviving servers' VmRSS stays under
  ``--rss-limit-mb`` despite the churn (dedup GC + incarnation GC + WAL
  draining work).

Client counts beyond ~512 fan out over worker PROCESSES (``--procs``,
auto-scaled) so the soak is not GIL-bound; the file descriptor limit is
raised automatically.

Invocations:
    python scripts/cp_soak.py --clients 5000 --churn --rejoin  # the ROADMAP soak
    python scripts/cp_soak.py --quick                          # make soak-smoke
    python scripts/cp_soak.py --quick --rejoin                 # + rejoin churn
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
import types

# Lean bootstrap (no jax): register dummy parent packages so the runtime
# modules import without executing bluefog_tpu/__init__.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "bluefog_tpu")
sys.path.insert(0, _ROOT)
for _name, _path in (("bluefog_tpu", _PKG),
                     ("bluefog_tpu.runtime", os.path.join(_PKG, "runtime"))):
    if _name not in sys.modules:
        _mod = types.ModuleType(_name)
        _mod.__path__ = [_path]
        sys.modules[_name] = _mod

from bluefog_tpu.runtime.native import (  # noqa: E402
    ControlPlaneClient, PeerLostError, load)  # noqa: F401
from bluefog_tpu.runtime.router import ShardRouter  # noqa: E402

SHARD_SERVER = os.path.join(_PKG, "runtime", "shard_server.py")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--clients", type=int, default=128)
    p.add_argument("--duration", type=float, default=30.0,
                   help="seconds of load (the kill lands mid-way)")
    p.add_argument("--churn", action="store_true",
                   help="clients periodically close and reattach with a "
                        "bumped incarnation (elastic-membership churn)")
    p.add_argument("--kill-shard", type=int, default=None,
                   help="shard index to SIGKILL mid-run (default: the "
                        "last shard; negative disables the kill)")
    p.add_argument("--rejoin", action="store_true",
                   help="restart the killed shard in place mid-run "
                        "(snapshot catch-up + even liveness generation) "
                        "and assert the ring converges back")
    p.add_argument("--kill-pairs", action="store_true",
                   help="correlated-failure mode (quorum replication, "
                        "R=3): SIGKILL a shard AND its ring successor "
                        "SIMULTANEOUSLY mid-run — zero deposit loss and "
                        "counter continuity are still required (any R-1 "
                        "deaths lose nothing)")
    p.add_argument("--partition", action="store_true",
                   help="partition mode (R=3): arm the deterministic "
                        "network cut (first half | second half of the "
                        "ring) mid-run; shards below their commit quorum "
                        "reject mutating ops with the typed "
                        "QuorumLostError until the cut heals, workers "
                        "tolerate the rejections, and the mass/counter "
                        "ledgers must still balance exactly")
    p.add_argument("--no-replication", action="store_true",
                   help="r14 mode: no WAL replication (restores the "
                        "documented one-cycle loss allowance)")
    p.add_argument("--procs", type=int, default=0,
                   help="worker processes to fan the clients over "
                        "(0 = auto: one per ~512 clients)")
    p.add_argument("--rss-limit-mb", type=float, default=512.0)
    p.add_argument("--record-bytes", type=int, default=2048,
                   help="max deposit record size")
    p.add_argument("--quick", action="store_true",
                   help="smoke preset (<= 60 s): 64 clients, 2 shards, "
                        "~18 s of load, churn on, one injected kill")
    # internal: worker-process mode (spawned by the parent soak)
    p.add_argument("--worker-slice", default=None, help=argparse.SUPPRESS)
    p.add_argument("--endpoints", default=None, help=argparse.SUPPRESS)
    p.add_argument("--deadline-wall", type=float, default=None,
                   help=argparse.SUPPRESS)
    args = p.parse_args(argv)
    if args.quick:
        args.shards = 2
        args.clients = min(args.clients, 64)
        args.duration = min(args.duration, 18.0)
        args.churn = True
    if args.kill_pairs:
        if args.rejoin:
            p.error("--kill-pairs and --rejoin are separate scenarios")
        args.shards = max(args.shards, 3)  # a pair death needs a survivor
        args.churn = False  # tolerant workers keep one attachment
    if args.partition:
        # 2|2 is the canonical symmetric cut; churn reattaches racing the
        # window would make giveups nondeterministic, so partition mode
        # runs without churn
        args.shards = max(args.shards, 4)
        args.churn = False
    if args.kill_shard is None:
        args.kill_shard = -1 if args.partition else args.shards - 1
    return args


def raise_nofile(need: int) -> None:
    """Best-effort RLIMIT_NOFILE bump: thousands of raw clients cost a
    couple of sockets each."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(hard, max(soft, need))
        if want > soft:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


def spawn_shard(index: int, world: int, replicate: bool, port: int = 0,
                rejoin: bool = False, env: dict = None):
    """One shard server process. With replication the start is two-phase
    (PORT line -> peers over stdin -> READY line); the caller finishes it
    with :func:`finish_shard_spawn` once every shard's port is known.
    ``env`` overrides the inherited environment (partition mode arms the
    cut on the SERVERS only — the workers stay ungrouped clients)."""
    cmd = [sys.executable, SHARD_SERVER, "--port", str(port), "--world",
           str(world), "--shard", str(index)]
    if replicate:
        cmd.append("--expect-peers")
    if rejoin:
        cmd.append("--rejoin")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE if replicate else None,
                            text=True, env=env)
    marker = "BF_SHARD_PORT" if replicate else "BF_SHARD_READY"
    line = proc.stdout.readline()
    if not line.startswith(marker):
        raise RuntimeError(f"shard {index} failed to start: {line!r}")
    return proc, int(line.split()[1])


def finish_shard_spawn(procs_ports, replicate: bool) -> None:
    """Phase 2: write the full ring to every shard and wait for READY."""
    if not replicate:
        return
    ring = ",".join(f"127.0.0.1:{port}" for _, port in procs_ports)
    for proc, _ in procs_ports:
        proc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        proc.stdin.flush()
    for i, (proc, _) in enumerate(procs_ports):
        line = proc.stdout.readline()
        if not line.startswith("BF_SHARD_READY"):
            raise RuntimeError(f"shard {i} failed to wire peers: {line!r}")


def vm_rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


class Worker(threading.Thread):
    """One raw client: heartbeat + counter + lock + deposit/drain loop."""

    def __init__(self, wid: int, endpoints, deadline: float, churn: bool,
                 record_bytes: int, replicated: bool,
                 quorum_tolerant: bool = False) -> None:
        super().__init__(daemon=True, name=f"soak-{wid}")
        self.wid = wid
        self.endpoints = endpoints
        self.deadline = deadline  # wall-clock (time.time) epoch
        self.churn = churn
        self.replicated = replicated
        self.quorum_tolerant = quorum_tolerant
        self.rng = random.Random(1000 + wid)
        self.record_bytes = max(64, record_bytes)
        self.inc = 0
        self.errors: list = []
        # ledgers
        self.ops = 0
        self.acked_bytes = 0
        self.drained_bytes = 0
        self.lost_bytes = 0
        self.lost_cycles = 0
        self.reattaches = 0
        self.reattach_giveups = 0
        self.peer_lost = 0
        self.last_hb = 0
        self.dead_seen: set = set()
        self.counter_eras = 1
        self.counter_acks = 0
        self.quorum_rejects = 0   # typed QuorumLostError rejections seen
        self.outstanding = 0      # deposited-not-yet-drained bytes
        self.expected = None      # tolerant-mode exactly-once cursor
        self._trail: list = []  # last few (op, owner, pre, dead) probes

    def _attach(self) -> ShardRouter:
        # Same contract as control_plane.attach: retry the connect for a
        # bounded window — a reattach can land in the instant AFTER a
        # shard died but BEFORE any survivor published its dead flag, and
        # the strict router correctly refuses until the flag appears.
        # Generous: on an oversubscribed box a single dial can take
        # seconds while thousands of peers redial through the same kill.
        deadline = time.monotonic() + 30.0
        while True:
            try:
                return ShardRouter(self.endpoints, self.wid, streams=1,
                                   incarnation=self.inc)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)

    def ledger(self) -> dict:
        return {
            "wid": self.wid, "ops": self.ops, "errors": self.errors[:4],
            "acked": self.acked_bytes, "drained": self.drained_bytes,
            "lost": self.lost_bytes, "lost_cycles": self.lost_cycles,
            "reattaches": self.reattaches, "peer_lost": self.peer_lost,
            "giveups": self.reattach_giveups,
            "last_hb": self.last_hb, "dead_seen": sorted(self.dead_seen),
            "eras": self.counter_eras, "acks": self.counter_acks,
            "qrejects": self.quorum_rejects,
            "alive": self.is_alive(),
        }

    def _cycle_tolerant(self, r, ckey: str, box: str, hb: str) -> None:
        """One load cycle under a possible partition window: any mutating
        op may come back as the typed QuorumLostError (counted; nothing
        is consumed — the server gate fires BEFORE apply, so a rejected
        fetch_add keeps the exactly-once cursor intact and a rejected
        append leaves no record behind). Deposits go one record at a time
        (a rejected batch could hide a partial apply) and the mass ledger
        runs on an OUTSTANDING model, because a drain may legitimately
        trail its deposits across the cut window."""
        from bluefog_tpu.runtime.native import QuorumLostError

        try:
            r.put(hb, self.last_hb + 1)
            self.last_hb += 1
        except QuorumLostError:
            self.quorum_rejects += 1
        try:
            pre = r.fetch_add(ckey, 1)
            self.counter_acks += 1
            if self.expected is not None and pre != self.expected:
                self.errors.append(
                    f"counter continuity violation across the partition: "
                    f"pre={pre} expected={self.expected} op={self.ops} "
                    f"qrejects={self.quorum_rejects}")
            self.expected = pre + 1
        except QuorumLostError:
            self.quorum_rejects += 1
        for _ in range(self.rng.randint(1, 4)):
            blob = bytes([self.rng.randint(0, 255)]) * \
                self.rng.randint(64, self.record_bytes)
            try:
                if r.append_bytes(box, blob) >= 1:
                    self.acked_bytes += len(blob)
                    self.outstanding += len(blob)
            except QuorumLostError:
                self.quorum_rejects += 1
                break
        try:
            drained = sum(len(x) for x in r.take_bytes(box))
        except QuorumLostError:
            self.quorum_rejects += 1
            return
        self.drained_bytes += drained
        if drained > self.outstanding:
            self.errors.append(
                f"drained {drained} B > outstanding {self.outstanding} B "
                "(duplicated deposit records)")
            self.outstanding = 0
        else:
            self.outstanding -= drained

    def _reconcile_outstanding(self, r, box: str) -> None:
        """Post-deadline settle: the cut has healed (or should have) —
        drain until every acked byte is accounted for; whatever stays
        outstanding is genuinely lost and fails the soak."""
        from bluefog_tpu.runtime.native import QuorumLostError

        deadline = time.monotonic() + 20.0
        while self.outstanding > 0 and time.monotonic() < deadline:
            try:
                drained = sum(len(x) for x in r.take_bytes(box))
            except QuorumLostError:
                time.sleep(0.3)
                continue
            if drained > self.outstanding:
                self.errors.append(
                    f"reconcile drained {drained} B > outstanding "
                    f"{self.outstanding} B (duplicated deposit records)")
                self.drained_bytes += drained
                self.outstanding = 0
                return
            self.drained_bytes += drained
            self.outstanding -= drained
            if drained == 0 and self.outstanding > 0:
                time.sleep(0.2)
        if self.outstanding:
            self.lost_bytes += self.outstanding
            self.lost_cycles += 1
            self.outstanding = 0

    def run(self) -> None:  # noqa: C901 — the soak loop is one scenario
        ckey = f"soak.ctr.{self.wid}"
        box = f"soak.box.{self.wid}"
        hb = f"soak.hb.{self.wid}"
        try:
            r = self._attach()
        except OSError:
            # same oversubscription allowance as a churn reattach: an
            # initial attach racing the kill instant can starve past its
            # window without any invariant being at stake
            self.reattach_giveups = 1
            return
        except Exception as exc:  # noqa: BLE001 — recorded, fails the soak
            self.errors.append(f"attach: {exc!r}")
            return
        if self.quorum_tolerant:
            # partition / pair-kill mode: same load shape, but any
            # mutating op may come back as the typed QuorumLostError
            # while a cut is engaged or the survivor is still
            # classifying its dead replica targets — tolerate, count,
            # and settle the mass ledger after the deadline
            next_poll = time.monotonic() + self.rng.uniform(0.5, 1.5)
            try:
                while time.time() < self.deadline:
                    self.ops += 1
                    self._cycle_tolerant(r, ckey, box, hb)
                    if time.monotonic() >= next_poll:
                        self.dead_seen |= r.poll_shard_health()
                        next_poll = time.monotonic() + \
                            self.rng.uniform(0.5, 1.5)
                self._reconcile_outstanding(r, box)
                self.dead_seen |= r.poll_shard_health()
            except Exception as exc:  # noqa: BLE001 — fails the soak
                self.errors.append(
                    f"tolerant loop died at op {self.ops}: {exc!r}")
            finally:
                try:
                    r.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
            return
        expected = None
        cur_owner = r.owner_of(ckey)
        next_churn = time.monotonic() + self.rng.uniform(4.0, 8.0)
        next_poll = time.monotonic() + self.rng.uniform(0.5, 1.5)
        try:
            while time.time() < self.deadline:
                self.ops += 1
                # heartbeat
                self.last_hb += 1
                r.put(hb, self.last_hb)
                # exactly-once counter. With replication the pre-add
                # values must be contiguous across EVERY boundary —
                # failover, rejoin, churn reattach — because the
                # successor continues the replicated value and the
                # rejoined shard catches up by snapshot. Unreplicated
                # (r14) mode re-learns the era on ownership moves.
                owner = r.owner_of(ckey)
                if owner != cur_owner:
                    cur_owner = owner
                    self.counter_eras += 1
                    if not self.replicated:
                        expected = None
                pre = r.fetch_add(ckey, 1)
                self.counter_acks += 1
                # short diagnostic trail: which store served which value
                # (rendered into the era-violation message — the routing
                # flip history is what makes those failures debuggable)
                self._trail.append((self.ops, cur_owner, pre,
                                    sorted(r.dead_shards())))
                del self._trail[:-8]
                owner2 = r.owner_of(ckey)
                if owner2 != cur_owner:
                    cur_owner = owner2
                    self.counter_eras += 1
                    if not self.replicated:
                        expected = pre + 1
                        continue
                if expected is None:
                    expected = pre + 1
                else:
                    if pre != expected:
                        self.errors.append(
                            f"counter era violation: pre={pre} "
                            f"expected={expected} op={self.ops} "
                            f"owner={cur_owner} t={time.time() % 1000:.2f} "
                            f"trail={self._trail}")
                    expected = pre + 1
                # occasional contended lock (typed degradation tolerated)
                if self.ops % 7 == 0:
                    lk = f"soak.lock.{self.wid % 8}"
                    try:
                        r.lock(lk)
                        r.unlock(lk)
                    except PeerLostError:
                        self.peer_lost += 1
                # deposit/drain cycle with a mass ledger
                nrec = self.rng.randint(1, 4)
                blobs = [bytes([self.rng.randint(0, 255)]) *
                         self.rng.randint(64, self.record_bytes)
                         for _ in range(nrec)]
                replies = r.append_bytes_many([box] * nrec, blobs)
                cycle_acked = sum(
                    len(b) for b, rep in zip(blobs, replies) if rep >= 1)
                self.acked_bytes += cycle_acked
                drained = sum(len(x) for lst in r.take_bytes_many([box])
                              for x in lst)
                self.drained_bytes += drained
                if drained < cycle_acked:
                    self.lost_bytes += cycle_acked - drained
                    self.lost_cycles += 1
                elif drained > cycle_acked:
                    self.errors.append(
                        f"drained {drained} > acked {cycle_acked} "
                        "(duplicated deposit records)")
                now = time.monotonic()
                if now >= next_poll:
                    self.dead_seen |= r.poll_shard_health()
                    next_poll = now + self.rng.uniform(0.5, 1.5)
                if self.churn and now >= next_churn:
                    # elastic churn: the respawn path — close, bump the
                    # incarnation, reattach (servers fence the zombie and
                    # GC its dedup/mailbox state on every shard)
                    r.close()
                    self.inc += 1
                    try:
                        r = self._attach()
                    except OSError:
                        # liveness, not integrity: under extreme
                        # oversubscription a reattach can starve past its
                        # window. The worker retires cleanly (its mass
                        # ledger is complete — churn lands between
                        # cycles); the driver bounds how many may do so.
                        self.reattach_giveups = 1
                        return
                    cur_owner = r.owner_of(ckey)
                    if not self.replicated:
                        expected = None
                    self.reattaches += 1
                    next_churn = now + self.rng.uniform(4.0, 8.0)
            self.dead_seen |= r.poll_shard_health()
        except Exception as exc:  # noqa: BLE001 — recorded, fails the soak
            self.errors.append(f"loop died at op {self.ops}: {exc!r}")
        finally:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown
                pass


def run_workers(args, endpoints, deadline_wall: float,
                replicated: bool) -> list:
    """Run this process's worker slice to completion; returns ledgers."""
    base, count = 0, args.clients
    if args.worker_slice:
        base, count = (int(x) for x in args.worker_slice.split(":"))
    raise_nofile(8 * count + 512)
    tolerant = args.partition or args.kill_pairs
    workers = [Worker(base + i, endpoints, deadline_wall, args.churn,
                      args.record_bytes, replicated,
                      quorum_tolerant=tolerant)
               for i in range(count)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(timeout=args.duration + 120)
    return [w.ledger() for w in workers]


def worker_main(args) -> int:
    """Child-process mode: run a slice, print one JSON ledger line."""
    endpoints = [(h, int(p)) for h, _, p in
                 (e.rpartition(":") for e in args.endpoints.split(","))]
    ledgers = run_workers(args, endpoints, args.deadline_wall,
                          not args.no_replication)
    print("BF_SOAK_LEDGERS " + json.dumps(ledgers), flush=True)
    return 0


def main(argv=None) -> int:  # noqa: C901 — one scenario, one driver
    args = parse_args(argv)
    if load() is None:
        print("cp_soak: native runtime unavailable", file=sys.stderr)
        return 1
    if args.worker_slice:
        return worker_main(args)
    t0 = time.time()
    os.environ.setdefault("BLUEFOG_CP_BACKOFF_MS", "20")
    replicate = not args.no_replication and args.shards > 1
    if args.rejoin and not replicate:
        print("cp_soak: --rejoin requires replication", file=sys.stderr)
        return 1
    procs = args.procs or max(1, min(16, args.clients // 512))
    raise_nofile(8 * args.clients + 1024)

    if args.kill_pairs or args.partition:
        if not replicate:
            print("cp_soak: --kill-pairs/--partition require replication",
                  file=sys.stderr)
            return 1
        # quorum replication: every shard keeps R=3 copies (primary +
        # BOTH ring successors), so a correlated pair death loses
        # nothing and a symmetric cut demotes shards below quorum
        # instead of minting two primaries
        os.environ.setdefault("BLUEFOG_CP_REPLICATION", "3")
    server_env = None
    if args.partition:
        half = args.shards // 2
        spec = ("partition="
                + ",".join(str(i) for i in range(half)) + "|"
                + ",".join(str(i) for i in range(half, args.shards))
                + f",part_after={0.35 * args.duration:.1f}"
                + f",heal_after={0.3 * args.duration:.1f}")
        # servers only: the workers stay ungrouped clients and can reach
        # both sides of the cut — what they see is the typed rejection
        server_env = dict(os.environ, BLUEFOG_CP_FAULT=spec)
        print(f"cp_soak: partition injector armed on servers: {spec}")

    servers = [spawn_shard(i, 1, replicate, env=server_env)
               for i in range(args.shards)]
    finish_shard_spawn(servers, replicate)
    endpoints = [("127.0.0.1", port) for _, port in servers]
    print(f"cp_soak: {args.shards} shard(s) up "
          f"({','.join(str(p) for _, p in servers)}); "
          f"{args.clients} client(s) over {procs} proc(es), "
          f"{args.duration:.0f}s"
          + (", churn" if args.churn else "")
          + ((", quorum replication R="
              + os.environ["BLUEFOG_CP_REPLICATION"])
             if (args.kill_pairs or args.partition)
             else (", WAL replication" if replicate else ", NO replication"))
          + (f", SIGKILL pair {args.kill_shard}+"
             f"{(args.kill_shard + 1) % args.shards} mid-run"
             if args.kill_pairs else
             (f", SIGKILL shard {args.kill_shard} mid-run"
              if args.kill_shard >= 0 else ""))
          + (", rejoin mid-run" if args.rejoin else ""))

    deadline_wall = time.time() + args.duration
    eps_spec = ",".join(f"{h}:{p}" for h, p in endpoints)

    children: list = []
    workers: list = []
    if procs > 1:
        per = (args.clients + procs - 1) // procs
        for k in range(procs):
            base = k * per
            count = min(per, args.clients - base)
            if count <= 0:
                break
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--worker-slice", f"{base}:{count}",
                   "--endpoints", eps_spec,
                   "--deadline-wall", str(deadline_wall),
                   "--duration", str(args.duration),
                   "--record-bytes", str(args.record_bytes)]
            if args.churn:
                cmd.append("--churn")
            if args.no_replication:
                cmd.append("--no-replication")
            if args.partition:
                cmd.append("--partition")
            if args.kill_pairs:
                cmd.append("--kill-pairs")
            children.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                             text=True))
    else:
        worker_thread = threading.Thread(
            target=lambda: workers.extend(
                run_workers(args, endpoints, deadline_wall, replicate)))
        worker_thread.start()

    # --- shard kill / rejoin schedule (parent drives it) -------------------
    killed = None
    killed_set: set = set()
    rejoined = False

    def rejoin_shard(idx: int, at_frac: float) -> bool:
        time.sleep(max(0.0, deadline_wall - time.time()
                       - (1.0 - at_frac) * args.duration))
        proc, port = spawn_shard(idx, 1, True, port=servers[idx][1],
                                 rejoin=True)
        # phase 2 for the single restarted shard: full ring over stdin
        ring = ",".join(f"127.0.0.1:{p}" for _, p in
                        [sp if i != idx else (proc, port)
                         for i, sp in enumerate(servers)])
        proc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        proc.stdin.flush()
        line = proc.stdout.readline()
        if not line.startswith("BF_SHARD_READY"):
            print(f"cp_soak: rejoin failed: {line!r}", file=sys.stderr)
            return False
        servers[idx] = (proc, port)
        print(f"cp_soak: shard {idx} REJOINED at "
              f"t+{at_frac * args.duration:.0f}s")
        return True

    if 0 <= args.kill_shard < args.shards:
        time.sleep(max(0.0, deadline_wall - time.time()
                       - 0.65 * args.duration))
        if args.kill_pairs:
            # correlated failure: a shard AND its ring successor die in
            # the same instant, mailboxes undrained — with R=3 the
            # second successor still holds every acked byte
            mate = (args.kill_shard + 1) % args.shards
            for idx in (args.kill_shard, mate):
                servers[idx][0].send_signal(signal.SIGKILL)
            for idx in (args.kill_shard, mate):
                servers[idx][0].wait()
            killed = args.kill_shard
            killed_set = {args.kill_shard, mate}
            print(f"cp_soak: SIGKILLed shard pair {sorted(killed_set)} "
                  f"SIMULTANEOUSLY at t+{0.35 * args.duration:.0f}s")
        else:
            victim, _ = servers[args.kill_shard]
            victim.send_signal(signal.SIGKILL)
            victim.wait()
            killed = args.kill_shard
            killed_set = {killed}
            print(f"cp_soak: SIGKILLed shard {killed} at "
                  f"t+{0.35 * args.duration:.0f}s")
        if args.rejoin:
            if not rejoin_shard(killed, 0.6):
                return 1
            rejoined = True
            # Round 2: churn the OTHER side of the ring — kill and rejoin
            # the restarted shard's ring predecessor, the shard whose
            # post-rejoin WAL stream must land above the fence the first
            # rejoiner adopted (a stale-fence regression silently drops
            # those acked records and surfaces here as lost deposit mass
            # and counter-era gaps).
            if args.shards >= 2:
                second = (killed - 1) % args.shards
                time.sleep(max(0.0, deadline_wall - time.time()
                               - 0.28 * args.duration))
                victim2, _ = servers[second]
                victim2.send_signal(signal.SIGKILL)
                victim2.wait()
                print(f"cp_soak: SIGKILLed shard {second} (round 2) at "
                      f"t+{0.72 * args.duration:.0f}s")
                if not rejoin_shard(second, 0.85):
                    return 1

    # --- collect ledgers ---------------------------------------------------
    ledgers: list = []
    if procs > 1:
        for ch in children:
            out, _ = ch.communicate(timeout=args.duration + 180)
            for line in out.splitlines():
                if line.startswith("BF_SOAK_LEDGERS "):
                    ledgers.extend(json.loads(line.split(None, 1)[1]))
    else:
        worker_thread.join(timeout=args.duration + 180)
        ledgers = workers

    failures: list = []
    stuck = [w["wid"] for w in ledgers if w["alive"]]
    if len(ledgers) != args.clients:
        failures.append(f"{args.clients - len(ledgers)} client ledger(s) "
                        "missing (worker process died?)")
    if stuck:
        failures.append(f"{len(stuck)} client(s) never finished: "
                        f"{stuck[:10]}")
    lossy_allowance = 0 if replicate else (1 if killed_set else 0)
    for w in ledgers:
        for e in w["errors"]:
            failures.append(f"client {w['wid']}: {e}")
        if w["lost_cycles"] > lossy_allowance:
            failures.append(
                f"client {w['wid']}: {w['lost_cycles']} lossy deposit "
                f"cycle(s), {w['lost']} B lost"
                + (" — replication promises ZERO" if replicate else
                   " (only the kill window may lose one)"))
        if w["acked"] != w["drained"] + w["lost"]:
            failures.append(
                f"client {w['wid']}: mass leak — acked {w['acked']} != "
                f"drained {w['drained']} + lost {w['lost']}")
        if killed_set and not rejoined and not w["alive"] and \
                not w["giveups"] and \
                not killed_set <= set(w["dead_seen"]):
            failures.append(
                f"client {w['wid']}: never converged on dead shard(s) "
                f"{sorted(killed_set)} (saw {w['dead_seen']})")
    giveups = sum(w.get("giveups", 0) for w in ledgers)
    if giveups > max(1, args.clients // 200):
        failures.append(
            f"{giveups} churn reattach giveups exceed the 0.5% "
            "oversubscription allowance (attach liveness regressed)")

    # fresh probe: health view converges from the outside too, and every
    # client's final heartbeat reads back through failover routing
    probe = ShardRouter(endpoints, 10 ** 6, streams=1, lenient=True)
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        dead = probe.poll_shard_health()
        want = set() if (not killed_set or rejoined) else killed_set
        if dead == want:
            break
        time.sleep(0.3)
    if killed_set and not rejoined and \
            not killed_set <= probe.dead_shards():
        failures.append(
            f"probe router did not converge on dead shard(s) "
            f"{sorted(killed_set)} (saw {sorted(probe.dead_shards())})")
    if rejoined and probe.dead_shards():
        failures.append(
            f"ring did not converge back after rejoin (probe still sees "
            f"{sorted(probe.dead_shards())} dead)")
    finished = [w for w in ledgers if not w["alive"] and not w["errors"]]
    hb_vals = probe.get_many([f"soak.hb.{w['wid']}" for w in finished])
    hb_bad = sum(1 for w, v in zip(finished, hb_vals) if v != w["last_hb"])
    if hb_bad:
        failures.append(f"{hb_bad} final heartbeat(s) unreadable through "
                        "failover routing")
    repl_views = []
    if replicate:
        for name, st in probe.server_stats_all():
            if st:
                repl_views.append(
                    f"{name} repl={st['repl_status']} "
                    f"lag={st['wal_enqueued'] - st['wal_acked']} "
                    f"dropped={st['wal_dropped']}")
    qrejects = sum(w.get("qrejects", 0) for w in ledgers)
    if args.partition:
        srv_rejects = 0
        below_quorum = []
        for name, st in probe.server_stats_all():
            if st:
                srv_rejects += int(st.get("partition_rejects", 0))
                if st.get("quorum_state") == 2:
                    below_quorum.append(name)
        if not qrejects and not srv_rejects:
            failures.append(
                "partition mode: the cut never engaged — no typed "
                "QuorumLostError anywhere (injector misarmed?)")
        if below_quorum:
            failures.append(
                "partition did not heal: shard(s) still below commit "
                f"quorum: {below_quorum}")
    probe.close()

    rss = {i: vm_rss_mb(proc.pid) for i, (proc, _) in enumerate(servers)
           if proc.poll() is None}
    for i, mb in rss.items():
        if mb > args.rss_limit_mb:
            failures.append(f"shard {i} RSS {mb:.0f} MB exceeds the "
                            f"{args.rss_limit_mb:.0f} MB bound")

    total_ops = sum(w["ops"] for w in ledgers)
    total_acked = sum(w["acked"] for w in ledgers)
    total_lost = sum(w["lost"] for w in ledgers)
    lossy = sum(w["lost_cycles"] for w in ledgers)
    print(f"cp_soak: {total_ops} cycles, "
          f"{sum(w['acks'] for w in ledgers)} counter acks "
          f"({sum(w['eras'] for w in ledgers)} eras), "
          f"{total_acked / 1e6:.1f} MB deposited, "
          f"{total_lost} B lost in {lossy} cycle(s), "
          f"{sum(w['reattaches'] for w in ledgers)} churn reattaches "
          f"({giveups} giveups), "
          f"{sum(w['peer_lost'] for w in ledgers)} typed PeerLost, "
          f"{qrejects} typed QuorumLost, "
          f"survivor RSS {max(rss.values()):.0f} MB, "
          f"wall {time.time() - t0:.1f}s")
    if repl_views:
        print("cp_soak: replication: " + "; ".join(repl_views))

    for i, (proc, _) in enumerate(servers):
        if proc.poll() is None:
            proc.terminate()
    for proc, _ in servers:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    if failures:
        print("cp_soak: FAIL", file=sys.stderr)
        for f in failures[:40]:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("cp_soak: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
