"""Measure whether ResNet-50's weight-gradient convs sit at the HBM roof.

VERDICT r4 #4: PERF.md's roofline argued the weight-gradient conv fusions
(`convert_reduce_fusion`, 21.9 ms/step, the largest trace bucket) are
HBM-bound, but no bytes/s was ever measured. This probe jits each hot
weight-gradient conv shape standalone (the same ``conv_general_dilated``
XLA emits for dW), times it on the real chip, and reports:

  * achieved HBM GB/s  = (activation reads + grad reads + dW writes) / t
  * achieved TFLOP/s   = 2 * B*Ho*Wo*k*k*Cin*Cout / t

against the v5e roofs (~819 GB/s HBM, 197 TFLOP/s bf16). A shape whose
bytes/s approaches the HBM roof while its TFLOP/s sits far below the MXU
roof is measured — not argued — to be bandwidth-bound.

Shapes: the B=128 ResNet-50 stage shapes that dominate the r4 trace
(3x3 convs of stages 2-4 and the stride-2 downsamples).

Run on the real chip:  python scripts/convgrad_probe.py
"""

import json
import os
import sys
import tempfile

import numpy as np

import jax
import jax.numpy as jnp


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resnet_profile import device_op_seconds  # noqa: E402

V5E_HBM = 819e9     # bytes/s
V5E_BF16 = 197e12   # FLOP/s

# (name, B, H, W, Cin, Cout, k, stride) — ResNet-50 hot dW shapes at B=128
SHAPES = [
    ("stage1_3x3", 128, 56, 56, 64, 64, 3, 1),
    ("stage2_3x3", 128, 28, 28, 128, 128, 3, 1),
    ("stage3_3x3", 128, 14, 14, 256, 256, 3, 1),
    ("stage4_3x3", 128, 7, 7, 512, 512, 3, 1),
    ("stage3_1x1_expand", 128, 14, 14, 256, 1024, 1, 1),
    ("stage4_1x1_expand", 128, 7, 7, 512, 2048, 1, 1),
]


def weight_grad(x, dy, k, stride):
    """dW of a NHWC conv via conv_general_dilated, as XLA's autodiff emits:
    contract batch+space of x against dy."""
    pad = (k - 1) // 2

    def fwd(w):
        return jax.lax.conv_general_dilated(
            x, w, (stride, stride), [(pad, pad), (pad, pad)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    w0 = jnp.zeros((k, k, x.shape[-1], dy.shape[-1]), x.dtype)
    _, vjp = jax.vjp(fwd, w0)
    (dw,) = vjp(dy)
    # real training accumulates dW in f32 (the trace's convert_reduce
    # fusions); include the convert so the probe matches the step's bucket
    return dw.astype(jnp.float32)


def main() -> int:
    dev = jax.devices()[0]
    print(f"# device: {dev.device_kind}", file=sys.stderr)
    for name, B, H, W, Cin, Cout, k, stride in SHAPES:
        Ho, Wo = H // stride, W // stride
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(B, H, W, Cin), jnp.bfloat16)
        dy = jnp.asarray(rng.randn(B, Ho, Wo, Cout), jnp.bfloat16)
        fn = jax.jit(lambda x, dy: weight_grad(x, dy, k, stride))
        out = fn(x, dy)
        float(out[0, 0, 0, 0])  # compile + sync (host transfer: the remote
        # tunnel can return early from block_until_ready)
        # Wall-clocking reps over the remote tunnel measures dispatch RTT
        # (~5-7 ms), not the 0.1-2 ms kernel: read DEVICE time from a
        # profiler trace instead, like scripts/resnet_profile.py.
        reps = 20
        with tempfile.TemporaryDirectory() as td:
            with jax.profiler.trace(td):
                for _ in range(reps):
                    out = fn(x, dy)
                float(out[0, 0, 0, 0])
            dt = device_op_seconds(td) / reps
        read_bytes = (x.size + dy.size) * 2            # bf16 operands
        write_bytes = k * k * Cin * Cout * 4           # f32 dW
        gbs = (read_bytes + write_bytes) / dt / 1e9
        flops = 2.0 * B * Ho * Wo * k * k * Cin * Cout
        tfs = flops / dt / 1e12
        print(json.dumps({
            "shape": name, "ms": round(dt * 1e3, 3),
            "GBps": round(gbs, 1), "hbm_frac": round(gbs / (V5E_HBM / 1e9), 3),
            "TFLOPs": round(tfs, 1),
            "mxu_frac": round(tfs / (V5E_BF16 / 1e12), 3),
            "intensity_flop_per_byte": round(
                flops / (read_bytes + write_bytes), 1),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
