"""Per-op dispatch/execution microbenchmark.

Analog of the reference's scripts/single_ops_test.py: time each op family
on the current mesh so dispatch-path regressions (e.g. a collective
accidentally re-tracing per call) are visible in isolation. Run on the
default devices, or an 8-device CPU mesh via
``bfrun --simulate 8 -- python scripts/op_microbench.py``.
"""

from __future__ import annotations

import argparse
import os
import tempfile
import time

import numpy as np

import jax


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

import bluefog_tpu as bf


def timeit(fn, iters):
    fn()  # warmup / compile
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn()
    if out is not None:
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--size", type=int, default=1 << 16,
                   help="elements per rank")
    p.add_argument("--iters", type=int, default=50)
    args = p.parse_args()

    from bluefog_tpu.runtime.config import example_devices
    bf.init(devices=example_devices())
    n = bf.size()
    print(f"mesh: {n} rank(s) on {bf.mesh().devices.flat[0].platform}, "
          f"{args.size} f32/rank, {args.iters} iters")

    x = bf.shard_rank_stacked(
        bf.mesh(), np.ones((n, args.size), np.float32))
    bf.win_create(x, name="mb.win", zero_init=True)
    peers = {r: r ^ 1 for r in range(n)} if n % 2 == 0 else None

    ops = [
        ("allreduce", lambda: bf.synchronize(bf.allreduce_nonblocking(x))),
        ("broadcast", lambda: bf.broadcast(x, 0)),
        ("allgather", lambda: bf.allgather(x)),
        ("neighbor_allreduce", lambda: bf.neighbor_allreduce(x)),
        ("neighbor_allgather", lambda: bf.neighbor_allgather(x)),
        ("barrier", lambda: bf.barrier()),
        ("win_put", lambda: bf.win_put(x, "mb.win")),
        ("win_accumulate", lambda: bf.win_accumulate(x, "mb.win")),
        ("win_update", lambda: bf.win_update(name="mb.win")),
    ]
    if peers:
        ops.append(("pair_gossip", lambda: bf.pair_gossip(x, peers)))

    for name, fn in ops:
        dt = timeit(fn, args.iters)
        print(f"{name:22s} {dt * 1e3:8.3f} ms/call")

    # Dynamic one-peer schedule, per-position host cost across cycles.
    # Cycle 1 builds (and caches) each step's CombinePlan; later cycles
    # must be flat and cheap — the per-step O(n^2) W rebuild the r3 review
    # flagged is gone (plan cache keyed on the step's edge set + weights).
    # A 1-rank mesh has no one-peer schedule to cycle.
    if n >= 2:
        topo = bf.load_topology()
        gens = [bf.topology_util.GetDynamicSendRecvRanks(topo, r)
                for r in range(n)]

        def dyn_step():
            sends, recv_from = {}, {r: [] for r in range(n)}
            for r, g in enumerate(gens):
                to, _ = next(g)
                sends[r] = to
            for s, dsts in sends.items():
                for d in dsts:
                    recv_from[d].append(s)
            sw = {r: 1.0 / (len(recv_from[r]) + 1) for r in range(n)}
            nw = {r: {s: sw[r] for s in recv_from[r]} for r in range(n)}
            return bf.neighbor_allreduce(
                x, self_weight=sw, neighbor_weights=nw,
                send_neighbors=sends)

        cycle = max(int(np.log2(n)), 1)
        for label in ("cold", "warm", "warm"):
            t0 = time.perf_counter()
            for _ in range(cycle):
                out = dyn_step()
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / cycle
            print(f"neighbor_allreduce_dyn {dt * 1e3:8.3f} ms/step ({label} "
                  f"cycle of {cycle})")

    bf.win_free("mb.win")
    bf.shutdown()


if __name__ == "__main__":
    main()
