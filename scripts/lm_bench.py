"""Transformer-LM headline benchmark: tokens/s and MFU on the real chip.

The reference's benchmark methodology (examples/pytorch_benchmark.py:
synthetic data, warmup, timed window, throughput printout) applied to the
long-context LM path this framework adds on top of reference parity:
flash-attention forward + flash-attention-2 backward kernels, bf16 compute,
one jitted train step. Reports ms/step, tokens/s, and model FLOPs
utilization against the v5e bf16 peak.

FLOPs accounting (PaLM-style model FLOPs, causal):
  matmul params: 6 * N_matmul * tokens   (fwd + bwd)
  attention:     12 * L * B * S^2 * d_model * 0.5

Run: python scripts/lm_bench.py [--seq-len 8192] [--d-model 2048] ...
Prints one JSON line per config, and appends nothing — PERF.md records the
numbers.
"""

from __future__ import annotations

import argparse
import json
import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
import optax

import sys, os
import tempfile
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

from bluefog_tpu.models import TransformerLM  # noqa: E402
from bluefog_tpu.parallel.flash import flash_attention  # noqa: E402

V5E_BF16_PEAK = 197e12  # TPU v5e per-chip bf16 peak FLOP/s


def matmul_param_count(params) -> int:
    """Parameters that induce matmul FLOPs: every >=2-D kernel EXCEPT the
    embedding table (a gather, not a matmul; the lm_head projection is a
    separate kernel and is counted)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    return sum(
        int(np.prod(p.shape)) for path, p in flat
        if hasattr(p, "shape") and len(p.shape) >= 2
        and "embed" not in jax.tree_util.keystr(path).lower())


def run(seq_len: int, d_model: int, num_layers: int, num_heads: int,
        batch: int, vocab: int, steps: int, warmup: int, remat: bool,
        chunked_ce: bool = False, ce_chunk: int = 1024):
    model = TransformerLM(
        vocab_size=vocab, num_layers=num_layers, num_heads=num_heads,
        d_model=d_model, d_ff=4 * d_model, dtype=jnp.bfloat16,
        attn_fn=partial(flash_attention, causal=True))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq_len),
                                0, vocab)
    targets = jnp.roll(tokens, -1, axis=1)
    params = jax.jit(lambda k: model.init(k, tokens)["params"])(
        jax.random.PRNGKey(0))
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def loss_fn(p, batch_):
        toks, tgts = batch_
        if chunked_ce:
            # exact CE without materializing the [S, V] logits (1 GB at
            # the headline config) — see parallel.chunked_ce_loss
            from bluefog_tpu.parallel import chunked_ce_loss
            return chunked_ce_loss(model, p, toks, tgts, chunk=ce_chunk,
                                   remat_backbone=remat)
        apply = model.apply
        if remat:
            apply = jax.checkpoint(model.apply)
        logits = apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    @jax.jit
    def step(p, s, batch_):
        l, g = jax.value_and_grad(loss_fn)(p, batch_)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, l

    if steps < 1:
        raise ValueError("--steps must be >= 1")
    batch_ = (tokens, targets)
    for _ in range(warmup):
        params, opt_state, l = step(params, opt_state, batch_)
    if warmup:
        float(np.asarray(l))  # close the warmup window

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, l = step(params, opt_state, batch_)
    float(np.asarray(l))  # ONE closing host sync (reference methodology)
    dt = (time.perf_counter() - t0) / steps

    n_mat = matmul_param_count(params)
    tokens_per_step = batch * seq_len
    flops = (6 * n_mat * tokens_per_step
             + 12 * num_layers * batch * seq_len ** 2 * d_model * 0.5)
    result = {
        "metric": "lm_tokens_per_s",
        "seq_len": seq_len, "d_model": d_model, "layers": num_layers,
        "batch": batch, "params_m": round(n_mat / 1e6, 1),
        "ms_per_step": round(dt * 1e3, 2),
        "value": round(tokens_per_step / dt),
        "unit": "tokens/s",
        "mfu": round(flops / dt / V5E_BF16_PEAK, 3),
        "final_loss": round(float(np.asarray(l)), 3),
    }
    print(json.dumps(result), flush=True)
    return result


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=8192)
    p.add_argument("--d-model", type=int, default=2048)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=16)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--vocab", type=int, default=32768)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--remat", action="store_true",
                   help="checkpoint the whole forward (longer S fits)")
    p.add_argument("--chunked-ce", action="store_true",
                   help="chunked vocab projection + CE (no [S, V] logits)")
    p.add_argument("--ce-chunk", type=int, default=1024)
    a = p.parse_args()
    run(a.seq_len, a.d_model, a.num_layers, a.num_heads, a.batch, a.vocab,
        a.steps, a.warmup, a.remat, a.chunked_ce, a.ce_chunk)


if __name__ == "__main__":
    main()
