"""Frontend stacking-overhead probe (VERDICT r5 weak #4).

Both frontends move parameters through host numpy on every communicate
step: torch re-stacks every parameter (`torch/__init__.py:_stacked_params`
-> `torch.stack` -> `to_jax`) and keras does the same per variable
(`keras/__init__.py:_stacked`), then both tear the mixed result back down
into the per-rank replicas. This probe measures what that costs for an
MLP-sized model (the opt-matrix bench model, ~7.4 MB of f32 params) on
the 8-device CPU mesh, split into the three phases of one communicate:

  stack      host gather: per-rank replicas -> rank-stacked host arrays
  comm       the compiled neighbor_allreduce over the stacked arrays
  write_back scatter the mixed values back onto the replicas

One JSON line per frontend goes to stdout; PERF.md records the row.

Usage:  python scripts/frontend_overhead_probe.py [--rounds N]
"""

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# 8 virtual CPU devices, configured before jax imports (conftest idiom)
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=8"])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np  # noqa: E402

import jax  # noqa: E402


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

import bluefog_tpu as bf  # noqa: E402

N = 8
LAYERS = [(3072, 512), (512, 512), (512, 10)]  # the bench MLP's shape


def _med(ts):
    return round(float(np.median(ts)) * 1e3, 3)


def probe_torch(rounds: int) -> dict:
    import torch

    import bluefog_tpu.torch as bft

    class MLP(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.layers = torch.nn.ModuleList(
                [torch.nn.Linear(i, o) for i, o in LAYERS])

    modules = [MLP() for _ in range(N)]
    param_bytes = sum(p.numel() * p.element_size()
                      for p in modules[0].parameters())
    t_stack, t_comm, t_wb = [], [], []
    for _ in range(rounds):
        t0 = time.perf_counter()
        stacked = bft._stacked_params(modules)
        t1 = time.perf_counter()
        mixed = {nm: bft.neighbor_allreduce(t) for nm, t in stacked.items()}
        jax.block_until_ready(None)  # results already torch; no-op guard
        t2 = time.perf_counter()
        bft._write_back(modules, mixed)
        t3 = time.perf_counter()
        t_stack.append(t1 - t0)
        t_comm.append(t2 - t1)
        t_wb.append(t3 - t2)
    # device-resident mode (ISSUE r13): parameters live in jax-owned
    # buffers behind dlpack views — the whole communicate is one call, so
    # the comparable number is the full-communicate wall time
    dmods = [MLP() for _ in range(N)]
    dplan = bft._comm_plan(dmods)
    t_dev = []
    if bft._install_device_rows(dplan):
        bft._device_communicate(dplan)  # warmup (jit)
        for _ in range(rounds):
            t0 = time.perf_counter()
            bft._device_communicate(dplan)
            t1 = time.perf_counter()
            t_dev.append(t1 - t0)
    return {
        "frontend": "torch", "params_mb": round(param_bytes / 1e6, 2),
        "stack_ms": _med(t_stack), "comm_ms": _med(t_comm),
        "write_back_ms": _med(t_wb),
        "host_overhead_ms": _med([a + b for a, b in zip(t_stack, t_wb)]),
        "device_resident_comm_ms": _med(t_dev) if t_dev else None,
        "legacy_total_ms": _med([a + b + c for a, b, c in
                                 zip(t_stack, t_comm, t_wb)]),
    }


def probe_keras(rounds: int) -> dict:
    import keras

    import bluefog_tpu.keras as bfk

    def make():
        m = keras.Sequential(
            [keras.layers.Input((LAYERS[0][0],))] +
            [keras.layers.Dense(o) for _, o in LAYERS])
        m.build((None, LAYERS[0][0]))
        return m

    models = [make() for _ in range(N)]
    param_bytes = sum(
        int(np.prod(v.shape)) * 4
        for v in models[0].trainable_variables)
    from bluefog_tpu.utils.local_view import to_global, to_local
    t_stack, t_comm, t_wb = [], [], []
    for _ in range(rounds):
        t0 = time.perf_counter()
        stacked = bfk._stacked(models)
        t1 = time.perf_counter()
        mixed = [to_local(bf.neighbor_allreduce(to_global(t)))
                 for t in stacked]
        t2 = time.perf_counter()
        bfk._write_back(models, mixed)
        t3 = time.perf_counter()
        t_stack.append(t1 - t0)
        t_comm.append(t2 - t1)
        t_wb.append(t3 - t2)
    return {
        "frontend": "keras", "params_mb": round(param_bytes / 1e6, 2),
        "stack_ms": _med(t_stack), "comm_ms": _med(t_comm),
        "write_back_ms": _med(t_wb),
        "host_overhead_ms": _med([a + b for a, b in zip(t_stack, t_wb)]),
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--frontends", nargs="*",
                    default=["torch", "keras"])
    args = ap.parse_args()
    bf.init(devices=jax.devices("cpu")[:N])
    try:
        for fe in args.frontends:
            res = (probe_torch if fe == "torch" else probe_keras)(args.rounds)
            res["where"] = "cpu-mesh-8dev"
            print(json.dumps(res), flush=True)
    finally:
        bf.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
