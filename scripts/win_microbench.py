"""Hosted-window data-plane microbenchmark (VERDICT r4 #1).

Launches 4 controller processes (1 simulated CPU device each) through the
real ``bfrun`` fan-out with control-plane authentication ON, runs
scripts/_win_microbench_child.py in each, and relays controller 0's JSON
result lines. Measures per-op latency and MB/s for win_put /
win_accumulate / win_update / win_get on ResNet-sized (102 MB), small
(1 MB), and bf16 windows, plus the raw put_bytes/get_bytes transport
ceiling the numbers should be judged against — measured BOTH at the full
striped connection pool (``raw_put_bytes``/``raw_get_bytes``, the default
client) and pinned to one stream (``raw_put_bytes_1s``/``raw_get_bytes_1s``),
so the striping win and either regime's regressions are visible in the
same run. Every timed series is preceded by explicit warmup rounds
(excluded from the medians): the first ops of a kind pay allocator +
page-cache + pool-connect costs that otherwise masquerade as transport
time (r6's win_put run-to-run swing).

Also prints a fold-vs-stream isolation line per config: the same drained
bytes timed as (a) the socket take alone and (b) the numpy fold alone, so
the drain pipeline's overlap headroom is a measured number, not a guess.

Usage:  python scripts/win_microbench.py [--quick] [--codec LIST]
                                         [--sharded LIST]
  --quick: tiny windows, 2 rounds, 1 warmup — seconds instead of minutes;
           exercised by the CI smoke test (tests/test_benchmark_smoke.py),
           numbers are NOT meaningful for PERF.md.
  --codec: comma-separated wire codecs (e.g. ``int8,fp8,topk:0.01``) to
           additionally sweep on the headline config's win_put/win_update
           series (docs/compression.md). ``mbps`` in codec rows is the
           EFFECTIVE rate — app-level payload bytes over wall time — so
           the compressed-vs-raw comparison reads off directly (the int8
           ``>= 2x win_update`` acceptance bar, PERF.md r15).
  --sharded: comma-separated shard factors (e.g. ``2,4``): replays
           win_put on shard-row-sized windows and counter-delta-verifies
           (``win.deposit_bytes``) that per-op wire bytes drop by
           ``>= 0.9*S`` — the sharded-window acceptance bar
           (docs/sharded_windows.md); the child ASSERTS it.
"""

import argparse
import os
import secrets
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Flight dumps from a bench run (deliberate fault probes included) land in
# a tempdir instead of littering the CWD, the same default the test
# suite's conftest applies; an explicit BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--codec", type=str, default=None,
                    help="comma-separated wire codecs to sweep "
                         "(int8,fp8,topk:<frac>) on the headline config")
    ap.add_argument("--sharded", type=str, default=None,
                    help="comma-separated shard factors (e.g. 2,4) to "
                         "sweep: shard-row windows replay win_put and the "
                         "per-op wire bytes are counter-delta verified to "
                         "drop ≥ 0.9*S (docs/sharded_windows.md)")
    args = ap.parse_args()
    env = os.environ.copy()
    if args.quick:
        env["BLUEFOG_WB_QUICK"] = "1"
    if args.codec:
        env["BLUEFOG_WB_CODECS"] = args.codec
    if args.sharded:
        env["BLUEFOG_WB_SHARD"] = args.sharded
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT", "BLUEFOG_WIN_CODEC"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # host-plane bench on a simulated mesh: skip the TPU-plugin probe (a
    # multi-minute per-controller timeout when the accelerator tunnel is
    # down)
    env["JAX_PLATFORMS"] = "cpu"
    env["BLUEFOG_CP_SECRET"] = secrets.token_hex(16)  # auth ON (VERDICT r4)
    port = free_port()
    child = str(REPO / "scripts" / "_win_microbench_child.py")

    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "bluefog_tpu.launcher", "-np", "4",
             "--coordinator", f"127.0.0.1:{port}", "--process-id", str(i),
             "--simulate", "1", "--", sys.executable, child],
            env=env,
            stdout=None if i == 0 else subprocess.DEVNULL,
            stderr=subprocess.STDOUT if i == 0 else subprocess.DEVNULL)
        for i in range(4)
    ]
    rc = 0
    for p in procs:
        p.wait(timeout=1800)
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(main())
