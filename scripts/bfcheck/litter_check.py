"""Runtime-debris guard: flight dumps must never land in the repo root.

A flight-recorder postmortem dump (``bf_flight_<rank>.json``) defaults to
the process cwd when ``BLUEFOG_FLIGHT_DIR`` is unset, so any crashing or
deliberately-dumping process launched from the repository root litters the
tree — and the litter then gets committed and shipped. The test suite's
conftest redirects its dumps to a throwaway temp dir; this analyzer
backstops every OTHER entry point (benches, smokes, ad-hoc runs) by
failing ``make check`` while a dump sits at the root, the same way a
stray ``core`` file would be flagged in a C tree.

Only the repository root is scanned: dumps under a temp dir, an
explicitly configured ``BLUEFOG_FLIGHT_DIR``, or a test fixture tree are
exactly where dumps belong.
"""

from __future__ import annotations

import fnmatch
import os
from typing import List

from . import Diagnostic

# Patterns of per-process runtime dump files (see runtime/flight.py's
# dump(): bf_flight_<rank>.json; bfrun --dump merges to bf_flight_all.json)
LITTER_PATTERNS = ("bf_flight_*.json",)


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return out
    for fn in entries:
        if not os.path.isfile(os.path.join(root, fn)):
            continue
        if any(fnmatch.fnmatch(fn, pat) for pat in LITTER_PATTERNS):
            out.append(Diagnostic(
                "litter", fn, 1,
                "flight-recorder dump littering the repository root — "
                "delete it (dumps belong under BLUEFOG_FLIGHT_DIR; a "
                "process launched from the repo root with the default "
                "config wrote it here)"))
    return out
