"""bfcheck — project-invariant static analysis for the bluefog_tpu tree.

Four analyzers over the repository (run all via ``python scripts/bfcheck``,
``make check``, or tier-1 through ``tests/test_bfcheck.py``):

``protocol``
    Wire-protocol consistency: the C++ ``enum Op`` + ``IsDedupOp`` retry
    set in ``csrc/bf_runtime.cc`` must be a bijection with the Python op
    table in ``bluefog_tpu/runtime/protocol.py`` — a new op cannot ship
    with a missing mirror or a silently retry-unsafe classification.

``knobs``
    Env-knob registry: every ``BLUEFOG_*`` read in the tree must be
    declared in ``runtime/config.py``'s ``KNOBS`` table, per-site literal
    defaults must agree with the registry, and every declared knob must be
    documented in ``docs/env_variables.md`` (whose knob table is generated
    from the registry — ``python scripts/bfcheck --write-docs``).

``locks``
    Lock & thread discipline over the Python runtime: lock-order
    inversions across the known thread entry points, blocking
    control-plane calls made while holding a local mutex, and daemon
    threads without stop/join wiring.

``metrics``
    Telemetry vocabulary: every registry instrument created in the
    package must use a declared prefix family and resolve to HELP text,
    and every live time-series binding / alert rule must reference a
    declared instrument or derived series (docs/observability.md).

``lint``
    Minimal pyflakes-style fallback (unused imports, duplicate
    definitions) used by ``make lint`` when ``ruff`` is not installed.

``litter``
    Runtime-debris guard: flight-recorder dumps (``bf_flight_*.json``)
    sitting in the repository root are flagged — dumps belong under
    ``BLUEFOG_FLIGHT_DIR``, never committed at the root.

A finding can be waived at its line with ``# bfcheck: ok-<check-id>`` plus
a justification; waivers are themselves flagged when they stop matching
anything. Analyzer self-tests (seeded violations) live in
``tests/test_bfcheck.py``; the enforced invariants are documented in
``docs/static_analysis.md``.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List

__all__ = [
    "Diagnostic", "ANALYZERS", "run", "run_all", "repo_root",
]


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: ``path:line: [analyzer] message``."""

    analyzer: str
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.analyzer}] {self.message}"


def repo_root(start: str = __file__) -> str:
    """The repository root (directory holding ``bluefog_tpu`` and ``csrc``)."""
    d = os.path.dirname(os.path.abspath(start))
    while d != os.path.dirname(d):
        if os.path.isdir(os.path.join(d, "bluefog_tpu")) and \
                os.path.isdir(os.path.join(d, "csrc")):
            return d
        d = os.path.dirname(d)
    raise RuntimeError("bfcheck: repository root not found")


def _analyzers() -> Dict[str, Callable[[str], List[Diagnostic]]]:
    # imported lazily so ``import bfcheck`` stays cheap and fixture tests
    # can import individual analyzers directly
    from . import (knob_check, lint_check, litter_check, lock_check,
                   metrics_check, protocol_check)

    return {
        "protocol": protocol_check.check,
        "knobs": knob_check.check,
        "locks": lock_check.check,
        "metrics": metrics_check.check,
        "lint": lint_check.check,
        "litter": litter_check.check,
    }


ANALYZERS = ("protocol", "knobs", "locks", "metrics", "lint", "litter")


def run(name: str, root: str) -> List[Diagnostic]:
    """Run one analyzer by name over the tree at ``root``."""
    return _analyzers()[name](root)


def run_all(root: str, names=None) -> List[Diagnostic]:
    """Run the given analyzers (default: all) and return every finding."""
    out: List[Diagnostic] = []
    for name in (names or ANALYZERS):
        out.extend(run(name, root))
    return out
