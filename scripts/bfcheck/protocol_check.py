"""Wire-protocol consistency analyzer.

Parses the C++ side of the control-plane protocol out of
``csrc/bf_runtime.cc`` — the ``enum Op`` block and the client's
``IsDedupOp`` retry switch — and cross-checks it against the Python
source of truth, ``bluefog_tpu/runtime/protocol.py``:

* the (enumerator, code) pairs must be a BIJECTION with the OPS table
  (no op missing a mirror, no code clash, no name drift),
* enum declarations must appear in numeric order (the canonical anchor
  both mirrors share),
* the ``IsDedupOp`` case set must equal the table's retry-unsafe rows
  (``idempotent=False``) — the cross-check that keeps a new op from
  shipping retry-unsafe: adding it to the enum without deciding its
  idempotency, or deciding it on one side only, fails here.
"""

from __future__ import annotations

import importlib.util
import os
import re
from typing import List

from . import Diagnostic

CC_PATH = os.path.join("csrc", "bf_runtime.cc")
PY_PATH = os.path.join("bluefog_tpu", "runtime", "protocol.py")

_ENUM_RE = re.compile(r"enum\s+Op\s*:\s*uint8_t\s*\{(.*?)\};", re.S)
_ENTRY_RE = re.compile(r"\bk([A-Za-z0-9]+)\s*=\s*(\d+)")
_DEDUP_RE = re.compile(
    r"IsDedupOp\s*\(uint8_t\s+\w+\)\s*\{(.*?)\n  \}", re.S)
_CASE_RE = re.compile(r"case\s+k([A-Za-z0-9]+)\s*:")


def _line_of(text: str, offset: int) -> int:
    return text.count("\n", 0, offset) + 1


def load_protocol(root: str):
    """Load runtime/protocol.py by path (dependency-free module, so this
    works for fixture trees without importing the bluefog_tpu package)."""
    path = os.path.join(root, PY_PATH)
    spec = importlib.util.spec_from_file_location("_bfcheck_protocol", path)
    mod = importlib.util.module_from_spec(spec)
    # dataclasses resolves string annotations through sys.modules
    import sys

    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod


def parse_cxx(root: str):
    """((name, code, line) enum entries, {dedup case names}, cc text)."""
    path = os.path.join(root, CC_PATH)
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    m = _ENUM_RE.search(text)
    entries = []
    if m:
        # strip comments inside the enum body before scanning entries
        body = re.sub(r"//[^\n]*", "", m.group(1))
        base = m.start(1)
        for em in _ENTRY_RE.finditer(body):
            # line numbers come from the uncommented body; recompute against
            # the original text by locating the exact "kName = N" token
            tok = re.search(r"\bk%s\s*=\s*%s\b" % (em.group(1), em.group(2)),
                            text[base:m.end(1)])
            line = _line_of(text, base + tok.start()) if tok else \
                _line_of(text, m.start())
            entries.append((f"k{em.group(1)}", int(em.group(2)), line))
    dm = _DEDUP_RE.search(text)
    dedup = set()
    dedup_line = _line_of(text, dm.start()) if dm else 1
    if dm:
        dedup = {f"k{c}" for c in _CASE_RE.findall(dm.group(1))}
    return entries, dedup, dedup_line, text


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []

    def bad(path, line, msg):
        out.append(Diagnostic("protocol", path, line, msg))

    try:
        proto = load_protocol(root)
    except (OSError, SyntaxError) as exc:
        bad(PY_PATH, 1, f"cannot load protocol table: {exc}")
        return out
    entries, dedup, dedup_line, _ = parse_cxx(root)
    if not entries:
        bad(CC_PATH, 1, "enum Op not found (parser anchor lost? keep the "
                        "`enum Op : uint8_t {` spelling)")
        return out

    ops = {o.cxx: o for o in proto.OPS}
    codes_py = {o.cxx: o.code for o in proto.OPS}
    cxx = {name: code for name, code, _ in entries}
    lines = {name: line for name, code, line in entries}

    # bijection: names
    for name, code, line in entries:
        if name not in ops:
            bad(CC_PATH, line,
                f"C++ op {name} = {code} has no row in "
                f"{PY_PATH} OPS — declare it (and decide its idempotency) "
                "before shipping")
    for o in proto.OPS:
        if o.cxx not in cxx:
            bad(PY_PATH, 1,
                f"Python op {o.name!r} ({o.cxx} = {o.code}) is missing "
                f"from the C++ enum in {CC_PATH}")
    # bijection: codes agree + unique
    for name, code, line in entries:
        if name in codes_py and codes_py[name] != code:
            bad(CC_PATH, line,
                f"{name} = {code} in C++ but {codes_py[name]} in "
                f"{PY_PATH} — the wire would desync")
    seen = {}
    for name, code, line in entries:
        if code in seen:
            bad(CC_PATH, line,
                f"duplicate op code {code}: {name} clashes with "
                f"{seen[code]}")
        seen[code] = name
    py_codes_seen = {}
    for o in proto.OPS:
        if o.code in py_codes_seen:
            bad(PY_PATH, 1,
                f"duplicate op code {o.code}: {o.name!r} clashes with "
                f"{py_codes_seen[o.code]!r}")
        py_codes_seen[o.code] = o.name

    # numeric declaration order (the shared canonical anchor)
    codes_in_order = [code for _, code, _ in entries]
    if codes_in_order != sorted(codes_in_order):
        first_bad = next(
            (i for i in range(1, len(codes_in_order))
             if codes_in_order[i] < codes_in_order[i - 1]), 0)
        name, code, line = entries[first_bad]
        bad(CC_PATH, line,
            f"enum Op declarations out of numeric order at {name} = {code} "
            "— keep the C++ enum sorted so diffs against the Python mirror "
            "stay reviewable")

    # retry-safety cross-check: IsDedupOp == idempotent=False rows
    unsafe_py = {o.cxx for o in proto.OPS if not o.idempotent}
    for name in sorted(dedup - unsafe_py):
        bad(CC_PATH, dedup_line,
            f"{name} rides the kSeqPre dedup path in C++ but is declared "
            f"idempotent in {PY_PATH} — reconcile the classification")
    for name in sorted(unsafe_py - dedup):
        bad(CC_PATH, dedup_line,
            f"{name} is declared retry-UNSAFE (idempotent=False) in "
            f"{PY_PATH} but missing from IsDedupOp — a retried "
            f"{ops[name].name} after a lost reply would be applied twice")
    # every C++ dedup case must at least be a known enum entry
    for name in sorted(dedup - set(cxx)):
        bad(CC_PATH, dedup_line,
            f"IsDedupOp names {name}, which is not in enum Op")
    _ = lines
    return out
