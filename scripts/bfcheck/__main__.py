"""CLI driver: ``python scripts/bfcheck [options]`` (or ``make check``).

Exit status 0 = tree clean, 1 = findings (printed as ``file:line:
[analyzer] message``), 2 = usage/setup error.
"""

from __future__ import annotations

import argparse
import os
import sys

# Support both `python scripts/bfcheck` (dir on sys.path, no package
# context) and `python -m bfcheck` from scripts/: ensure the parent dir is
# importable and re-import ourselves as a package.
_HERE = os.path.dirname(os.path.abspath(__file__))
if __package__ in (None, ""):
    sys.path.insert(0, os.path.dirname(_HERE))
    import bfcheck  # noqa: E402
    from bfcheck import knob_check  # noqa: E402
else:
    from . import knob_check
    import bfcheck  # noqa: F401 — resolved via sys.path by the runner

    bfcheck = sys.modules[__package__.split(".")[0]]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="bfcheck",
        description="project-invariant static analysis for bluefog_tpu")
    ap.add_argument("--root", default=None,
                    help="repository root (default: auto-detected)")
    ap.add_argument("--analyzer", "-a", action="append",
                    choices=list(bfcheck.ANALYZERS), default=None,
                    help="run only this analyzer (repeatable)")
    ap.add_argument("--lint", action="store_true",
                    help="shorthand for --analyzer lint (the make-lint "
                         "fallback when ruff is unavailable)")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the docs/env_variables.md knob table "
                         "from the registry, then exit")
    ap.add_argument("--list", action="store_true",
                    help="list analyzers and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in bfcheck.ANALYZERS:
            print(name)
        return 0

    try:
        root = args.root or bfcheck.repo_root()
    except RuntimeError as exc:
        print(f"bfcheck: {exc}", file=sys.stderr)
        return 2

    if args.write_docs:
        changed = knob_check.write_docs(root)
        print("docs/env_variables.md: "
              + ("knob table regenerated" if changed else "already current"))
        return 0

    names = args.analyzer or (["lint"] if args.lint else None)
    findings = bfcheck.run_all(root, names)
    for d in findings:
        print(d)
    ran = ", ".join(names or bfcheck.ANALYZERS)
    if findings:
        print(f"bfcheck: {len(findings)} finding(s) [{ran}]",
              file=sys.stderr)
        return 1
    print(f"bfcheck: clean [{ran}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
