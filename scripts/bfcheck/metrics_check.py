"""Metrics-vocabulary analyzer (`[metrics]`).

The telemetry plane's contract is that every published sample is
self-describing (``# HELP`` per family, r12) and that the live
time-series layer's declarative tables (``TS_BINDINGS``, the alert
rules) reference real instruments — a typo'd binding silently samples
nothing, and an instrument outside the curated vocabulary scrapes with a
generic HELP line. This analyzer closes both holes statically:

1. **Creation sites** — every ``_metrics.counter/gauge/histogram/timed(
   "name")`` call in ``bluefog_tpu/`` must
     * use a name whose first dotted segment is a declared prefix family
       (``metrics._PREFIX_FAMILIES``), and
     * resolve to HELP text: a ``doc=`` argument at the site, an entry in
       the curated ``_HELP_EXACT`` table, or a ``_HELP_PREFIX`` rule.
2. **Bindings & rules** — every instrument named by
   ``timeseries.TS_BINDINGS`` and every series named by an alert rule in
   ``timeseries.DEFAULT_RULES`` must resolve to a known instrument
   (a creation-site literal, a curated-table entry, or a prefix rule), a
   declared derived series (``DERIVED_SERIES``), or a ``.rate`` of a
   ``RATE_SERIES`` member.

Waive a finding with ``# bfcheck: ok-metrics (reason)`` on its line.
Everything is AST-parsed — fixture trees (tests/test_bfcheck.py) supply
their own miniature ``metrics.py``/``timeseries.py``.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Set, Tuple

from . import Diagnostic

METRICS_PATH = os.path.join("bluefog_tpu", "runtime", "metrics.py")
TS_PATH = os.path.join("bluefog_tpu", "runtime", "timeseries.py")
PKG_ROOT = "bluefog_tpu"

WAIVER = "bfcheck: ok-metrics"

_CREATORS = {"counter", "gauge", "histogram", "timed"}


def _literal_assign(tree: ast.AST, name: str):
    """The literal value assigned to module-level ``name`` (plain or
    annotated assignment); None when absent or not a literal."""
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            target = node.target.id
            value = node.value
        if target == name and value is not None:
            try:
                return ast.literal_eval(value)
            except (ValueError, SyntaxError):
                return None
    return None


def load_vocabulary(root: str):
    """(exact HELP names, HELP prefixes, prefix families) from the
    metrics module — parsed, never imported."""
    path = os.path.join(root, METRICS_PATH)
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    exact = _literal_assign(tree, "_HELP_EXACT") or {}
    prefix_rows = _literal_assign(tree, "_HELP_PREFIX") or ()
    families = _literal_assign(tree, "_PREFIX_FAMILIES") or ()
    prefixes = tuple(p for p, _ in prefix_rows)
    return set(exact), prefixes, tuple(families)


def load_ts_tables(root: str):
    """(bindings, rule series, rate series, derived series) from the
    timeseries module; all empty when the module does not exist (fixture
    trees without a live plane)."""
    path = os.path.join(root, TS_PATH)
    if not os.path.isfile(path):
        return (), (), (), ()
    with open(path) as f:
        tree = ast.parse(f.read(), path)
    bindings = _literal_assign(tree, "TS_BINDINGS") or ()
    rate = _literal_assign(tree, "RATE_SERIES") or ()
    derived = _literal_assign(tree, "DERIVED_SERIES") or ()
    rules: List[Tuple[str, str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "Rule" and len(node.args) >= 2 and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[1], ast.Constant):
            rules.append((str(node.args[0].value),
                          str(node.args[1].value), node.lineno))
    bound = []
    for row in bindings:
        if isinstance(row, (tuple, list)) and row and \
                isinstance(row[0], str):
            bound.append(row[0])
    return tuple(bound), tuple(rules), tuple(rate), tuple(derived)


def _iter_package_files(root: str):
    pkg = os.path.join(root, PKG_ROOT)
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", "build")]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _is_metrics_receiver(func: ast.AST) -> bool:
    """True for ``<something named *metrics*>.counter(...)`` shapes —
    the package-wide convention is ``_metrics.counter("name")``."""
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr not in _CREATORS:
        return False
    value = func.value
    while isinstance(value, ast.Attribute):
        value = value.value
    return isinstance(value, ast.Name) and "metrics" in value.id.lower()


def collect_instruments(root: str):
    """{name: [(path, line, has_doc)]} for every creation site in the
    package (the metrics module itself is registry plumbing, skipped)."""
    out: Dict[str, List[Tuple[str, int, bool]]] = {}
    skip = os.path.join(root, METRICS_PATH)
    for path in _iter_package_files(root):
        if os.path.abspath(path) == os.path.abspath(skip):
            continue
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), path)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or \
                    not _is_metrics_receiver(node.func):
                continue
            if not node.args or \
                    not isinstance(node.args[0], ast.Constant) or \
                    not isinstance(node.args[0].value, str):
                continue
            has_doc = any(kw.arg == "doc" for kw in node.keywords)
            out.setdefault(node.args[0].value, []).append(
                (path, node.lineno, has_doc))
    return out


def _waived(lines: List[str], lineno: int) -> bool:
    for ln in (lineno - 1, lineno - 2):
        if 0 <= ln < len(lines) and WAIVER in lines[ln]:
            return True
    return False


def _resolves_help(name: str, exact: Set[str],
                   prefixes: Tuple[str, ...]) -> bool:
    return name in exact or any(name.startswith(p) for p in prefixes)


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    try:
        exact, prefixes, families = load_vocabulary(root)
    except (OSError, SyntaxError) as exc:
        return [Diagnostic("metrics", METRICS_PATH, 1,
                           f"cannot parse the metrics module: {exc}")]
    instruments = collect_instruments(root)
    file_lines: Dict[str, List[str]] = {}

    def lines_of(path: str) -> List[str]:
        if path not in file_lines:
            try:
                with open(path) as f:
                    file_lines[path] = f.read().splitlines()
            except OSError:
                file_lines[path] = []
        return file_lines[path]

    rel = os.path.relpath
    for name, sites in sorted(instruments.items()):
        family = name.split(".", 1)[0]
        for path, line, has_doc in sites:
            if _waived(lines_of(path), line):
                continue
            if families and family not in families:
                out.append(Diagnostic(
                    "metrics", rel(path, root), line,
                    f"instrument '{name}' uses undeclared prefix family "
                    f"'{family}' (declare it in metrics._PREFIX_FAMILIES "
                    "with curated HELP coverage, or rename)"))
            if not has_doc and not _resolves_help(name, exact, prefixes):
                out.append(Diagnostic(
                    "metrics", rel(path, root), line,
                    f"instrument '{name}' has no HELP text: pass doc= at "
                    "the creation site or add it to metrics._HELP_EXACT "
                    "(every scraped sample must be self-describing)"))
    # live-plane tables: bindings + alert rules name real series
    bindings, rules, rate_series, derived = load_ts_tables(root)
    known: Set[str] = set(instruments) | set(exact) | set(derived)

    def known_series(name: str) -> bool:
        if name in known or _resolves_help(name, set(), prefixes):
            return True
        if name.endswith(".rate"):
            stem = name[:-len(".rate")]
            return stem in rate_series and (
                known_series(stem) or stem in bindings)
        return False

    ts_rel = TS_PATH
    for name in bindings:
        if not known_series(name):
            out.append(Diagnostic(
                "metrics", ts_rel, 1,
                f"TS_BINDINGS names '{name}', which no creation site, "
                "curated HELP entry, or prefix rule declares — the "
                "sampler would silently record nothing"))
    for rule_name, series, line in rules:
        if not known_series(series):
            out.append(Diagnostic(
                "metrics", ts_rel, line,
                f"alert rule '{rule_name}' references series "
                f"'{series}', which is neither a declared instrument, a "
                "derived series, nor a RATE_SERIES '.rate' — the rule "
                "can never fire"))
    return out
