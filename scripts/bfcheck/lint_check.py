"""Minimal pyflakes-style fallback linter.

``make lint`` prefers ``ruff`` (configured in pyproject.toml); on boxes
without it this analyzer keeps the highest-signal checks enforceable with
the stdlib only:

* **unused imports** — a module-level ``import``/``from-import`` whose
  bound name is never referenced again in the file (``# noqa`` on the
  line, conventional re-export contexts like ``__init__.py``, and names
  listed in ``__all__`` are exempt),
* **duplicate definitions** — two top-level ``def``/``class`` statements
  binding the same name in one module (the later silently shadows the
  earlier; almost always a copy-paste casualty).

Scope matches the ruff config: ``bluefog_tpu/``, ``scripts/``,
``tests/``.
"""

from __future__ import annotations

import ast
import os
from typing import List

from . import Diagnostic

SCAN_ROOTS = ("bluefog_tpu", "scripts", "tests")


def _names_used(tree: ast.AST) -> set:
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            # the ROOT of an attribute chain is a name usage
            base = node.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)
    return used


def _exported(tree: ast.AST) -> set:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" and \
                        isinstance(node.value, (ast.List, ast.Tuple)):
                    out.update(e.value for e in node.value.elts
                               if isinstance(e, ast.Constant)
                               and isinstance(e.value, str))
    return out


def check_file(path: str, rel: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as exc:
        return [Diagnostic("lint", rel, exc.lineno or 1,
                           f"syntax error: {exc.msg}")]
    lines = src.splitlines()
    used = _names_used(tree)
    exported = _exported(tree)
    reexport_ok = os.path.basename(path) == "__init__.py"

    # unused imports (module level only; function-local imports are almost
    # always deliberate lazy imports in this tree)
    for node in tree.body:
        names = []
        if isinstance(node, ast.Import):
            names = [(a.asname or a.name.split(".")[0], a) for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__" or \
                    any(a.name == "*" for a in node.names):
                continue
            names = [(a.asname or a.name, a) for a in node.names]
        if not names:
            continue
        line_text = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        span = "\n".join(lines[node.lineno - 1:node.end_lineno])
        if "noqa" in line_text or "noqa" in span:
            continue
        for bound, alias in names:
            if bound.startswith("_"):
                continue
            if reexport_ok or bound in exported:
                continue
            # count references excluding the import statement itself
            if bound not in used or _only_import_uses(tree, bound):
                out.append(Diagnostic(
                    "lint", rel, node.lineno,
                    f"'{bound}' imported but unused (delete it, or mark a "
                    "deliberate re-export with `# noqa: F401`)"))

    # duplicate top-level definitions
    seen = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen:
                out.append(Diagnostic(
                    "lint", rel, node.lineno,
                    f"redefinition of '{node.name}' (first defined at "
                    f"line {seen[node.name]}) — the earlier definition is "
                    "dead"))
            else:
                seen[node.name] = node.lineno
    return out


def _only_import_uses(tree: ast.AST, name: str) -> bool:
    """True when every Name reference to ``name`` sits inside an import
    statement (i.e. no real use)."""
    import_lines = set()
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            import_lines.update(range(node.lineno, (node.end_lineno or
                                                    node.lineno) + 1))
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id == name and \
                node.lineno not in import_lines:
            return False
        if isinstance(node, ast.Attribute):
            base = node.value
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name) and base.id == name and \
                    base.lineno not in import_lines:
                return False
    return True


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    for entry in SCAN_ROOTS:
        base = os.path.join(root, entry)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "build")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    path = os.path.join(dirpath, fn)
                    out.extend(check_file(path, os.path.relpath(path, root)))
    return out
