"""Env-knob registry analyzer.

Walks every Python file in the tree (AST, no imports) plus the native
source and finds each ``BLUEFOG_*`` environment READ:

* ``os.environ.get(name[, default])`` / ``os.getenv`` / ``env.get`` (any
  receiver whose attribute chain mentions ``environ``),
* ``os.environ[name]`` subscripts in Load context,
* ``name in os.environ`` membership probes,
* ``timeout_from_env(name, default)`` (the shared entry-script helper),
* ``EnvInt("NAME", default)`` / ``EnvSeconds("NAME", default)`` in
  ``csrc/bf_runtime.cc``.

Checks, against ``runtime/config.py``'s ``KNOBS`` registry:

1. every read knob is declared (a typo'd or ad-hoc knob fails the tree),
2. a per-site LITERAL default must agree with the registry default —
   the "four different defaults for one knob" drift class,
3. every declared knob appears in ``docs/env_variables.md``, and the
   generated knob table section matches the registry exactly
   (``python scripts/bfcheck --write-docs`` regenerates it).

Writes (``env[name] = ...``), deletes, and knob names inside plain string
literals are ignored — only reads are classified.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
import sys
from typing import List, Optional

from . import Diagnostic

CONFIG_PATH = os.path.join("bluefog_tpu", "runtime", "config.py")
DOCS_PATH = os.path.join("docs", "env_variables.md")
CC_PATH = os.path.join("csrc", "bf_runtime.cc")
TABLE_BEGIN = "<!-- bfcheck:knob-table:begin (generated - edit "\
    "runtime/config.py KNOBS and run `python scripts/bfcheck "\
    "--write-docs`) -->"
TABLE_END = "<!-- bfcheck:knob-table:end -->"

PY_ROOTS = ("bluefog_tpu", "scripts", "tests", "bench.py",
            "__graft_entry__.py")

_CC_ENV_RE = re.compile(
    r'Env(?:Int|Seconds)\(\s*"(BLUEFOG_[A-Z0-9_]+)"\s*,\s*([-0-9.]+)')


def load_registry(root: str):
    """Load the KNOBS table from runtime/config.py by path (stdlib-only
    module; fixture trees supply their own)."""
    path = os.path.join(root, CONFIG_PATH)
    spec = importlib.util.spec_from_file_location("_bfcheck_config", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return {k.name: k for k in mod.KNOBS}


def iter_py_files(root: str):
    for entry in PY_ROOTS:
        path = os.path.join(root, entry)
        if os.path.isfile(path):
            yield path
        elif os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", "build")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


def _const_eval(node) -> Optional[object]:
    """Evaluate simple constant expressions (literals and arithmetic over
    them — `8 * 1024 * 1024` style defaults); None when not constant."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.BinOp) and \
            isinstance(node.op, (ast.Mult, ast.Add, ast.Sub, ast.Pow)):
        left, right = _const_eval(node.left), _const_eval(node.right)
        if isinstance(left, (int, float)) and isinstance(right, (int, float)):
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            return left ** right
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _const_eval(node.operand)
        if isinstance(v, (int, float)):
            return -v
    return None


def _mentions_environ(node) -> bool:
    """True when the attribute/name chain of ``node`` mentions environ."""
    while isinstance(node, ast.Attribute):
        if node.attr == "environ":
            return True
        node = node.value
    return isinstance(node, ast.Name) and node.id == "environ"


class _ReadCollector(ast.NodeVisitor):
    """Collects (knob name, default node or None, line) env reads."""

    def __init__(self) -> None:
        self.reads = []

    @staticmethod
    def _knob_arg(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.startswith("BLUEFOG_"):
            return node.value
        return None

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = None
        default = None
        if isinstance(fn, ast.Attribute) and fn.attr in ("get", "getenv") \
                and (_mentions_environ(fn.value)
                     or (isinstance(fn.value, ast.Name)
                         and fn.value.id in ("os", "env"))):
            if node.args:
                name = self._knob_arg(node.args[0])
                if len(node.args) > 1:
                    default = node.args[1]
        elif isinstance(fn, ast.Name) and fn.id == "timeout_from_env":
            if node.args:
                name = self._knob_arg(node.args[0])
                if len(node.args) > 1:
                    default = node.args[1]
        if name:
            self.reads.append((name, default, node.lineno))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and _mentions_environ(node.value):
            name = self._knob_arg(node.slice)
            if name:
                self.reads.append((name, None, node.lineno))
        self.generic_visit(node)

    def visit_Compare(self, node: ast.Compare) -> None:
        if len(node.ops) == 1 and \
                isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                _mentions_environ(node.comparators[0]):
            name = self._knob_arg(node.left)
            if name:
                self.reads.append((name, None, node.lineno))
        self.generic_visit(node)


def _default_matches(knob, value) -> bool:
    """Is a per-site literal default compatible with the registry's?"""
    reg = knob.default
    if knob.type in ("int", "float"):
        try:
            site = float(value)
        except (TypeError, ValueError):
            return False
        return reg is not None and float(reg) == site
    if knob.type == "bool":
        site = value == "1" if isinstance(value, str) else bool(value)
        return bool(reg) == site
    # str / path / spec: empty-string and None both mean "unset"
    return (reg or "") == (value or "")


def render_knob_table(registry) -> str:
    """The generated docs/env_variables.md knob table (between markers)."""
    lines = [TABLE_BEGIN,
             "| Variable | Type | Default | Effect |",
             "|---|---|---|---|"]
    for k in registry.values():
        if k.default is None:
            dflt = "unset"
        elif k.type == "bool":
            dflt = "`1`" if k.default else "`0`"
        elif isinstance(k.default, float) and k.default == int(k.default):
            dflt = f"`{int(k.default)}`"
        else:
            dflt = f"`{k.default}`"
        scope = " *(read by the native layer)*" if k.scope == "native" \
            else ""
        lines.append(f"| `{k.name}` | {k.type} | {dflt} | {k.doc}{scope} |")
    lines.append(TABLE_END)
    return "\n".join(lines) + "\n"


def write_docs(root: str) -> bool:
    """Regenerate the knob table between the markers; True if changed."""
    registry = load_registry(root)
    path = os.path.join(root, DOCS_PATH)
    with open(path, "r", encoding="utf-8") as f:
        text = f.read()
    begin = text.find(TABLE_BEGIN)
    end = text.find(TABLE_END)
    if begin < 0 or end < 0:
        raise RuntimeError(f"{DOCS_PATH}: knob-table markers not found")
    new = text[:begin] + render_knob_table(registry) + \
        text[end + len(TABLE_END) + 1:]
    if new != text:
        with open(path, "w", encoding="utf-8") as f:
            f.write(new)
        return True
    return False


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []

    def bad(path, line, msg):
        out.append(Diagnostic("knobs", os.path.relpath(path, root)
                              if os.path.isabs(path) else path, line, msg))

    try:
        registry = load_registry(root)
    except Exception as exc:  # noqa: BLE001 — any load failure is the finding
        bad(CONFIG_PATH, 1, f"cannot load knob registry: {exc}")
        return out

    # -- Python read sites --------------------------------------------------
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root)
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as exc:
            bad(rel, exc.lineno or 1, f"syntax error: {exc.msg}")
            continue
        col = _ReadCollector()
        col.visit(tree)
        for name, default, line in col.reads:
            k = registry.get(name)
            if k is None:
                bad(rel, line,
                    f"read of undeclared knob {name} — declare it in "
                    f"{CONFIG_PATH} KNOBS (type, default, doc) first")
                continue
            if default is not None:
                value = _const_eval(default)
                if value is not None and not _default_matches(k, value):
                    bad(rel, line,
                        f"per-site default {value!r} for {name} "
                        f"contradicts the registry default "
                        f"{k.default!r} — import it from the registry "
                        "(runtime/config.py knob_env) instead")

    # -- native read sites --------------------------------------------------
    cc = os.path.join(root, CC_PATH)
    if os.path.exists(cc):
        with open(cc, "r", encoding="utf-8") as f:
            cc_text = f.read()
        for m in _CC_ENV_RE.finditer(cc_text):
            name, site_default = m.group(1), m.group(2)
            line = cc_text.count("\n", 0, m.start()) + 1
            k = registry.get(name)
            if k is None:
                bad(CC_PATH, line,
                    f"native read of undeclared knob {name} — declare it "
                    f"in {CONFIG_PATH} KNOBS (scope=\"native\")")
                continue
            if k.default is not None and \
                    float(k.default) != float(site_default):
                bad(CC_PATH, line,
                    f"native default {site_default} for {name} contradicts "
                    f"the registry default {k.default!r}")

    # -- docs coverage ------------------------------------------------------
    docs = os.path.join(root, DOCS_PATH)
    if not os.path.exists(docs):
        bad(DOCS_PATH, 1, "docs/env_variables.md missing")
        return out
    with open(docs, "r", encoding="utf-8") as f:
        doc_text = f.read()
    for name in registry:
        if f"`{name}`" not in doc_text:
            bad(DOCS_PATH, 1,
                f"declared knob {name} is not documented — run "
                "`python scripts/bfcheck --write-docs`")
    begin = doc_text.find(TABLE_BEGIN)
    end = doc_text.find(TABLE_END)
    if begin < 0 or end < 0:
        bad(DOCS_PATH, 1, "knob-table markers missing (the Live-knobs "
                          "table is generated from the registry)")
    else:
        current = doc_text[begin:end + len(TABLE_END)] + "\n"
        if current != render_knob_table(registry):
            line = doc_text.count("\n", 0, begin) + 1
            bad(DOCS_PATH, line,
                "generated knob table is stale — run "
                "`python scripts/bfcheck --write-docs`")
    return out
