"""Lock & thread discipline analyzer (Python runtime).

Three checks over ``bluefog_tpu/`` (AST only, no imports):

``lock-order``
    Builds the lock-acquisition graph: a ``with <lock>:`` / ``.acquire()``
    nested inside another lock's scope records the ordered pair
    (outer → inner), keyed by the lock's attribute/variable name
    (``self._mu`` → ``_mu``; ``win.state_mu`` → ``state_mu``;
    ``win_mutex(...)`` → ``win_mutex``). One interprocedural hop is
    followed: a call made while holding L to a same-module function that
    acquires M also records (L → M). Any cycle in the global graph is a
    potential deadlock between thread entry points and is reported at
    both edges.

``blocking-under-lock``
    Flags calls that can block on the control-plane SERVER — names in
    ``BLOCKING_CALLS`` (``barrier``, distributed ``lock``, ``win_mutex``,
    ``synchronize``…) — made while a local ``threading`` lock is held:
    a handler parked for seconds while holding a process-local mutex
    stalls every other thread that needs it (the heartbeat above all).
    Sites that hold a lock across a blocking call DELIBERATELY carry a
    ``# bfcheck: ok-blocking-under-lock (reason)`` waiver on the call
    line, which this check honors (and reports when unused).

``daemon-join``
    Every ``threading.Thread(daemon=True)`` creation must have stop/join
    wiring: a ``.join(`` somewhere in the same module (matching how the
    thread object is stored), or an explicit
    ``# bfcheck: ok-daemon-no-join (reason)`` waiver. Fire-and-forget
    daemons outlive shutdown and segfault interpreters at teardown.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from . import Diagnostic

PY_ROOT = "bluefog_tpu"

# Calls that can park on the control-plane server (or a peer) indefinitely.
BLOCKING_CALLS = {
    "barrier", "win_mutex", "mutex_acquire", "_acquire", "_acquire_all",
    "synchronize", "lock",
}

# Lock names recognized as process-local threading locks. Derived from the
# naming convention the runtime actually uses; the analyzer also treats any
# `with X:` whose key ends in one of these suffixes as a lock scope.
LOCK_SUFFIXES = ("_mu", "_lock", "mutex", "mutexes", "state_mu", "_gate",
                 "_gates")

WAIVER_BLOCKING = "bfcheck: ok-blocking-under-lock"
WAIVER_DAEMON = "bfcheck: ok-daemon-no-join"


def _key_of(node) -> Optional[str]:
    """Normalize a lock expression to its stable name key."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Subscript):
        return _key_of(node.value)
    if isinstance(node, ast.Call):
        return _call_name(node)
    return None


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _looks_like_lock(key: str) -> bool:
    return key is not None and (
        key.endswith(LOCK_SUFFIXES) or key in ("win_mutex",))


class _FuncInfo:
    """Per-function facts: locks acquired at top level, ordered pairs,
    blocking calls with held-lock context, calls made under each lock."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.acquires: List[Tuple[str, int]] = []          # (lock, line)
        self.pairs: List[Tuple[str, str, int]] = []        # (outer, inner)
        self.blocking: List[Tuple[str, str, int]] = []     # (lock, call)
        self.calls_under: List[Tuple[str, str, int]] = []  # (lock, callee)


class _ModuleScanner(ast.NodeVisitor):
    def __init__(self, rel: str, waived_lines: Set[int]) -> None:
        self.rel = rel
        self.waived_lines = waived_lines
        self.funcs: Dict[str, _FuncInfo] = {}
        self._stack: List[str] = []      # held locks (lexical)
        self._fn: Optional[_FuncInfo] = None

    # -- function scoping ---------------------------------------------------

    def _visit_fn(self, node) -> None:
        prev_fn, prev_stack = self._fn, self._stack
        info = _FuncInfo(node.name)
        # methods of different classes may share names; last one wins is
        # acceptable for this analysis (keys are advisory)
        self.funcs[node.name] = info
        self._fn, self._stack = info, []
        for child in node.body:
            self.visit(child)
        self._fn, self._stack = prev_fn, prev_stack

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    # -- lock scopes --------------------------------------------------------

    def _record_acquire(self, key: str, line: int) -> None:
        if self._fn is None:
            return
        if not self._stack:
            self._fn.acquires.append((key, line))
        for outer in self._stack:
            if outer != key:
                self._fn.pairs.append((outer, key, line))

    def visit_With(self, node: ast.With) -> None:
        keys = []
        for item in node.items:
            key = _key_of(item.context_expr)
            if key is not None and (_looks_like_lock(key)
                                    or key in BLOCKING_CALLS):
                # a `with win_mutex(...)` is both an acquisition and a
                # potentially blocking server call
                if key in BLOCKING_CALLS and self._stack and \
                        node.lineno not in self.waived_lines and \
                        self._fn is not None:
                    self._fn.blocking.append(
                        (self._stack[-1], key, node.lineno))
                self._record_acquire(key, node.lineno)
                keys.append(key)
        self._stack.extend(keys)
        for child in node.body:
            self.visit(child)
        for _ in keys:
            self._stack.pop()

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        if name == "acquire" and isinstance(node.func, ast.Attribute):
            key = _key_of(node.func.value)
            if _looks_like_lock(key):
                # .acquire() without `with`: treat the rest of the function
                # as holding it (matching the acquire/try/finally idiom)
                self._record_acquire(key, node.lineno)
                self._stack.append(key)
        elif name in BLOCKING_CALLS and self._fn is not None \
                and self._stack and node.lineno not in self.waived_lines:
            self._fn.blocking.append((self._stack[-1], name, node.lineno))
        elif name and self._fn is not None and self._stack:
            self._fn.calls_under.append((self._stack[-1], name, node.lineno))
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        # releases in `finally:` close the acquire/try/finally idiom; pop
        # any lock released there once the try block is done
        for child in node.body + node.handlers + node.orelse:
            self.visit(child)
        released = set()
        for child in node.finalbody:
            for sub in ast.walk(child):
                if isinstance(sub, ast.Call) and \
                        _call_name(sub) == "release" and \
                        isinstance(sub.func, ast.Attribute):
                    key = _key_of(sub.func.value)
                    if key:
                        released.add(key)
            self.visit(child)
        for key in released:
            if key in self._stack:
                self._stack.remove(key)


def _waived(src: str, marker: str) -> Set[int]:
    """Lines covered by a waiver comment: the marker's own line plus the
    following few lines (a waiver usually sits in a comment block just
    above the flagged statement)."""
    out = set()
    for i, line in enumerate(src.splitlines(), 1):
        if marker in line:
            out.update(range(i, i + 7))
    return out


def check(root: str) -> List[Diagnostic]:
    out: List[Diagnostic] = []

    def bad(path, line, msg):
        out.append(Diagnostic("locks", path, line, msg))

    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}

    py_root = os.path.join(root, PY_ROOT)
    for dirpath, dirnames, filenames in os.walk(py_root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            try:
                tree = ast.parse(src, filename=rel)
            except SyntaxError as exc:
                bad(rel, exc.lineno or 1, f"syntax error: {exc.msg}")
                continue

            scanner = _ModuleScanner(rel, _waived(src, WAIVER_BLOCKING))
            scanner.visit(tree)

            # intraprocedural pairs -> global edge set
            for info in scanner.funcs.values():
                for outer, inner, line in info.pairs:
                    edges.setdefault((outer, inner), (rel, line))
                # one interprocedural hop: call under L to a same-module
                # function whose top level acquires M
                for lock, callee, line in info.calls_under:
                    target = scanner.funcs.get(callee)
                    if target is None:
                        continue
                    for inner, _ in target.acquires:
                        if inner != lock:
                            edges.setdefault((lock, inner), (rel, line))
                for lock, call, line in info.blocking:
                    bad(rel, line,
                        f"potentially blocking control-plane call "
                        f"'{call}' while holding local lock '{lock}' — a "
                        "parked server op would stall every thread "
                        "needing that lock (waive deliberate sites with "
                        f"`# {WAIVER_BLOCKING} (reason)`)")

            # daemon-thread join wiring
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and _call_name(node) == "Thread"):
                    continue
                daemon = any(kw.arg == "daemon"
                             and isinstance(kw.value, ast.Constant)
                             and kw.value.value is True
                             for kw in node.keywords)
                if not daemon:
                    continue
                if node.lineno in _waived(src, WAIVER_DAEMON) or \
                        (node.lineno - 1) in _waived(src, WAIVER_DAEMON):
                    continue
                if ".join(" not in src:
                    bad(rel, node.lineno,
                        "daemon thread created but this module never "
                        "join()s any thread — wire a stop()/join() path "
                        "or waive with "
                        f"`# {WAIVER_DAEMON} (reason)`")

    # cycles in the global lock-order graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    reported = set()
    for (a, b), (rel, line) in sorted(edges.items()):
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            rel2, line2 = edges[(b, a)]
            bad(rel, line,
                f"lock-order inversion: '{a}' → '{b}' here but "
                f"'{b}' → '{a}' at {rel2}:{line2} — two threads taking "
                "them in opposite orders deadlock")
    # longer cycles (3+): DFS
    def _find_cycle(start: str) -> Optional[List[str]]:
        seen, stack = set(), [(start, [start])]
        while stack:
            node, path_ = stack.pop()
            for nxt in graph.get(node, ()):  # noqa: B007
                if nxt == start and len(path_) > 2:
                    return path_ + [start]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path_ + [nxt]))
        return None

    for start in sorted(graph):
        cyc = _find_cycle(start)
        if cyc and not any((cyc[i], cyc[i + 1]) in reported
                           or (cyc[i + 1], cyc[i]) in reported
                           for i in range(len(cyc) - 1)):
            rel, line = edges[(cyc[0], cyc[1])]
            reported.add((cyc[0], cyc[1]))
            bad(rel, line,
                "lock-order cycle: " + " → ".join(cyc)
                + " — break one edge or order the acquisitions")
    return out
