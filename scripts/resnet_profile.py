"""Capture + categorize a device trace of the benchmarked ResNet-50 step.

Answers the VERDICT-r3 question behind "push ResNet MFU": WHERE do the
46-49 ms of device time go — MXU-limited convolutions, HBM-limited
fusions, or scheduling gaps? Writes a jax.profiler trace (xplane + chrome
json) under ``traces/<name>/`` and prints a per-category duration table
parsed from the chrome trace, which is the evidence the PERF.md roofline
section cites.

Run on the real chip: ``python scripts/resnet_profile.py``.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def categorize(name: str) -> str:
    n = name.lower()
    if ("convolution" in n or "conv" in n) and "fusion" not in n:
        return "convolution"
    if "fusion" in n:
        return "fusion (elementwise/BN/pool)"
    if "copy" in n or "transpose" in n:
        return "copy/transpose"
    if "reduce" in n:
        return "reduce"
    if "dot" in n or "matmul" in n:
        return "matmul"
    if "dynamic" in n or "slice" in n or "concatenate" in n:
        return "slice/concat"
    return "other"


def iter_device_op_events(trace_dir: str):
    """Yield (name, args, dur_us) for XLA-op rows on device lanes.

    These are the ONLY rows safe to sum: the steps/modules lanes of the
    same device pid re-cover the identical time spans and would double-
    count. Shared by parse_trace and scripts/convgrad_probe.py."""
    files = sorted(glob.glob(
        os.path.join(trace_dir, "plugins/profile/*/*.trace.json.gz")))
    if not files:
        raise RuntimeError(f"no chrome trace found under {trace_dir}")
    with gzip.open(files[-1], "rt") as f:
        events = json.load(f)["traceEvents"]
    # device lanes: pid whose process_name mentions TPU/device; fall back to
    # lanes that carry XLA op events (args with 'long_name'/hlo)
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("name") == "process_name"}
    device_pids = {p for p, n in pid_names.items()
                   if "TPU" in n or "/device" in n.lower()}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        dur = float(e.get("dur", 0.0))
        name = e.get("name", "")
        args = e.get("args") or {}
        if not (args.get("long_name") or args.get("hlo_category")
                or name.startswith(("%", "fusion", "convolution", "copy"))):
            continue
        yield name, args, dur


def device_op_seconds(trace_dir: str) -> float:
    """Total device XLA-op time in seconds (double-count-safe)."""
    return sum(d for _, _, d in iter_device_op_events(trace_dir)) / 1e6


def parse_trace(trace_dir: str) -> None:
    per_cat = collections.Counter()
    per_op = collections.Counter()
    total = 0.0
    try:
        for name, args, dur in iter_device_op_events(trace_dir):
            cat = args.get("hlo_category") or categorize(name)
            per_cat[cat] += dur
            per_op[name.split(".")[0]] += dur
            total += dur
    except RuntimeError as exc:
        print(exc)
        return
    print(f"\ndevice op time by category ({os.path.basename(trace_dir)}):")
    for cat, dur in per_cat.most_common():
        print(f"  {cat:32s} {dur / 1e3:8.2f} ms  {100 * dur / total:5.1f} %")
    print(f"  {'TOTAL':32s} {total / 1e3:8.2f} ms")
    print("\ntop 12 ops:")
    for op, dur in per_op.most_common(12):
        print(f"  {op:48s} {dur / 1e3:8.2f} ms")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--name", default="resnet50_r4")
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--parse-only", action="store_true",
                   help="only re-parse an existing trace directory")
    args = p.parse_args()
    trace_dir = os.path.join(REPO, "traces", args.name)

    if not args.parse_only:
        import jax
        import numpy as np

        import bench

        opt, state, batch, sync = bench.setup()
        for _ in range(3):  # compile + warm
            state, m = opt.step(state, batch)
        sync(m)
        with jax.profiler.trace(trace_dir):
            for _ in range(args.steps):
                state, m = opt.step(state, batch)
            sync(m)
        import bluefog_tpu as bf
        bf.shutdown()
        print("trace written to", trace_dir)

    parse_trace(trace_dir)


if __name__ == "__main__":
    main()
