#!/usr/bin/env python
"""SLO/tracing smoke test (`make slo-smoke`, docs/slo.md).

A trainer-stand-in publisher child + one traced serve client over a real
control-plane shard server, asserting the request-path observability
acceptance surface end to end:

  * **overhead gate**: the full per-request trace record set (the ~10
    slotted ring stores a traced request costs) stays under 2 us;
  * **burn-rate red path**: with ``serve_staleness:1ver@5s`` declared,
    arming the native fault injector (the runtime front-end of
    ``BLUEFOG_CP_FAULT``) with a per-op delay in the CLIENT process
    makes pulls crawl while the untouched publisher keeps committing —
    staleness breaches push both burn windows over the threshold and
    the ``slo.serve_staleness`` alert FIRES within the window;
  * while red: ``bfrun --top --once`` renders the SERVING SLO section
    and ``bfrun --status --strict`` exits 2 on budget exhaustion;
  * **recovery**: disarming the injector clears the alert as soon as
    the fast window recovers (and the published ``bf.alerts.<rank>``
    blob empties);
  * **merged trace**: the client's and the publisher's flight rings
    merge into one chrome trace with at least one cross-process
    publisher->client stripe flow pair, and the committed snapshot's
    lineage record resolves to the exact producing train step.

Exits non-zero (with a message) on any violated assertion.
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import zlib

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("BLUEFOG_CP_BACKOFF_MS", "20")
os.environ.update({
    "BLUEFOG_TRACE_SERVE": "1",
    "BLUEFOG_SLO": "serve_staleness:1ver@5s",
    "BLUEFOG_SLO_BURN": "2.0",
    "BLUEFOG_SERVE_POLL_S": "0.1",
    "BLUEFOG_FLIGHT_CAPACITY": "32768",
})

import numpy as np  # noqa: E402

from bluefog_tpu.runtime import flight  # noqa: E402
from bluefog_tpu.runtime import native  # noqa: E402
from bluefog_tpu.serving import snapshot as snap  # noqa: E402
from bluefog_tpu.serving.client import ServeClient  # noqa: E402

SHARD_SERVER = os.path.join(_ROOT, "bluefog_tpu", "runtime",
                            "shard_server.py")
PUB_CHILD = os.path.join(_ROOT, "tests", "_serve_pub_child.py")


def check(cond, msg):
    if not cond:
        print(f"slo-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def overhead_gate() -> float:
    """Best-of-5 mean per-record cost of the traced-request pattern (us)
    — the same < 2 us/record bar the obs-smoke ring gate holds."""
    rec = flight.FlightRecorder(capacity=32768)
    nids = [rec.intern(n) for n in
            ("serve.req", "serve.admit", "serve.queue", "serve.linger",
             "serve.decode")]
    iters = 5000
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter_ns()
        for i in range(iters):
            # the per-request pattern: req B, admit B/E, queue B/E,
            # linger B/E, decode B/E, req E — 10 slotted stores
            rec.rec(flight.SPAN_B, nids[0], 0.0, i)
            rec.rec(flight.SPAN_B, nids[1], 0.0, i)
            rec.rec(flight.SPAN_E, nids[1], 0.0, i)
            rec.rec(flight.SPAN_B, nids[2], 0.0, i)
            rec.rec(flight.SPAN_E, nids[2], 0.0, i)
            rec.rec(flight.SPAN_B, nids[3], 0.0, i)
            rec.rec(flight.SPAN_E, nids[3], 0.0, i)
            rec.rec(flight.SPAN_B, nids[4], 0.0, i)
            rec.rec(flight.SPAN_E, nids[4], 0.0, i)
            rec.rec(flight.SPAN_E, nids[0], 7.0, i)
        best = min(best, (time.perf_counter_ns() - t0) / (iters * 10) / 1e3)
    return best


def spawn_shard(port=0):
    cmd = [sys.executable, SHARD_SERVER, "--port", str(port),
           "--world", "1", "--shard", "0"]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("BF_SHARD_READY"):
        raise RuntimeError(f"shard server failed to start: {line!r}")
    return proc, int(line.split()[1])


def main() -> int:
    if native.load() is None:
        print("slo-smoke: native runtime unavailable", file=sys.stderr)
        return 1

    # 0) overhead gate: the tracing hot path must stay microscopic
    us = overhead_gate()
    print(f"slo-smoke: per-request trace records cost {us:.2f} us each "
          f"(~{us * 10:.1f} us per traced request; gate: < 2.0 us/record)")
    check(us < 2.0, f"per-request trace record overhead {us:.2f} us "
          ">= 2 us/record")

    server, port = spawn_shard()
    endpoints = [("127.0.0.1", port)]
    os.environ.update({"BLUEFOG_CP_HOST": "127.0.0.1",
                       "BLUEFOG_CP_PORT": str(port),
                       "BLUEFOG_CP_WORLD": "1"})
    tmp = tempfile.mkdtemp(prefix="slo_smoke_")
    pub_dump = os.path.join(tmp, "pub_flight.json")
    pub = subprocess.Popen(
        [sys.executable, PUB_CHILD, "--port", str(port), "--shards", "4",
         "--elems", "20000", "--period-ms", "150", "--keep", "4",
         "--flight-dump", pub_dump, "--flight-rank", "1"],
        stdout=subprocess.DEVNULL, env=dict(os.environ))

    def model_fn(params, xs):
        return xs + params[0][0]

    sc = ServeClient(endpoints, model_fn=model_fn)
    bfrun_env = dict(os.environ)
    try:
        check(sc.wait_ready(timeout=20),
              "client never pulled a first snapshot")
        check(sc._ts is not None, "BLUEFOG_SLO set but the client owns "
              "no time-series store")

        def drive(seconds, rate=40.0):
            futs = []
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                try:
                    futs.append(sc.submit(np.zeros(2, np.float32)))
                except Exception:  # noqa: BLE001 — shed is fine here
                    pass
                time.sleep(1.0 / rate)
            for f in futs:
                try:
                    f.result(timeout=10)
                except Exception:  # noqa: BLE001
                    pass

        # 1) green traffic: objective declared, no alert
        drive(3.0)
        st = sc._ts.slo_status()
        check(st and st[0]["name"] == "serve_staleness",
              f"slo_status missing the declared objective: {st}")
        check(not st[0]["active"],
              f"staleness alert active before any fault: {st}")

        # 2) red path: per-op delay in THIS process only — pulls crawl,
        # the publisher child keeps committing, staleness breaches
        native.fault_arm(delay_ms=60)
        fired = False
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and not fired:
            drive(1.0)
            fired = any(o["active"] for o in sc._ts.slo_status())
        check(fired, "staleness burn-rate alert never fired under a "
              "60 ms/op pull delay (30 s deadline)")
        st = [o for o in sc._ts.slo_status() if o["active"]][0]
        print(f"slo-smoke: alert slo.{st['name']} FIRED (burn fast "
              f"{st['burn_fast']:.1f}x / slow {st['burn_slow']:.1f}x, "
              f"budget {st['budget_remaining']:.2f})")
        check(st["budget_remaining"] is not None
              and st["budget_remaining"] <= 0.0,
              f"budget not exhausted while red: {st}")

        # keep request traffic flowing while the external consumers are
        # probed — with no requests in the fast window the error rate is
        # 0 and the alert would (correctly) clear mid-check
        red_stop = threading.Event()

        def red_traffic():
            while not red_stop.is_set():
                try:
                    sc.submit(np.zeros(2, np.float32))
                except Exception:  # noqa: BLE001 — shed is fine here
                    pass
                red_stop.wait(0.03)

        rt = threading.Thread(target=red_traffic, daemon=True)
        rt.start()
        # let a publication carry the alert out, then check the consumers
        time.sleep(2.5)
        out = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.launcher", "--top",
             "--once", "--cp", f"127.0.0.1:{port}"],
            env=bfrun_env, capture_output=True, text=True, timeout=120)
        check(out.returncode == 0, f"bfrun --top --once failed: "
              f"{out.stderr}")
        check("SERVING SLO" in out.stdout and "serve_staleness"
              in out.stdout,
              f"--top missing the SERVING SLO section: {out.stdout!r}")
        out = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.launcher", "--status",
             "--strict", "--cp", f"127.0.0.1:{port}"],
            env=bfrun_env, capture_output=True, text=True, timeout=120)
        check(out.returncode == 2,
              f"--status --strict exit {out.returncode} != 2 with an "
              f"exhausted budget: {out.stdout} {out.stderr}")
        check("budget" in out.stderr,
              f"--strict findings missing the budget line: {out.stderr!r}")
        check(any(o["active"] for o in sc._ts.slo_status()),
              "alert flapped off while traffic was still red")
        alerts_blob = bytes(sc._cl.get_bytes(
            f"bf.alerts.{4096 + sc._cid}"))
        check(alerts_blob and b"serve_staleness"
              in zlib.decompress(alerts_blob),
              "published bf.alerts blob missing the active SLO alert")
        red_stop.set()
        rt.join(timeout=5)

        # 3) recovery: disarm -> the fast window drains -> alert clears
        native.fault_disarm()
        cleared = False
        deadline = time.perf_counter() + 25.0
        while time.perf_counter() < deadline and not cleared:
            drive(1.0)
            cleared = not any(o["active"] for o in sc._ts.slo_status())
        check(cleared, "alert never cleared within 25 s of disarming "
              "the fault")
        print("slo-smoke: alert CLEARED after recovery")
        time.sleep(2.5)  # one more publication: the alerts blob empties
        check(not bytes(sc._cl.get_bytes(f"bf.alerts.{4096 + sc._cid}")),
              "bf.alerts blob not emptied after the alert cleared")

        # 4) lineage: the committed version resolves to its train step
        ver = sc.version()
        lin = snap.read_lineage(sc._cl, ver)
        check(lin is not None, f"no lineage record for v{ver}")
        check(lin["step"] == ver and lin["ver"] == ver,
              f"lineage v{ver} does not resolve to its step: {lin}")

        # 5) merged trace: client + publisher rings -> one chrome trace
        # with >= 1 cross-process stripe flow pair
        drive(1.0)  # fresh pulls so both rings hold the same stripe keys
        pub.terminate()
        pub.wait(timeout=20)
        check(os.path.exists(pub_dump), "publisher child wrote no "
              "flight dump on SIGTERM")
        with open(pub_dump) as f:
            pub_doc = json.load(f)
        client_doc = flight.build_dump("slo-smoke")
        merged = flight.merge_dumps([client_doc, pub_doc])
        starts, finishes = {}, {}
        for e in merged:
            if e.get("cat") != "bf.flow":
                continue
            (starts if e["ph"] == "s" else finishes)[e["id"]] = e["pid"]
        pairs = [fid for fid, pid in starts.items()
                 if fid in finishes and finishes[fid] != pid]
        check(pairs, f"no cross-process stripe flow pair in the merged "
              f"trace ({len(starts)} starts, {len(finishes)} finishes)")
        rep = flight.analyze_serve(client_doc)
        check(rep and rep["requests"] > 0,
              "client ring holds no attributable request trace")
        print(f"slo-smoke: merged trace has {len(pairs)} cross-process "
              f"flow pair(s); {rep['requests']} request(s) attributed, "
              f"req p99 {rep['p99_us']:.0f} us")
    finally:
        native.fault_disarm()
        sc.close()
        if pub.poll() is None:
            pub.kill()
        server.terminate()
        try:
            server.wait(timeout=10)
        except subprocess.TimeoutExpired:
            server.kill()

    print("slo-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
