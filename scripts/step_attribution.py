#!/usr/bin/env python
"""Step-time attribution: where does each gossip step's wall time go?

Consumes flight-recorder dumps (``bf_flight_<rank>.json``, ``bfrun
--dump`` output — per-rank or merged) or a merged chrome trace, and prints
per rank the LAST complete optimizer step's phase breakdown — pack / wire
/ drain / fold (plus local compute, unpack, and the unattributed
remainder) — the per-edge deposit totals with byte-weighted wire-time
estimates, and the dominant phase/edge. This is the input the per-edge
plane planner needs (ROADMAP: on-device gossip fast path): the edges whose
wire+drain share dominates the step are the ones to move in-program.

Cross-rank (multiple dumps / a merged trace): deposit→drain flow pairs are
matched by id, reporting per-edge transit latency — the one number a
single rank cannot measure about itself.

``--live`` answers the per-edge half of the same questions WITHOUT a
dump: it reads every rank's streamed ``bf.ts.<rank>`` series (the live
telemetry plane, docs/observability.md) over a raw control-plane client
and prints per-edge bytes / bytes/s and deposit→drain transit latency
(p50/p99) from the live estimators plus cross-rank flow matching — the
numbers this script previously only produced postmortem.

Usage:
    python scripts/step_attribution.py bf_flight_0.json [bf_flight_1.json ...]
    python scripts/step_attribution.py bf_flight_dump/merged.json
    python scripts/step_attribution.py --live --cp HOST:PORT [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bluefog_tpu.runtime import flight  # noqa: E402

# chrome ph -> flight kind (for merged-trace input)
_PH_KIND = {"B": flight.SPAN_B, "E": flight.SPAN_E, "i": flight.INSTANT,
            "C": flight.COUNTER, "s": flight.FLOW_S, "f": flight.FLOW_F}
# legacy timeline span names -> the flight vocabulary
_TIMELINE_NAMES = {"STEP": "opt.step", "PACK": "opt.pack",
                   "UNPACK": "opt.unpack"}


def _docs_from_chrome(events: list) -> dict:
    """Regroup a merged chrome trace into per-pid pseudo-dumps that
    :func:`flight.analyze_dump` understands."""
    per_pid: dict = {}
    for e in events:
        ph = e.get("ph")
        kind = _PH_KIND.get(ph)
        if kind is None:
            continue
        pid = e.get("pid", 0)
        doc = per_pid.setdefault(pid, {"names": [], "_ids": {},
                                       "events": {"kind": [], "name": [],
                                                  "t_wall_us": [], "a": [],
                                                  "b": []}})
        name = e.get("name", "")
        name = _TIMELINE_NAMES.get(name, name)
        if ph == "E" and not name:
            # timeline E events carry no name; un-analyzable — skip
            continue
        nid = doc["_ids"].get(name)
        if nid is None:
            nid = doc["_ids"][name] = len(doc["names"])
            doc["names"].append(name)
        args = e.get("args", {})
        a = args.get("a", args.get("bytes", args.get("value", 0.0)))
        b = e.get("id", args.get("b", 0))
        ev = doc["events"]
        ev["kind"].append(kind)
        ev["name"].append(nid)
        ev["t_wall_us"].append(float(e.get("ts", 0.0)))
        ev["a"].append(float(a or 0.0))
        ev["b"].append(int(b or 0))
    for doc in per_pid.values():
        doc.pop("_ids")
    return per_pid


def load(paths) -> dict:
    """{rank: dump-doc} from flight dumps and/or merged chrome traces."""
    docs: dict = {}
    for p in paths:
        with open(p) as f:
            data = json.load(f)
        if isinstance(data, list):  # merged chrome trace
            for pid, doc in _docs_from_chrome(data).items():
                docs[pid] = doc
        elif "events" in data:      # flight dump
            docs[data.get("meta", {}).get("rank", len(docs))] = data
        else:
            raise ValueError(f"{p}: neither a flight dump nor a chrome "
                             "trace")
    return docs


def flow_pairs(docs: dict) -> dict:
    """Cross-rank deposit→drain transit latency per edge: flow id matched
    between any rank's FLOW_S and any rank's FLOW_F."""
    starts: dict = {}
    finishes: dict = {}
    for doc in docs.values():
        names = doc.get("names", [])
        ev = doc.get("events", {})
        for k, n, t, a, b in zip(ev["kind"], ev["name"], ev["t_wall_us"],
                                 ev["a"], ev["b"]):
            name = names[n] if 0 <= n < len(names) else ""
            if k == flight.FLOW_S and name.startswith("edge."):
                starts[b] = (name[5:].replace(".", "->"), t, a)
            elif k == flight.FLOW_F:
                finishes[b] = t
    per_edge: dict = {}
    for fid, (edge, t0, nbytes) in starts.items():
        t1 = finishes.get(fid)
        if t1 is None:
            continue
        d = per_edge.setdefault(edge, {"pairs": 0, "bytes": 0.0,
                                       "transit_us": []})
        d["pairs"] += 1
        d["bytes"] += nbytes
        d["transit_us"].append(t1 - t0)
    return per_edge


def live_report(cl, world: int) -> dict:
    """Per-edge live attribution from the streamed series: bytes,
    bytes/s, deposits, transit p50/p99 (rank-local estimators merged
    with cross-rank flow matching) plus each rank's step cadence and
    consensus gauges — the dump-free answer."""
    from bluefog_tpu.runtime import timeseries as ts

    acc = ts.HistoryAccumulator()
    for r in range(world):
        doc = ts.read_rank(cl, r)
        if doc is not None:
            acc.update(r, doc)
    edges: dict = {}
    for r, per in sorted(acc.edges.items()):
        for edge, st in per.items():
            cur = edges.setdefault(edge, {"bytes": 0.0, "deposits": 0,
                                          "bps": 0.0})
            cur["bytes"] += st.get("bytes") or 0.0
            cur["deposits"] += st.get("deposits") or 0
            cur["bps"] += st.get("bps") or 0.0
    for edge, cur in edges.items():
        p50, p99 = acc.edge_transit(edge)
        cur["transit_p50_us"] = p50
        cur["transit_p99_us"] = p99
    ranks = {}
    for r in sorted(acc.meta):
        ranks[str(r)] = {
            "step": acc.latest(r, "opt.step"),
            "step_rate": acc.latest(r, "opt.step.rate"),
            "consensus_dist": acc.latest(r, "opt.consensus_dist"),
            "mixing_rate": acc.latest(r, "opt.mixing_rate"),
            "alerts": acc.alerts.get(r, []),
        }
    return {"schema_version": 1, "live": True, "world": world,
            "ranks": ranks, "edges": edges,
            "silent": acc.silent_ranks(world)}


def _live(args) -> int:
    from bluefog_tpu.launcher import _cp_address, _discover_world, \
        _raw_client

    addr = _cp_address(args, "--live")
    if addr is None:
        return 1
    cl = _raw_client(addr, what="--live")
    if cl is None:
        return 1
    try:
        rep = live_report(cl, _discover_world(cl))
    finally:
        cl.close()
    if args.json:
        print(json.dumps(rep))
        return 0
    print(f"== live attribution ({rep['world']} rank(s)) ==")
    for r, st in rep["ranks"].items():
        line = f"  rank {r}: step {st['step'] or 0:.0f}"
        if st["step_rate"] is not None:
            line += f", {st['step_rate']:.2f} step/s"
        if st["consensus_dist"] is not None:
            line += f", consensus {st['consensus_dist']:.3g}"
        if st["mixing_rate"] is not None:
            line += f", mixing {st['mixing_rate']:.3f}"
        for a in st["alerts"]:
            line += f"  [ALERT:{a['name']}]"
        print(line)
    if rep["silent"]:
        print(f"  silent rank(s): {rep['silent']}")
    if rep["edges"]:
        print("  edges (live estimators + cross-rank flow matching):")
        for edge in sorted(rep["edges"]):
            e = rep["edges"][edge]
            p50 = e.get("transit_p50_us")
            print(f"    {edge:<8} {e['deposits']:5d} deposits, "
                  f"{e['bytes'] / 1e6:8.2f} MB, {e['bps'] / 1e6:7.2f} "
                  "MB/s, median transit "
                  + (f"{p50 / 1e3:.2f} ms" if p50 is not None else "-"))
    else:
        print("  no per-edge flow data streamed yet (hosted window "
              "deposits feed the estimators)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("files", nargs="*",
                    help="flight dumps and/or merged chrome traces")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (one JSON object)")
    ap.add_argument("--live", action="store_true",
                    help="read the streamed bf.ts.<rank> series instead "
                         "of dumps (needs --cp or BLUEFOG_CP_* env)")
    ap.add_argument("--cp", type=str, default=None,
                    metavar="HOST:PORT[,HOST:PORT...]",
                    help="control-plane address(es) for --live")
    args = ap.parse_args(argv)
    if args.live:
        return _live(args)
    if not args.files:
        ap.error("files are required unless --live is given")
    docs = load(args.files)
    reports = {}
    for rank in sorted(docs):
        rep = flight.analyze_dump(docs[rank])
        if rep is not None:
            reports[rank] = rep
    if not reports:
        print("no complete optimizer step found in the input "
              "(did the job run a window optimizer?)", file=sys.stderr)
        return 1
    pairs = flow_pairs(docs)
    # sharded-window rotation (ISSUE r17): the win.shard_factor gauge
    # rides every dump's metrics snapshot; surfacing it here keeps the
    # per-edge byte totals honest — shard-sized flow events ARE the real
    # wire cost, and a consumer (plan.load_attribution overrides) can
    # tell a 1/S-sized edge from a small model. Additive, schema-stable
    # field: schema_version stays 1 and absent means unsharded.
    shard_factor = {
        str(r): int(doc.get("metrics", {}).get("gauges", {}).get(
            "win.shard_factor", 1) or 1)
        for r, doc in docs.items()}
    if args.json:
        # --json is a MACHINE interface now: the per-edge plane planner
        # consumes it (bluefog_tpu.ops.plan.load_attribution). The literal
        # must match plan.ATTRIBUTION_SCHEMA_VERSION — kept inline so this
        # script stays importable without jax; a test pins the pair.
        print(json.dumps({"schema_version": 1,
                          "shard_factor": shard_factor,
                          "ranks": {str(r): rep
                                    for r, rep in reports.items()},
                          "flow_pairs": {e: {**d, "transit_us":
                                             sorted(d["transit_us"])}
                                         for e, d in pairs.items()}}))
        return 0
    for rank, rep in reports.items():
        print(f"== rank {rank} ==")
        sf = shard_factor.get(str(rank), 1)
        if sf > 1:
            print(f"  sharded window rotation: factor {sf} "
                  "(per-edge bytes below are shard-sized)")
        print(flight.format_report(rep))
        # the critical path: the dominant attributed phase and edge
        dom_phase = max(rep["phases"], key=lambda p: rep["phases"][p])
        line = (f"  dominant phase: {dom_phase} "
                f"({rep['phases'][dom_phase] * 1e3:.3f} ms of "
                f"{rep['step_sec'] * 1e3:.3f} ms)")
        if rep["edges"]:
            dom_edge = max(rep["edges"],
                           key=lambda e: rep["edges"][e]["bytes"])
            line += f"; dominant edge: {dom_edge}"
        print(line)
    if pairs:
        print("== cross-rank deposit→drain transit (flow pairs) ==")
        for edge in sorted(pairs):
            d = pairs[edge]
            ts = sorted(d["transit_us"])
            med = ts[len(ts) // 2]
            print(f"  {edge:<8} {d['pairs']:4d} pairs, "
                  f"{d['bytes'] / 1e6:8.2f} MB, median transit "
                  f"{med / 1e3:.2f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
