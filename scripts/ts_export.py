#!/usr/bin/env python
"""Export the live time-series plane (OpenMetrics / JSON lines).

Reads every rank's ``bf.ts.<rank>`` delta stream over a raw control-plane
client (the ``bfrun --status`` pattern — no jax, no mesh join) and writes
the accumulated history in one of two machine formats:

* ``--format jsonl`` (default): one sample per line —
  ``{"ts": <epoch sec>, "rank": r, "series": name, "value": v}`` —
  ready for jq / a columnar loader / pandas.
* ``--format openmetrics``: the OpenMetrics text format with explicit
  millisecond timestamps per sample (``# TYPE``/``# HELP`` per family,
  terminated by ``# EOF``), ready for a backfill-capable scraper.

Per-edge estimator summaries export as ``bf_edge_*`` samples labeled with
the edge. Serve clients' streams (``bf.ts.<4096 + cid>`` — the ``slo.*``
burn-rate/budget gauges and ``trace.*`` request counters of docs/slo.md)
ride along automatically; their rank label is ``4096 + cid``. ``--watch N`` keeps polling every N seconds and appending
(jsonl only); the default is one pass over whatever history the ranks
currently publish (late joiners still get the downsampled tiers — the
publication carries them periodically).

Usage:
    python scripts/ts_export.py --cp HOST:PORT[,HOST:PORT...] [--out F]
        [--format jsonl|openmetrics] [--watch SEC] [--world N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bluefog_tpu.runtime import timeseries as ts  # noqa: E402


def _client(spec: str):
    from bluefog_tpu.launcher import _raw_client
    from bluefog_tpu.runtime.router import parse_endpoints

    return _raw_client(parse_endpoints(spec), what="ts_export")


def _poll(cl, acc: ts.HistoryAccumulator, world: int) -> None:
    for r in range(world):
        doc = ts.read_rank(cl, r)
        if doc is not None:
            acc.update(r, doc)
    # serve-client band (bf.ts.<SERVE_TS_RANK_BASE + cid>): the slo.* /
    # trace.* request-path families publish here, not at trainer ranks
    try:
        from bluefog_tpu.serving.snapshot import live_client_ids
        cids = live_client_ids(cl)
    except (OSError, RuntimeError):
        cids = []
    for cid in cids:
        doc = ts.read_rank(cl, ts.SERVE_TS_RANK_BASE + cid)
        if doc is not None:
            acc.update(ts.SERVE_TS_RANK_BASE + cid, doc)


def _metric_name(series: str) -> str:
    out = []
    for ch in series:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    return "bf_" + "".join(out)


def emit_jsonl(acc: ts.HistoryAccumulator, out, seen: set) -> int:
    """Append every not-yet-written sample; returns the count written."""
    n = 0
    for (rank, name), hist in sorted(acc.series.items()):
        for t, v in hist:
            key = (rank, name, t)
            if key in seen:
                continue
            seen.add(key)
            out.write(json.dumps({"ts": t, "rank": rank, "series": name,
                                  "value": v}) + "\n")
            n += 1
    for rank, edges in sorted(acc.edges.items()):
        for edge, st in sorted(edges.items()):
            key = (rank, f"edge:{edge}", st.get("bytes", 0.0))
            if key in seen:
                continue
            seen.add(key)
            out.write(json.dumps({"ts": acc.meta[rank]["ts"], "rank": rank,
                                  "series": "edge", "edge": edge, **st})
                      + "\n")
            n += 1
    return n


def emit_openmetrics(acc: ts.HistoryAccumulator, out) -> int:
    """Full-history OpenMetrics dump (one family per series name)."""
    n = 0
    by_name: dict = {}
    for (rank, name), hist in acc.series.items():
        by_name.setdefault(name, []).append((rank, hist))
    for name in sorted(by_name):
        m = _metric_name(name)
        out.write(f"# TYPE {m} gauge\n")
        out.write(f"# HELP {m} bluefog live series {name}\n")
        for rank, hist in sorted(by_name[name]):
            for t, v in hist:
                out.write(f'{m}{{rank="{rank}"}} {v:g} {int(t * 1000)}\n')
                n += 1
    for rank, edges in sorted(acc.edges.items()):
        for edge, st in sorted(edges.items()):
            for field in ("bps", "bytes", "deposits", "p50_us", "p99_us"):
                v = st.get(field)
                if v is None:
                    continue
                m = f"bf_edge_{field}"
                out.write(f'{m}{{rank="{rank}",edge="{edge}"}} {v:g}\n')
                n += 1
    out.write("# EOF\n")
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--cp", type=str,
                    default=os.environ.get("BLUEFOG_CP_HOSTS")
                    or (f"{os.environ.get('BLUEFOG_CP_HOST')}:"
                        f"{os.environ.get('BLUEFOG_CP_PORT')}"
                        if os.environ.get("BLUEFOG_CP_HOST")
                        and os.environ.get("BLUEFOG_CP_PORT") else None),
                    help="control-plane endpoint(s) "
                         "(default: BLUEFOG_CP_HOSTS / _CP_HOST+_CP_PORT)")
    ap.add_argument("--out", type=str, default="-",
                    help="output file (default stdout)")
    ap.add_argument("--format", choices=("jsonl", "openmetrics"),
                    default="jsonl")
    ap.add_argument("--watch", type=float, default=0.0, metavar="SEC",
                    help="keep polling every SEC seconds and appending "
                         "new samples (jsonl only; 0 = one pass)")
    ap.add_argument("--world", type=int, default=0,
                    help="rank count (default: discovered from the KV)")
    args = ap.parse_args(argv)
    if not args.cp:
        print("ts_export: control-plane address unknown; pass --cp or set "
              "BLUEFOG_CP_HOST/BLUEFOG_CP_PORT", file=sys.stderr)
        return 1
    cl = _client(args.cp)
    if cl is None:
        return 1
    out = sys.stdout if args.out == "-" else open(args.out, "w")
    acc = ts.HistoryAccumulator()
    seen: set = set()
    try:
        from bluefog_tpu.launcher import _discover_world

        world = args.world or _discover_world(cl)
        _poll(cl, acc, world)
        if args.format == "openmetrics":
            n = emit_openmetrics(acc, out)
            print(f"ts_export: {n} samples ({world} rank(s))",
                  file=sys.stderr)
            return 0 if n else 1
        n = emit_jsonl(acc, out, seen)
        while args.watch > 0:
            out.flush()
            time.sleep(args.watch)
            _poll(cl, acc, world)
            n += emit_jsonl(acc, out, seen)
        print(f"ts_export: {n} samples ({world} rank(s))", file=sys.stderr)
        return 0 if n else 1
    except KeyboardInterrupt:
        return 0
    finally:
        if out is not sys.stdout:
            out.close()
        cl.close()


if __name__ == "__main__":
    sys.exit(main())
