#!/usr/bin/env python
"""Per-request serving-latency attribution (docs/slo.md).

Answers "where did the request's milliseconds go?" from the request-path
traces the serving plane records when ``BLUEFOG_TRACE_SERVE=1``: every
request's admission, queue wait, batch linger, decode, and reply phases
plus the poller's stripe-group pulls, carved into disjoint buckets (the
queue time a swap pull was blocking is attributed to ``swap_blocked``,
not ``queue``).

Two modes:

* **dump mode** (``--dump FILE_OR_DIR ...``): replay flight-recorder
  dump files (``flight_<r>.json`` from ``bfrun --dump``, or local
  ``bf_flight_<rank>.json``) through the span analyzer and print one
  attribution table per dump that recorded requests.
* **live mode** (``--cp HOST:PORT[,...]``): read the serve clients'
  published time-series streams (``bf.ts.<4096 + cid>``) and the
  serving plane's lineage records over a raw control-plane client — no
  jax, no mesh join — and print the current phase percentiles, SLO
  burn-rate state, and the committed snapshot's provenance.

``--json`` emits one machine-readable document (``schema_version: 1``)
instead of the tables.

Usage:
    python scripts/serve_attribution.py --dump ./flight_dump/
    python scripts/serve_attribution.py --cp 127.0.0.1:45607 [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bluefog_tpu.runtime import flight  # noqa: E402


def _dump_paths(specs):
    out = []
    for spec in specs:
        p = Path(spec)
        if p.is_dir():
            out.extend(sorted(p.glob("flight_*.json")))
            out.extend(sorted(p.glob("bf_flight_*.json")))
        elif p.exists():
            out.append(p)
        else:
            print(f"serve_attribution: no such dump: {spec}",
                  file=sys.stderr)
    return out


def analyze_dumps(paths):
    """-> [(path, rank, report)] for every dump that recorded requests."""
    reports = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            print(f"serve_attribution: unreadable dump {path} ({exc})",
                  file=sys.stderr)
            continue
        rep = flight.analyze_serve(doc)
        if rep is not None:
            reports.append((str(path), doc.get("meta", {}).get("rank"),
                            rep))
    return reports


def live_report(cl):
    """The live view: per-client phase gauges + SLO state from the
    published streams, plus the committed snapshot's lineage record."""
    from bluefog_tpu.runtime import timeseries as ts
    from bluefog_tpu.serving import snapshot as snap

    out = {"clients": [], "lineage": None, "serve": None}
    try:
        st = snap.read_serve_status(cl)
    except (OSError, RuntimeError):
        st = None
    if st:
        out["serve"] = st
        lin = snap.read_lineage(cl, st["version"])
        if lin:
            out["lineage"] = lin
    acc = ts.HistoryAccumulator()
    for cid in snap.live_client_ids(cl):
        r = ts.SERVE_TS_RANK_BASE + cid
        doc = ts.read_rank(cl, r)
        if doc is None:
            continue
        acc.update(r, doc)
        row = {"cid": cid, "phases": {}, "slo": {}}
        for p in flight.SERVE_PHASES:
            p50 = acc.latest(r, f"slo.phase.{p}.p50_us")
            p99 = acc.latest(r, f"slo.phase.{p}.p99_us")
            if p99 is not None:
                row["phases"][p] = {"p50_us": p50, "p99_us": p99}
        for name in ("slo.request_p50_us", "slo.request_p99_us",
                     "slo.staleness_p99_ver", "slo.requests.rate",
                     "slo.shed.rate", "trace.requests"):
            v = acc.latest(r, name)
            if v is not None:
                row[name] = v
        for (rank, name) in sorted(acc.series):
            if rank != r or not name.startswith("slo.budget."):
                continue
            kind = name[len("slo.budget."):]
            row["slo"][kind] = {
                "budget_remaining": acc.latest(r, name),
                "burn_fast": acc.latest(r, f"slo.burn.{kind}.fast"),
                "burn_slow": acc.latest(r, f"slo.burn.{kind}.slow"),
            }
        out["clients"].append(row)
    return out


def _print_report(rep, title):
    print(title)
    print(f"  {rep['requests']} request(s), req p50/p99 "
          f"{rep['p50_us']:.0f}/{rep['p99_us']:.0f} us, "
          f"{rep['pulls']} snapshot pull(s), "
          f"{rep['failovers']} failover(s)")
    print(f"  {'phase':>14} {'p50 us':>10} {'p99 us':>10} {'mean us':>10}")
    for p in flight.SERVE_PHASES:
        row = rep["phases"].get(p)
        if row is None:
            continue
        print(f"  {p:>14} {row['p50_us']:>10.0f} {row['p99_us']:>10.0f} "
              f"{row['mean_us']:>10.0f}")
    for ep, row in sorted(rep.get("endpoints", {}).items()):
        print(f"  endpoint {ep}: {row['pulls']} pull(s), "
              f"{row['bytes'] / 1e6:.1f} MB, p50/p99 "
              f"{row['p50_us']:.0f}/{row['p99_us']:.0f} us")


def _print_live(doc):
    st = doc.get("serve")
    if st:
        print(f"serving plane: snapshot v{st['version']} "
              f"(step {st['pub_step']}), "
              f"{st['clients_live']}/{st['clients_total']} client(s) live")
    lin = doc.get("lineage")
    if lin:
        print(f"  lineage v{lin['ver']}: train step {lin['step']}, "
              f"published by rank {lin['rank']}, codec "
              f"{lin.get('codec') or 'none'}")
    for row in doc.get("clients", []):
        print(f"  client {row['cid']}: "
              f"req p50/p99 {row.get('slo.request_p50_us') or 0:.0f}/"
              f"{row.get('slo.request_p99_us') or 0:.0f} us, "
              f"{row.get('trace.requests') or 0:.0f} traced")
        if row["phases"]:
            attr = "  ".join(
                f"{p} {v['p50_us'] or 0:.0f}/{v['p99_us']:.0f}"
                for p, v in row["phases"].items())
            print(f"    phases p50/p99 us: {attr}")
        for kind, s in sorted(row["slo"].items()):
            b = s["budget_remaining"]
            print(f"    {kind}: budget "
                  f"{(b if b is not None else 1.0) * 100:.1f}%  burn "
                  f"{s['burn_fast'] or 0:.2f}x/{s['burn_slow'] or 0:.2f}x")
    if not doc.get("clients"):
        print("  (no serve client is publishing SLO/trace series — set "
              "BLUEFOG_TRACE_SERVE=1 / BLUEFOG_SLO on the client)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--dump", nargs="+", metavar="FILE_OR_DIR",
                    help="flight dump file(s)/dir(s) to attribute")
    ap.add_argument("--cp", type=str,
                    default=os.environ.get("BLUEFOG_CP_HOSTS")
                    or (f"{os.environ.get('BLUEFOG_CP_HOST')}:"
                        f"{os.environ.get('BLUEFOG_CP_PORT')}"
                        if os.environ.get("BLUEFOG_CP_HOST")
                        and os.environ.get("BLUEFOG_CP_PORT") else None),
                    help="control-plane endpoint(s) for live mode")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output (schema_version 1)")
    args = ap.parse_args(argv)

    if args.dump:
        reports = analyze_dumps(_dump_paths(args.dump))
        if args.json:
            print(json.dumps({
                "schema_version": 1, "mode": "dump",
                "reports": [{"path": p, "rank": r, **rep}
                            for p, r, rep in reports]}))
        else:
            for p, r, rep in reports:
                _print_report(rep, f"{p} (rank {r}):")
        if not reports:
            print("serve_attribution: no dump recorded request spans "
                  "(was BLUEFOG_TRACE_SERVE=1 on the client?)",
                  file=sys.stderr)
            return 1
        return 0

    if not args.cp:
        print("serve_attribution: pass --dump FILES or --cp HOST:PORT",
              file=sys.stderr)
        return 2
    from bluefog_tpu.launcher import _raw_client
    from bluefog_tpu.runtime.router import parse_endpoints

    cl = _raw_client(parse_endpoints(args.cp), what="serve_attribution")
    if cl is None:
        return 1
    try:
        doc = live_report(cl)
        if args.json:
            print(json.dumps({"schema_version": 1, "mode": "live", **doc}))
        else:
            _print_live(doc)
    finally:
        cl.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
