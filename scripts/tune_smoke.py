#!/usr/bin/env python
"""Self-tuning-controller smoke test (`make tune-smoke`).

A 4-rank in-process job with the control plane + hosted window plane
forced on, asserting the acceptance surface of the online performance
controller (docs/self_tuning.md) end to end:

  * **healthy fleet => zero decisions**: with no slow edges, no
    stragglers, and no alerts, repeated controller ticks apply nothing
    (``tune.decisions`` stays 0 and the demotion set stays empty);
  * **asymmetric edge delay** (``BLUEFOG_CP_FAULT delay_edges``) is
    really armed: the deposit batch covering the delayed edge ships
    measurably late, and the slow edge's transit pressure escalates its
    wire codec one ladder rung (``Window.set_edge_codec``, receiver
    untouched) within a few ticks;
  * **injected straggler => demotion within N ticks**: a rank whose
    published ``opt.step`` gauge trails the fleet is demoted to its
    ``keep_in`` fastest in-edges, the decision rides the epoch-fenced
    ``bf.tune.demoted`` document, and the membership epoch is bumped so
    every optimizer re-plans at the same boundary;
  * **numpy-oracle parity**: the optimizers' healed receive weights
    under the demotion equal the column-renormalized weight matrix
    computed independently in numpy (total-preserving, convex), and the
    healed send table drops exactly the demoted edges;
  * **recovery => promotion**: once the straggler catches up, the
    demotion is lifted and the healed tables return to the original
    uniform weights EXACTLY (the demote -> promote round-trip);
  * the decision trail (``bf.tune.<rank>``) records every move and
    ``bfrun --top`` renders the SELF-TUNER section from it.

Exits non-zero (with a message) on any violated assertion.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

_s = socket.socket()
_s.bind(("127.0.0.1", 0))
PORT = _s.getsockname()[1]
_s.close()

os.environ.update({
    "BLUEFOG_CP_HOST": "127.0.0.1",
    "BLUEFOG_CP_PORT": str(PORT),
    "BLUEFOG_CP_WORLD": "1",
    "BLUEFOG_CP_RANK": "0",
    "BLUEFOG_WIN_HOST_PLANE": "1",
    "BLUEFOG_METRICS_INTERVAL": "1",
    "BLUEFOG_TS_INTERVAL": "1",
    # the knob is ON (the demotion consumers are live) but the passive
    # heartbeat/step funnels are interval-gated out of the way — the
    # harness drives tick() with a synthetic clock for determinism
    "BLUEFOG_TUNE": "1",
    "BLUEFOG_TUNE_INTERVAL": "3600",
    # deterministic bandwidth asymmetry: deposits covering 0->1 ship late
    "BLUEFOG_CP_FAULT": "delay_edges=0>1:60",
})

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu import optimizers as O  # noqa: E402
from bluefog_tpu.ops import codec as codec_mod  # noqa: E402
from bluefog_tpu.ops import windows as win_mod  # noqa: E402
from bluefog_tpu.runtime import control_plane as cp  # noqa: E402
from bluefog_tpu.runtime import heartbeat as hb  # noqa: E402
from bluefog_tpu.runtime import metrics as mx  # noqa: E402
from bluefog_tpu.runtime import timeseries as ts  # noqa: E402
from bluefog_tpu.runtime import tuner  # noqa: E402
from bluefog_tpu.runtime.state import _global_state  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORLD = 4

# fast-hysteresis decision table: seconds-scale sustained windows so the
# smoke converges in a handful of synthetic-clock ticks; slow_ratio off so
# the codec lever is driven by the transit trigger alone (deterministic)
RULES = dict(tuner.DEFAULT_RULES, slow_ratio=0.0, transit_p99_ms=10.0,
             slow_for=1.0, straggler_for=1.0, dwell=2.0, keep_in=1.0)


def check(cond, msg):
    if not cond:
        print(f"tune-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def publish_snapshot(cl, rank: int, step: float) -> None:
    """Publish a peer metrics snapshot (the straggler injection: the
    step-counter-spread detector consumes exactly these gauges)."""
    cl.put_bytes(mx._metrics_key(rank), mx.pack_snapshot({
        "meta": {"schema": 1, "rank": rank, "inc": 0, "ts": time.time()},
        "counters": {}, "gauges": {"opt.step": float(step)}, "hists": {}}))


def main() -> int:
    bf.init(devices=jax.devices("cpu")[:WORLD])
    st = _global_state()
    cl = cp.client()

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zloss,
                                        window_prefix="tune.wp")
    state = opt.init({"w": jnp.ones((64,), jnp.float32)})
    for _ in range(6):
        state, _ = opt.step(state, jnp.zeros((WORLD, 1), jnp.float32))
    s0 = mx.gauge("opt.step").value

    # one controller, world-4 sensor view, harness-pinned decision table;
    # installed as the singleton so module consumers share its state
    tn = tuner.Tuner(0, WORLD, rules=RULES)
    tuner._singleton = tn
    t = time.time()

    def tick():
        # production order (heartbeat tail): sample the telemetry plane,
        # then tick the controller off the freshened store
        ts.maybe_sample(force=True, publish=True)
        nonlocal t
        t += 1.0
        return tn.tick(cl, t)

    # 1) healthy fleet: N ticks, zero decisions, nothing demoted
    for _ in range(3):
        check(tick() == [], "controller applied a decision on a healthy "
              "fleet")
    check(mx.counter("tune.decisions").value == 0,
          "tune.decisions moved on a healthy fleet")
    check(tuner.demoted_edges() == frozenset(),
          "demotion set non-empty on a healthy fleet")
    print("healthy fleet: 0 decisions over 3 ticks — ok")

    # 2) asymmetric delay + slow-edge codec escalation. Split-ownership
    # flow pair (the test_metrics harness): the origin half owns rank 0
    # and deposits over the REAL server wire — where the delay_edges
    # clause injects — and the owner half drains late, so the 0->1
    # transit estimator carries the pressure the codec lever keys on.
    x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((WORLD, 256)))
    orig_owned = cp.owned_ranks
    try:
        cp.owned_ranks = lambda devs, pid: [0]
        check(bf.win_create(x, "tune.flow", zero_init=True),
              "win_create failed")
        cp.owned_ranks = lambda devs, pid: [1]
        win_b = win_mod.Window("tune.flow", np.ones((WORLD, 256), np.float32),
                               zero_init=True)
        slowest_put = 0.0
        for _ in range(4):
            t0 = time.monotonic()
            bf.win_put(x, "tune.flow")
            slowest_put = max(slowest_put, time.monotonic() - t0)
            time.sleep(0.03)  # drain late: deposit->drain transit > 10 ms
            with win_b.state_mu:
                win_b._drain_deposits()
    finally:
        cp.owned_ranks = orig_owned
    check(slowest_put >= 0.055,
          f"delay_edges=0>1:60 not armed: slowest win_put "
          f"{slowest_put * 1e3:.1f} ms")
    win_o = st.windows["tune.flow"]
    for i in range(4):
        applied = tick()
        if any(d.lever == "codec" and d.target == (0, 1) for d in applied):
            break
    check(tn._level.get((0, 1), 0) >= 1,
          "slow edge 0->1 never escalated off the raw codec")
    check((0, 1) in win_o._edge_codec and
          win_o._edge_codec[(0, 1)].cid == codec_mod.CODEC_INT8,
          f"edge codec not installed on the window: {win_o._edge_codec}")
    check(mx.counter("win.codec.edge_switches").value >= 1,
          "edge-switch counter never moved")
    print(f"slow edge 0->1 escalated to int8 after {i + 1} tick(s) — ok")

    # 3) injected straggler -> demotion within N ticks, epoch-fenced
    ep0 = hb.membership_epoch()
    demoted = frozenset()
    for i in range(8):
        for r in (1, 2):
            publish_snapshot(cl, r, s0)
        publish_snapshot(cl, 3, s0 - 10)  # rank 3 trails the fleet
        tick()
        demoted = tuner.demoted_edges()
        if demoted:
            break
    check(demoted, "straggler was never demoted (8 ticks)")
    check(i + 1 <= 4, f"demotion took {i + 1} ticks (bound: 4)")
    check(all(dst == 3 for _, dst in demoted),
          f"demoted edges not all into rank 3: {sorted(demoted)}")
    in3 = set(win_o.in_neighbors[3])
    check(len(demoted) == len(in3) - int(RULES["keep_in"]),
          f"expected keep_in={RULES['keep_in']:g} of {sorted(in3)} kept, "
          f"demoted {sorted(demoted)}")
    doc = json.loads(bytes(cl.get_bytes(tuner.DEMOTE_KEY)).decode())
    check({tuple(e) for e in doc["edges"]} == set(demoted),
          f"bf.tune.demoted document disagrees: {doc}")
    check(hb.membership_epoch() > ep0,
          "membership epoch not bumped by the demotion")
    print(f"straggler demoted after {i + 1} tick(s): {sorted(demoted)} — ok")

    # 4) numpy-oracle parity: healed receive weights == the column-
    # renormalized uniform weight matrix, healed send table drops exactly
    # the demoted edges
    W = np.zeros((WORLD, WORLD))
    for r in range(WORLD):
        w = 1.0 / (len(win_o.in_neighbors[r]) + 1)
        W[r, r] = w
        for s in win_o.in_neighbors[r]:
            W[s, r] = w
    Wd = W.copy()
    for s, d in demoted:
        Wd[s, d] = 0.0
    for d in {d for _, d in demoted}:
        Wd[:, d] *= W[:, d].sum() / Wd[:, d].sum()
    sw, nw = O._healed_recv_weights(win_o, set(), None, None, demoted)
    for r in range(WORLD):
        check(abs(sw[r] - Wd[r, r]) < 1e-12, f"self weight rank {r}: "
              f"{sw[r]} vs oracle {Wd[r, r]}")
        oracle_in = {s: Wd[s, r] for s in win_o.in_neighbors[r]
                     if (s, r) not in demoted}
        check(set(nw[r]) == set(oracle_in) and
              all(abs(nw[r][s] - oracle_in[s]) < 1e-12 for s in oracle_in),
              f"in-weights rank {r}: {nw[r]} vs oracle {oracle_in}")
        check(abs(sw[r] + sum(nw[r].values()) - 1.0) < 1e-12,
              f"column {r} total not preserved")
    send = O._healed_send_table(win_o, set(), None, demoted)
    for s, d in demoted:
        check(d not in send[s], f"demoted edge {s}->{d} still in the "
              "send table")
    print("healed tables match the numpy renormalization oracle — ok")

    # 5) recovery -> promotion, demote -> promote round-trip exact
    for i in range(8):
        for r in (1, 2, 3):
            publish_snapshot(cl, r, s0)  # rank 3 caught up
        tick()
        if not tuner.demoted_edges():
            break
    check(tuner.demoted_edges() == frozenset(),
          "recovered straggler was never promoted (8 ticks)")
    sw2, nw2 = O._healed_recv_weights(win_o, set(), None, None, frozenset())
    for r in range(WORLD):
        u = 1.0 / (len(win_o.in_neighbors[r]) + 1)
        check(sw2[r] == u and
              nw2[r] == {s: u for s in win_o.in_neighbors[r]},
              f"round-trip weights rank {r} not restored exactly")
    print(f"straggler promoted after {i + 1} tick(s), weights restored "
          "exactly — ok")

    # 6) decision trail + --top rendering
    trail = json.loads(bytes(cl.get_bytes(
        tuner.TUNE_KEY_FMT.format(rank=0))).decode())
    acts = {(d["lever"], d["action"]) for d in trail["decisions"]
            if d["status"] == "applied"}
    check({("codec", "escalate"), ("indegree", "demote"),
           ("indegree", "promote")} <= acts,
          f"decision trail incomplete: {sorted(acts)}")
    # the transit pressure persists across the phases, so the slow edge
    # may have climbed past int8 by now — any raised rung is correct
    check(trail["levels"].get("0>1") in ("int8", "topk:0.01"),
          f"trail levels wrong: {trail['levels']}")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--top", "--once"],
        env=dict(os.environ), capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"bfrun --top failed: {out.stderr}")
    check("SELF-TUNER" in out.stdout,
          f"--top missing the SELF-TUNER section: {out.stdout!r}")
    print("decision trail published and rendered by --top — ok")

    opt.free()
    bf.shutdown()
    print("tune-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
