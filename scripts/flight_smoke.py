#!/usr/bin/env python
"""Flight-recorder smoke test (`make flight-smoke`).

The telemetry-smoke sibling for the always-on black box: a 4-rank
in-process job with the control plane + hosted window plane forced on,
asserting the flight recorder's acceptance surface end to end:

  * the ring's hot path stays cheap: one slotted record costs < 1500 ns
    (the metrics-smoke harness style; the recorder is ~5 numpy stores +
    perf_counter_ns, measured ~500 ns on an idle box — the budget leaves
    3x for CI noise);
  * a window-optimizer job leaves a decodable ring: ``bf.step_report()``
    attributes the last step into phases that cover the step span;
  * ``bf.flight_dump()`` writes a parseable dump whose attribution
    (scripts/step_attribution.py) reports the pack/wire/drain/fold
    breakdown summing (with the explicit local/other remainder) to within
    10% of the measured step time;
  * ``bfrun --dump`` from a SEPARATE process triggers a cluster-wide dump
    over the control plane (no filesystem access to the "workers") and
    retrieves a merged, clock-synced trace.

Exits non-zero (with a message) on any violated assertion.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import timeit

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

_s = socket.socket()
_s.bind(("127.0.0.1", 0))
PORT = _s.getsockname()[1]
_s.close()

WORKDIR = tempfile.mkdtemp(prefix="bf_flight_smoke_")
os.environ.update({
    "BLUEFOG_CP_HOST": "127.0.0.1",
    "BLUEFOG_CP_PORT": str(PORT),
    "BLUEFOG_CP_WORLD": "1",
    "BLUEFOG_CP_RANK": "0",
    "BLUEFOG_WIN_HOST_PLANE": "1",
    "BLUEFOG_METRICS_INTERVAL": "1",
    "BLUEFOG_FLIGHT_DIR": WORKDIR,
})

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu.runtime import flight as flight_mod  # noqa: E402

BUDGET_NS = 1500.0


def check(cond, msg):
    if not cond:
        print(f"flight-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def microbench_record_ns() -> float:
    """Per-call cost of one ring record (pre-interned name id — the hot
    call-site shape). Same de-noising as metrics_smoke: 10x unroll to
    amortize the loop scaffolding, min over many short windows."""
    r = flight_mod.FlightRecorder(capacity=4096)
    nid = r.intern("smoke.bench")
    unroll = 10
    n = 2_000
    stmt = ";".join(["rec(3, nid)"] * unroll)
    best = min(timeit.repeat(stmt, globals={"rec": r.rec, "nid": nid},
                             number=n, repeat=60)) / (n * unroll)
    return best * 1e9


def main() -> int:
    # 1) hot path: a slotted ring record stays under the budget
    ns = microbench_record_ns()
    print(f"flight record: {ns:.0f} ns/event (budget {BUDGET_NS:.0f})")
    check(ns < BUDGET_NS, f"ring record costs {ns:.0f} ns "
                          f"(budget {BUDGET_NS:.0f})")

    # 2) a real 4-rank hosted job leaves an attributable ring
    bf.init(devices=jax.devices("cpu")[:4])

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zloss,
                                         window_prefix="smoke.fl")
    state = opt.init({"w": jnp.ones((64,), jnp.float32)})
    for _ in range(4):
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))

    rep = bf.step_report()
    check(rep is not None, "step_report found no complete step")
    check(rep["step"] == 4, f"step_report step {rep['step']} != 4")
    print(flight_mod.format_report(rep))
    check(rep["phases"]["drain"] > 0, "no drain time attributed")
    check(rep["phases"]["fold"] > 0, "no fold time attributed")
    total = sum(rep["phases"].values()) + rep["other_sec"]
    check(abs(total - rep["step_sec"]) <= 0.10 * rep["step_sec"],
          f"attributed phases ({total:.6f}s incl. remainder) diverge from "
          f"step_sec {rep['step_sec']:.6f}s by more than 10%")

    # 3) explicit dump: parseable, attribution tool agrees
    path = bf.flight_dump()
    check(path is not None and os.path.exists(path), "flight_dump wrote "
                                                     "nothing")
    doc = json.load(open(path))
    check(doc["events"]["kind"], "dump has no events")
    check(doc["metrics"].get("gauges", {}).get("opt.step") == 4.0,
          "dump's metrics snapshot missing opt.step")
    out = subprocess.run(
        [sys.executable, "scripts/step_attribution.py", path],
        capture_output=True, text=True, timeout=120)
    print(out.stdout, end="")
    check(out.returncode == 0, f"step_attribution failed: {out.stderr}")
    for token in ("pack", "wire", "drain", "fold", "dominant phase"):
        check(token in out.stdout, f"attribution output missing {token!r}")

    # 4) bfrun --dump from a separate process: remote trigger -> per-rank
    # tails -> merged clock-synced trace. The single-controller job has no
    # heartbeat monitor, so this also exercises the watchdog poll path.
    dump_dir = os.path.join(WORKDIR, "remote")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--dump",
         "--cp", f"127.0.0.1:{PORT}", "--out", dump_dir,
         "--dump-timeout", "60"],
        env=dict(os.environ), capture_output=True, text=True, timeout=120)
    print(out.stdout, end="")
    check(out.returncode == 0, f"bfrun --dump failed: rc "
                               f"{out.returncode}: {out.stderr}")
    rank0 = os.path.join(dump_dir, "flight_0.json")
    merged = os.path.join(dump_dir, "merged.json")
    check(os.path.exists(rank0), "bfrun --dump retrieved no rank-0 tail")
    check(os.path.exists(merged), "bfrun --dump wrote no merged trace")
    remote_doc = json.load(open(rank0))
    check(remote_doc["meta"]["reason"].startswith("remote-trigger"),
          f"unexpected dump reason {remote_doc['meta']['reason']!r}")
    merged_events = json.load(open(merged))
    check(any(e.get("name") == "bf.clock_sync_us" for e in merged_events),
          "merged trace lost its clock-sync anchor")

    opt.free()
    bf.shutdown()
    print("flight-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
