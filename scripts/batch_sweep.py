"""Batch-size sweep for the ResNet-50 benchmark step (real-chip probe).

Imports bench.setup() so the probe measures EXACTLY the benchmarked step
(same model, optimizer, data placement, and host-transfer sync idiom),
printing img/s per batch size. Used to pick bench.py's BATCH_PER_CHIP
(PERF.md: B=128 adopted in round 2).

Run from the repo root: ``python scripts/batch_sweep.py [batch ...]``.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

import bluefog_tpu as bf  # noqa: E402
import bench  # noqa: E402

WARMUP = 5
STEPS = 30


def measure(batch: int) -> float:
    # bench.setup() re-inits in place; no per-point shutdown — announcing
    # coordinated shutdown between points would latch every peer's
    # shutdown_requested() in a multi-controller job (see state.py re-init
    # note).
    opt, state, data, sync = bench.setup(batch)
    for _ in range(WARMUP):
        state, metrics = opt.step(state, data)
    sync(metrics)
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state, metrics = opt.step(state, data)
    sync(metrics)
    return batch * STEPS / (time.perf_counter() - t0)


if __name__ == "__main__":
    batches = [int(b) for b in sys.argv[1:]] or [96, 128, 192, 256]
    try:
        for b in batches:
            rate = measure(b)
            print(f"B={b:4d}: {rate:8.1f} img/s/chip  "
                  f"({1000*b/rate:.1f} ms/step)", flush=True)
    finally:
        bf.shutdown()
