#!/usr/bin/env python
"""Serving-plane smoke test (`make serve-smoke`).

A 2-rank in-process trainer with the publisher hook armed
(BLUEFOG_SERVE_PUBLISH_EVERY=1) plus one read-only serve client,
asserting the train-while-serve acceptance surface end to end
(docs/serving.md):

  * the trainer's post-gossip snapshots land behind the version fence
    and the attached client hot-swaps on every bump while training
    continues (swap count grows across extra steps);
  * batched inference returns non-empty replies that EXACTLY match a
    numpy oracle applied to the client's own swapped-in snapshot —
    the params the gate admitted against are the params that answered;
  * the admission gate sheds at the hard queue cap (gate
    ``queue_full``) and every already-admitted future still resolves;
  * ``bfrun --serve --once`` attaches from a SEPARATE process (raw
    control-plane client, no jax) and prints the swap line;
  * ``bfrun --status`` from outside shows the serving-plane rows.

Exits non-zero (with a message) on any violated assertion.
"""

import os
import socket
import subprocess
import sys
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

_s = socket.socket()
_s.bind(("127.0.0.1", 0))
PORT = _s.getsockname()[1]
_s.close()

os.environ.update({
    "BLUEFOG_CP_HOST": "127.0.0.1",
    "BLUEFOG_CP_PORT": str(PORT),
    "BLUEFOG_CP_WORLD": "1",
    "BLUEFOG_CP_RANK": "0",
    "BLUEFOG_SERVE_PUBLISH_EVERY": "1",
    "BLUEFOG_SERVE_POLL_S": "0.1",
})

import numpy as np  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"serve-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def main() -> int:
    # 1) a 2-rank trainer whose every communicating step publishes
    bf.init(devices=jax.devices("cpu")[:2])

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zloss,
                                         window_prefix="smoke.serve")
    state = opt.init({"w": jnp.arange(96, dtype=jnp.float32)})
    for _ in range(3):
        state, _ = opt.step(state, jnp.zeros((2, 1), jnp.float32))

    # 2) serve client hot-swaps while the trainer keeps stepping
    def model_fn(params, xs):
        return xs + params[0][0]

    sc = bf.serve_client(model_fn, endpoints=[("127.0.0.1", PORT)])
    check(sc.wait_ready(timeout=20), "no complete snapshot within 20 s — "
          "did the publisher hook fire?")
    v0, s0 = sc.version(), sc.stats()["swaps"]
    check(v0 >= 1, f"serving version {v0} after 3 published steps")
    for _ in range(3):
        state, _ = opt.step(state, jnp.zeros((2, 1), jnp.float32))
    deadline = 20.0
    while sc.version() <= v0 and deadline > 0:
        deadline -= 0.1
        threading.Event().wait(0.1)
    check(sc.version() > v0 and sc.stats()["swaps"] > s0,
          f"no hot-swap: version {v0} -> {sc.version()}, "
          f"swaps {s0} -> {sc.stats()['swaps']}")

    # 3) batched replies match the numpy oracle on the swapped-in params
    params = sc.params()
    xs = np.linspace(-1.0, 1.0, 8, dtype=np.float32)
    futs = [sc.submit(np.array([x], np.float32)) for x in xs]
    ys = np.array([f.result(timeout=10)[0] for f in futs])
    want = xs + float(np.asarray(params[0]).ravel()[0])
    check(np.allclose(ys, want),
          f"batched replies diverge from the snapshot oracle: {ys} != {want}")
    check(sc.stats()["batches"] >= 1, "no batch was formed")
    sc.close()

    # 4) shed path: a hard queue cap of 2 with a blocked model must shed
    os.environ.update({"BLUEFOG_SERVE_QUEUE_MAX": "2",
                       "BLUEFOG_SERVE_QUEUE_SOFT": "1",
                       "BLUEFOG_SERVE_BATCH": "1"})
    gate = threading.Event()

    def slow_fn(params, xs):
        gate.wait(20)
        return xs

    from bluefog_tpu.serving.client import RequestShed
    sc2 = bf.serve_client(slow_fn, endpoints=[("127.0.0.1", PORT)])
    check(sc2.wait_ready(timeout=20), "second client never became ready")
    admitted, shed = [], 0
    for i in range(8):
        try:
            admitted.append(sc2.submit(np.zeros(1, np.float32)))
        except RequestShed as exc:
            shed += 1
            check(exc.gate == "queue_full",
                  f"shed gate {exc.gate!r}, expected queue_full")
    check(shed >= 1, "queue cap 2 never shed across 8 submits")
    gate.set()
    for f in admitted:
        f.result(timeout=10)  # every admitted request still resolves
    check(sc2.stats()["shed"] == shed, "shed counter out of sync")
    sc2.close()
    del os.environ["BLUEFOG_SERVE_QUEUE_MAX"]
    del os.environ["BLUEFOG_SERVE_QUEUE_SOFT"]
    del os.environ["BLUEFOG_SERVE_BATCH"]

    # 5) the external attach path: bfrun from a separate process
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--serve", "--once",
         "--cp", f"127.0.0.1:{PORT}"],
        env=env, capture_output=True, text=True, timeout=120)
    print(out.stdout, end="")
    check(out.returncode == 0, f"bfrun --serve --once failed: {out.stderr}")
    check("snapshot v" in out.stdout,
          f"--serve printed no swap line: {out.stdout!r}")

    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--status"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"bfrun --status failed: {out.stderr}")
    check("serving plane" in out.stdout and "snapshot v" in out.stdout,
          f"--status output missing serving rows: {out.stdout!r}")

    opt.free()
    bf.shutdown()
    print("serve-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
