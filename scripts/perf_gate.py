#!/usr/bin/env python
"""Performance regression gate (`make perf-gate`).

Runs the quick modes of the two standing benchmark harnesses —
``win_microbench`` (hosted window data plane, 4 real controller processes)
and ``opt_matrix_bench`` (full optimizer step over the 8-device simulated
mesh) — ``--repeats`` times, takes the per-metric **median**, and compares
against the committed baseline (``PERF_BASELINE.json``) with a
**percentage band**: a metric whose median lands below
``baseline * (1 - band)`` reds the gate. Median-of-N plus a generous band
is the noise tolerance: quick-mode numbers on a shared CI box jitter tens
of percent run to run, a real regression (a serialization bug, an extra
copy, a lost overlap) costs 2-10x.

Only the *stable* quick-mode series gate: the hosted window ops
(win_put / win_accumulate / win_update / win_get MB/s), the optimizer
step rates, the ``hybrid.*`` plane-sweep rates (gating since r15), the
``codec.*`` compressed-wire window-op rates (gating since r18), the
``sharded.*`` sharded-window series (gating since r19), including the
counter-delta ``wire_reduction_x`` ratios (deterministic byte
accounting, the least noisy rows in the gate), and — since r20, two
stable rounds after r18 introduced the serving plane — the ``serve.*``
snapshot-pull throughput / scaling / int8-wire-ratio rows.
Sub-millisecond raw-socket probes, the codec wire-leg probes
(``drain_stream``: 2x run-to-run jitter), and the lower-better serving
latency rows (``serve.p50_ms``/``p99_ms``) are reported in the JSON but
never gate.

Exit codes: 0 pass, 1 regression (or a bench failed), 2 usage/baseline
problems.

Usage:
    python scripts/perf_gate.py [--quick] [--repeats N] [--band FRAC]
    python scripts/perf_gate.py --update-baseline   # rewrite the baseline
    BLUEFOG_PERF_GATE_DELAY_MS=50 make perf-gate    # seeded slowdown: RED

The seeded-slowdown knob (declared in runtime/config.py) injects an
artificial delay into every hosted window op and optimizer step, which is
how the gate's red path is exercised deterministically — if that run ever
passes, the gate is broken.
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import statistics
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BASELINE = REPO / "PERF_BASELINE.json"

# metrics that GATE (stable quick-mode series); everything else collected
# is informational
_GATING_OPS = ("win_put", "win_accumulate", "win_update", "win_get")
_OPT_MODES = ("neighbor_allreduce", "win_put")


def _run(cmd, timeout) -> str:
    r = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                       timeout=timeout,
                       env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if r.returncode != 0:
        raise RuntimeError(
            f"bench failed ({' '.join(map(str, cmd))}):\n"
            + (r.stdout + r.stderr)[-2000:])
    return r.stdout


def collect_once() -> dict:
    """One pass over both harnesses -> {metric: value} (higher = better)."""
    out: dict = {}
    # the --codec and --sharded sweeps ride the SAME 4-process run (extra
    # rows after the plain series, which stay untouched): codec.* GATES
    # since r18 (window-op rates only — see gating()); sharded.* GATES
    # since r19 (mbps rows plus the wire_reduction_x counter-delta
    # ratios); the sharded run also ASSERTS the ≥0.9·S wire-byte
    # reduction inside the child — a broken claim fails the run outright
    text = _run([sys.executable, "scripts/win_microbench.py", "--quick",
                 "--codec", "int8,topk:0.01", "--sharded", "2,4"],
                timeout=900)
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        row = json.loads(line)
        if row.get("sharded") is not None or \
                str(row.get("op", "")).startswith("sharded_"):
            if row.get("mbps") is not None:
                out[f"sharded.{row['config']}.{row['op']}.mbps"] = \
                    row["mbps"]
            elif row.get("reduction_x") is not None:
                out[f"sharded.{row['config']}.s{row['sharded']}"
                    ".wire_reduction_x"] = row["reduction_x"]
            continue
        if row.get("codec"):
            if row.get("mbps") is not None:
                out[f"codec.{row['codec']}.{row['config']}.{row['op']}"
                    ".mbps"] = row["mbps"]
            continue
        if row.get("mbps") is not None:
            out[f"win.{row['config']}.{row['op']}.mbps"] = row["mbps"]
    text = _run([sys.executable, "scripts/opt_matrix_bench.py", "--quick",
                 "--modes", *_OPT_MODES], timeout=1800)
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        row = json.loads(line)
        if "error" in row:
            raise RuntimeError(
                f"opt_matrix_bench mode {row['mode']} failed: "
                f"{row['error']}")
        out[f"opt.{row['mode']}.img_per_sec"] = row["img_per_sec"]
    # hybrid plane sweep (ISSUE r13): `hybrid.*` series — GATING since r15
    # (two stable rounds elapsed per the stable-series rule; baseline
    # refreshed alongside)
    text = _run([sys.executable, "scripts/opt_matrix_bench.py", "--quick",
                 "--hybrid"], timeout=1800)
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        row = json.loads(line)
        if row.get("mode") == "win_planes_equivalence":
            if not row.get("passed"):
                raise RuntimeError(
                    "win-plane equivalence tests failed during the hybrid "
                    f"sweep: {row.get('tail')}")
            continue
        if "error" in row:
            raise RuntimeError(
                f"opt_matrix_bench --hybrid {row.get('plane')}/ov"
                f"{row.get('overlap')} failed: {row['error']}")
        out[f"hybrid.{row['mode']}.{row['plane']}.ov{row['overlap']}"
            ".img_per_sec"] = row["img_per_sec"]
    # serving plane: `serve.*` GATES since r20 (two stable rounds after
    # r18 introduced it, per the stable-series rule) — the pull
    # throughput rows, the net scaling ratio, and the counter-delta int8
    # wire ratio; the lower-better latency rows (p50/p99 ms) stay info
    # (see gating()).
    text = _run([sys.executable, "scripts/serve_bench.py", "--quick"],
                timeout=900)
    for line in text.splitlines():
        if not line.startswith("BF_SERVE_BENCH "):
            continue
        row = json.loads(line.split(None, 1)[1])
        for key in ("pull_mbps_1shard", "pull_mbps_4shard",
                    "pull_mbps_1shard_net", "pull_mbps_4shard_net",
                    "pull_scaling_x_net", "int8_wire_ratio",
                    "p50_ms", "p99_ms"):
            if row.get(key) is not None:
                out[f"serve.{key}"] = row[key]
        # r21 request-path attribution rows: serve.trace.* (phase
        # percentiles + traced-request count) and slo.* (SLO engine
        # counters) — collected INFO-ONLY, excluded in gating()
        for key, v in row.items():
            if not isinstance(v, (int, float)):
                continue
            if key.startswith("trace."):
                out[f"serve.{key}"] = v
            elif key.startswith("slo."):
                out[key] = v
    return out


def collect(repeats: int) -> dict:
    """Median over ``repeats`` full passes, per metric."""
    runs = []
    for i in range(repeats):
        t0 = time.time()
        runs.append(collect_once())
        print(f"perf-gate: pass {i + 1}/{repeats} done "
              f"({time.time() - t0:.0f}s, {len(runs[-1])} metrics)",
              flush=True)
    metrics = {}
    for name in sorted({k for r in runs for k in r}):
        vals = [r[name] for r in runs if name in r]
        metrics[name] = statistics.median(vals)
    return metrics


def gating(metrics: dict) -> dict:
    keep = {}
    for name, v in metrics.items():
        if name.startswith("codec.") and \
                not any(name.endswith(f"{op}.mbps")
                        for op in _GATING_OPS):
            # codec.* GATES since r18 (two stable rounds elapsed since
            # r15), but only its stable window-op series — the wire-leg
            # probes (drain_stream) jitter 2x run to run and stay info
            continue
        if name.startswith("slo."):
            # slo.* (r21, SLO engine counters from the churned serving
            # run) is INFO-ONLY: run-length-dependent counts, not rates;
            # per the stable-series rule they could only ever graduate
            # as derived rates, two stable rounds from now at the
            # earliest
            continue
        if name.startswith("serve."):
            # serve.* GATES since r20 (two stable rounds elapsed since
            # r18 introduced the serving plane, per the stable-series
            # rule): the snapshot-pull throughput rows, the sharded
            # net scaling ratio, and the counter-delta int8 wire ratio.
            # The LATENCY rows (p50/p99 ms) stay info-only: they are
            # lower-better, and compare()'s band is higher-is-better —
            # they would need inverting (or replacing with a rate)
            # before they could ever gate. serve.trace.* (r21 phase
            # attribution) is info-only for the same lower-better
            # reason, plus quick-mode phase tails jitter far beyond the
            # band.
            if name.endswith("_ms") or name.startswith("serve.trace."):
                continue
            keep[name] = v
            continue
        if name.startswith("opt.") or name.startswith("hybrid.") or \
                name.startswith("codec.") or \
                name.startswith("sharded.") or \
                any(name.endswith(f"{op}.mbps") or f".{op}." in name
                    for op in _GATING_OPS):
            # sharded.* GATES since r19 (two stable rounds elapsed since
            # r17, per the stable-series rule — the same graduation
            # hybrid.* took at r15 and codec.* at r18); its
            # wire_reduction_x rows are counter-delta ratios, the most
            # deterministic series in the gate
            keep[name] = v
    return keep


def compare(metrics: dict, baseline: dict, band: float):
    """-> (failures, report lines) against the baseline's gating set."""
    failures = []
    lines = []
    for name in sorted(baseline):
        base = baseline[name]
        got = metrics.get(name)
        if got is None:
            failures.append(name)
            lines.append(f"  MISSING  {name}: baseline {base:g}, no "
                         "measurement this run")
            continue
        ratio = got / base if base else float("inf")
        verdict = "ok"
        if ratio < 1.0 - band:
            verdict = "REGRESSION"
            failures.append(name)
        lines.append(f"  {verdict:<10} {name}: {got:g} vs baseline "
                     f"{base:g} ({(ratio - 1) * 100:+.0f}%, band "
                     f"-{band * 100:.0f}%)")
    for name in sorted(set(metrics) - set(baseline)):
        lines.append(f"  info      {name}: {metrics[name]:g} "
                     "(not a gating metric)")
    return failures, lines


def bench_doc(metrics: dict, repeats: int, band: float) -> dict:
    """BENCH_rXX-style JSON document."""
    return {
        "meta": {
            "kind": "perf_gate",
            "host": platform.node(),
            "repeats": repeats,
            "band": band,
            "harnesses": ["win_microbench --quick --codec int8,topk:0.01 "
                          "--sharded 2,4 (codec.* window-op rates gating "
                          "since r18; sharded.* gating since r19)",
                          "opt_matrix_bench --quick --modes "
                          + " ".join(_OPT_MODES),
                          "opt_matrix_bench --quick --hybrid",
                          "serve_bench --quick (serve.* gating since "
                          "r20; latency rows info-only)"],
            "note": "quick-mode numbers: gate-relative only, meaningless "
                    "as absolute throughput (see PERF.md for real runs)",
        },
        "metrics": metrics,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--quick", action="store_true",
                    help="accepted for Makefile symmetry (the gate always "
                         "runs the harnesses' quick modes)")
    ap.add_argument("--repeats", type=int, default=3,
                    help="full passes to median over (default 3)")
    ap.add_argument("--band", type=float, default=0.40,
                    help="allowed fractional drop below baseline before "
                         "red (default 0.40 — quick modes are noisy; real "
                         "regressions are larger)")
    ap.add_argument("--baseline", type=str, default=str(BASELINE))
    ap.add_argument("--update-baseline", action="store_true",
                    help="measure and REWRITE the baseline file instead of "
                         "comparing")
    ap.add_argument("--json", type=str, default=None,
                    help="also write this run's BENCH-style JSON here")
    args = ap.parse_args(argv)

    if os.environ.get("BLUEFOG_PERF_GATE_DELAY_MS") and \
            args.update_baseline:
        print("perf-gate: refusing to bake a seeded slowdown "
              "(BLUEFOG_PERF_GATE_DELAY_MS is set) into the baseline",
              file=sys.stderr)
        return 2

    try:
        metrics = collect(max(1, args.repeats))
    except (RuntimeError, subprocess.TimeoutExpired) as exc:
        print(f"perf-gate: bench run failed:\n{exc}", file=sys.stderr)
        return 1
    doc = bench_doc(metrics, args.repeats, args.band)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)

    if args.update_baseline:
        base_doc = bench_doc(gating(metrics), args.repeats, args.band)
        with open(args.baseline, "w") as f:
            json.dump(base_doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"perf-gate: baseline updated -> {args.baseline} "
              f"({len(base_doc['metrics'])} gating metrics)")
        return 0

    try:
        with open(args.baseline) as f:
            baseline = json.load(f)["metrics"]
    except (OSError, KeyError, json.JSONDecodeError) as exc:
        print(f"perf-gate: cannot read baseline {args.baseline} ({exc}); "
              "run `python scripts/perf_gate.py --update-baseline` on a "
              "healthy tree first", file=sys.stderr)
        return 2
    failures, lines = compare(metrics, baseline, args.band)
    print("perf-gate comparison (median of "
          f"{args.repeats} pass(es) vs {args.baseline}):")
    for line in lines:
        print(line)
    if failures:
        print(f"perf-gate: RED — {len(failures)} metric(s) regressed "
              f"beyond the {args.band * 100:.0f}% band: {failures}",
              file=sys.stderr)
        return 1
    print("perf-gate: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
