#!/usr/bin/env python
"""Serving-plane benchmark: sharded snapshot fan-out + batched inference.

Three phases over a real control plane (no jax anywhere — the serving
path is numpy-only by contract):

1. **Pull-bandwidth scaling.** Publish a ``--model-mb`` snapshot
   (default 102 MB) and time pinned parallel pulls against 1 and then 4
   control-plane shard servers. Wire bytes are VERIFIED against the
   native transport counters (``client_stats()['bytes_in']`` deltas), so
   the reported bandwidth is what crossed the sockets, not what the
   Python layer believes. The acceptance bar is >= 1.6x from 1 -> 4.

2. **Codec wire savings.** Publish the same model raw and int8-quantized
   and compare the EXACT per-pull wire-byte counter deltas. Bar: int8
   moves >= 3x fewer bytes.

3. **Open-loop serving latency under churn.** A trainer-side publisher
   keeps committing versions whose every element equals the version
   number (torn reads become value mismatches); a :class:`ServeClient`
   serves an open-loop arrival stream (fixed rate, no backpressure from
   completions) while the harness injects a straggling model batch every
   ``--straggle-every`` batches, SIGKILLs a replicated control-plane
   shard mid-run, and rejoins it ON A NEW PORT. Reported: p50/p99
   request latency, shed count, and the two invariants that must be
   ZERO: torn reads and stale-beyond-keep-window serving at settle.

Prints one machine-readable line -- ``BF_SERVE_BENCH {json}`` -- that
``perf_gate.py`` collects as INFO-ONLY ``serve.*`` metrics.

Invocations:
    python scripts/serve_bench.py            # full: 102 MB, 30 s churn
    python scripts/serve_bench.py --quick    # perf-gate preset (~30 s)
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
import types

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "bluefog_tpu")
sys.path.insert(0, _ROOT)
for _name in ("bluefog_tpu", "bluefog_tpu.runtime", "bluefog_tpu.ops",
              "bluefog_tpu.serving"):
    if _name not in sys.modules:
        _mod = types.ModuleType(_name)
        _mod.__path__ = [os.path.join(_PKG, *_name.split(".")[1:])]
        sys.modules[_name] = _mod

import numpy as np  # noqa: E402

from bluefog_tpu.ops import codec as codec_mod  # noqa: E402
from bluefog_tpu.runtime import native  # noqa: E402
from bluefog_tpu.runtime.router import ShardRouter  # noqa: E402
from bluefog_tpu.serving import snapshot as snap  # noqa: E402
from bluefog_tpu.serving.client import ServeClient, RequestShed  # noqa: E402

SHARD_SERVER = os.path.join(_PKG, "runtime", "shard_server.py")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model-mb", type=float, default=102.0,
                   help="snapshot size for the bandwidth/codec phases")
    p.add_argument("--snap-shards", type=int, default=16,
                   help="snapshot stripe count (pull units)")
    p.add_argument("--trials", type=int, default=5,
                   help="timed pulls per configuration (best-of)")
    p.add_argument("--rate", type=float, default=150.0,
                   help="open-loop arrival rate (requests/s)")
    p.add_argument("--duration", type=float, default=30.0,
                   help="seconds of open-loop serving load")
    p.add_argument("--straggle-every", type=int, default=23,
                   help="every Nth model batch sleeps --straggle-ms")
    p.add_argument("--straggle-ms", type=float, default=25.0)
    p.add_argument("--net-mbps", type=float, default=300.0,
                   help="modeled per-endpoint link capacity (MB/s) for "
                        "the paced scaling pass; 0 disables it. On a "
                        "single-core host the UNCONSTRAINED pass cannot "
                        "exceed 1x (everything shares the core); the "
                        "paced pass shows the fan-out overlap the way "
                        "NIC-bound production pulls experience it")
    p.add_argument("--skip-latency", action="store_true",
                   help="bandwidth + codec phases only")
    p.add_argument("--quick", action="store_true",
                   help="perf-gate preset: 16 MB model, 3 trials, "
                        "10 s of churned serving load")
    args = p.parse_args(argv)
    if args.quick:
        args.model_mb = min(args.model_mb, 16.0)
        args.trials = min(args.trials, 3)
        args.duration = min(args.duration, 10.0)
        args.rate = min(args.rate, 80.0)
    return args


# ---------------------------------------------------------------------------
# control-plane process helpers (same two-phase spawn as cp_soak)
# ---------------------------------------------------------------------------

def spawn_shard(index, world, replicate, port=0, rejoin=False):
    cmd = [sys.executable, SHARD_SERVER, "--port", str(port),
           "--world", str(world), "--shard", str(index)]
    if replicate:
        cmd.append("--expect-peers")
    if rejoin:
        cmd.append("--rejoin")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE if replicate else None,
                            text=True)
    marker = "BF_SHARD_PORT" if replicate else "BF_SHARD_READY"
    line = proc.stdout.readline()
    if not line.startswith(marker):
        raise RuntimeError(f"shard {index} failed to start: {line!r}")
    return proc, int(line.split()[1])


def finish_shard_spawn(servers, ring=None):
    ring = ring or ",".join(f"127.0.0.1:{p}" for _, p in servers)
    for proc, _ in servers:
        proc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        proc.stdin.flush()
    for i, (proc, _) in enumerate(servers):
        line = proc.stdout.readline()
        if not line.startswith("BF_SHARD_READY"):
            raise RuntimeError(f"shard {i} failed to wire peers: {line!r}")


def stop_shards(servers):
    for proc, _ in servers:
        if proc.poll() is None:
            proc.terminate()
    for proc, _ in servers:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()


def attach(endpoints):
    if len(endpoints) == 1:
        return native.ControlPlaneClient(endpoints[0][0], endpoints[0][1], 0,
                                         streams=1)
    return ShardRouter(endpoints, 0, streams=1, lenient=True)


def wire_in_total():
    st = native.client_stats()
    return sum(st.get("bytes_in", {}).values())


def model_leaves(total_mb, fill=None, seed=0):
    """A few unequal f32 leaves totalling ~total_mb (like a real tree)."""
    total = int(total_mb * 2 ** 20 / 4)
    splits = [total // 2, total // 3, total - total // 2 - total // 3]
    if fill is not None:
        return [np.full(n, float(fill), np.float32) for n in splits]
    rng = np.random.default_rng(seed)
    return [rng.standard_normal(n).astype(np.float32) for n in splits]


# ---------------------------------------------------------------------------
# phase 1+2: pull-bandwidth scaling and codec wire savings
# ---------------------------------------------------------------------------

def bench_pull(args, nshards, leaves, codec=None, pace_mbps=0.0):
    """Publish once against ``nshards`` servers; return the best timed
    parallel pull (counter-verified wire bytes)."""
    servers = [spawn_shard(i, 1, False) for i in range(nshards)]
    endpoints = [("127.0.0.1", p) for _, p in servers]
    cl = attach(endpoints)
    sc = ServeClient(endpoints, register=False, start=False)
    sc._pace_mbps = pace_mbps
    try:
        pub = snap.SnapshotPublisher(cl, shards=args.snap_shards,
                                     codec=codec, keep=8)
        pub.publish(leaves, 1)
        meta = snap.fetch_meta(cl)
        keys = snap.snap_keys(meta, 1)
        best_dt, wire = float("inf"), 0
        c0 = wire_in_total()
        for _ in range(args.trials):
            t0 = time.perf_counter()
            blobs = sc.pull_blobs(keys)
            best_dt = min(best_dt, time.perf_counter() - t0)
            wire = sum(len(b) for b in blobs)
        counted = wire_in_total() - c0
        # the transport counter must agree with what we think we pulled
        # (headers/framing allow a small envelope)
        verified = abs(counted - args.trials * wire) <= \
            0.05 * args.trials * wire + 4096
        # decode correctness once per configuration
        out, ver, _ = snap.fetch_snapshot(cl, meta=meta, ver=1,
                                          pull=sc.pull_blobs)
        assert ver == 1
        tol = 0.0 if codec is None else 0.05
        for a, b in zip(leaves, out):
            np.testing.assert_allclose(a, b, atol=tol)
        return {"mbps": wire / best_dt / 1e6, "wire_bytes": wire,
                "counter_verified": bool(verified), "dt_s": best_dt}
    finally:
        sc.close()
        try:
            cl.close()
        except (OSError, RuntimeError):
            pass
        stop_shards(servers)


# ---------------------------------------------------------------------------
# phase 3: open-loop serving under straggler + kill/rejoin churn
# ---------------------------------------------------------------------------

class Publisher(threading.Thread):
    """Trainer stand-in: commits a version every ``period`` whose every
    element equals the version (torn reads become value mismatches)."""

    def __init__(self, cl, elems, period=0.4, keep=3):
        super().__init__(daemon=True, name="bench-pub")
        self.cl = cl
        self.elems = elems
        self.period = period
        self.pub = snap.SnapshotPublisher(cl, shards=8, keep=keep)
        self.ver = 0
        self.committed = 0
        self.failed = 0
        self.stop = threading.Event()

    def run(self):
        while not self.stop.is_set():
            # poll EVERY tick (what the trainer's heartbeat loop does):
            # a writer that only discovers churn on failure would keep
            # natively-redirected fence writes on the ring successor
            # after the shard rejoined — readers re-point to the
            # rejoined shard and would never see a fence move again
            if hasattr(self.cl, "poll_shard_health"):
                try:
                    self.cl.poll_shard_health()
                except (OSError, RuntimeError):
                    pass
            nxt = self.ver + 1
            leaves = [np.full(self.elems, float(nxt), np.float32),
                      np.full(self.elems // 4 + 1, float(nxt), np.float32)]
            try:
                self.pub.publish(leaves, nxt, step=nxt)
                self.ver = nxt
                self.committed += 1
            except (OSError, RuntimeError):
                self.failed += 1  # shard outage window: fence unmoved
            self.stop.wait(self.period)


def bench_latency(args):
    os.environ.setdefault("BLUEFOG_CP_BACKOFF_MS", "20")
    os.environ["BLUEFOG_SERVE_POLL_S"] = "0.1"
    # r21: the churn run doubles as the request-path attribution bench —
    # tracing + a declared SLO produce the phase p50/p99 and slo.* rows
    # that perf_gate collects INFO-ONLY (docs/slo.md)
    os.environ["BLUEFOG_TRACE_SERVE"] = "1"
    os.environ.setdefault("BLUEFOG_SLO", "serve_p99:50ms@1m,serve_avail:99@1m")
    keep = 3
    servers = [spawn_shard(i, 1, True) for i in range(2)]
    finish_shard_spawn(servers)
    endpoints = [("127.0.0.1", p) for _, p in servers]
    pub_cl = attach(endpoints)
    publisher = Publisher(pub_cl, elems=200_000, keep=keep)
    publisher.start()

    state = {"batches": 0}

    def model_fn(params, xs):
        state["batches"] += 1
        if args.straggle_every > 0 and \
                state["batches"] % args.straggle_every == 0:
            time.sleep(args.straggle_ms / 1e3)  # injected straggler
        return xs + params[0][0]

    sc = ServeClient(endpoints, model_fn=model_fn, register=True)
    torn = [0]
    verify_stop = threading.Event()

    def verifier():
        # the serving-side torn-read probe: whatever (params, version)
        # pair is swapped in, every element must equal the version
        while not verify_stop.is_set():
            with sc._mu:
                p, v = sc._params, sc._version
            if p is not None:
                for leaf in p:
                    if leaf[0] != float(v) or leaf[-1] != float(v) or \
                            not bool((leaf == float(v)).all()):
                        torn[0] += 1
                        break
            verify_stop.wait(0.05)

    vt = threading.Thread(target=verifier, daemon=True, name="bench-verify")
    vt.start()

    if not sc.wait_ready(timeout=20):
        raise RuntimeError("serve client never pulled a first snapshot")

    lat_ms, shed = [], [0]
    lat_mu = threading.Lock()

    def arrival(t_sched):
        try:
            fut = sc.submit(np.zeros(4, np.float32))
        except RequestShed:
            shed[0] += 1
            return
        fut.add_done_callback(
            lambda f: (lat_mu.acquire(),
                       lat_ms.append((time.perf_counter() - t_sched) * 1e3)
                       if f.exception() is None else None,
                       lat_mu.release()))

    t_start = time.perf_counter()
    t_kill = t_start + 0.4 * args.duration
    t_rejoin = t_start + 0.6 * args.duration
    t_end = t_start + args.duration
    killed = rejoined = False
    next_t = t_start
    old_port = servers[1][1]
    while time.perf_counter() < t_end:
        now = time.perf_counter()
        if now >= next_t:
            arrival(next_t)  # open loop: scheduled arrival, no waiting
            next_t += 1.0 / args.rate
        if not killed and now >= t_kill:
            servers[1][0].send_signal(signal.SIGKILL)
            servers[1][0].wait()
            killed = True
            print(f"serve_bench: SIGKILLed shard 1 at "
                  f"t+{now - t_start:.1f}s")
        if killed and not rejoined and now >= t_rejoin:
            proc, nport = spawn_shard(1, 1, True, port=0, rejoin=True)
            ring = f"127.0.0.1:{servers[0][1]},127.0.0.1:{old_port}"
            finish_shard_spawn([(proc, nport)], ring=ring)
            servers[1] = (proc, nport)
            rejoined = True
            print(f"serve_bench: shard 1 REJOINED on new port {nport} "
                  f"(was {old_port}) at t+{now - t_start:.1f}s")
        time.sleep(min(0.002, max(0.0, next_t - time.perf_counter())))

    # settle: the client must catch back up to within the keep window
    stale_beyond_keep = 1
    settle_deadline = time.monotonic() + 15.0
    while time.monotonic() < settle_deadline:
        if publisher.ver and publisher.ver - sc.version() <= keep:
            stale_beyond_keep = 0
            break
        time.sleep(0.2)

    publisher.stop.set()
    publisher.join(timeout=10)
    verify_stop.set()
    vt.join(timeout=5)
    st = sc.stats()
    # request-path attribution: replay the flight ring's request spans
    # (client + in-process publisher share one ring here) into the
    # per-phase percentile table
    from bluefog_tpu.runtime import flight as flight_mod
    from bluefog_tpu.runtime import metrics as metrics_mod
    trace_rows: dict = {}
    rep = flight_mod.serve_report()
    if rep:
        trace_rows["trace.requests"] = rep["requests"]
        for p, prow in sorted(rep["phases"].items()):
            trace_rows[f"trace.phase.{p}.p50_us"] = round(prow["p50_us"], 1)
            trace_rows[f"trace.phase.{p}.p99_us"] = round(prow["p99_us"], 1)
        attr = "  ".join(f"{p} {prow['p50_us']:.0f}/{prow['p99_us']:.0f}"
                         for p, prow in sorted(rep["phases"].items()))
        print(f"serve_bench: phase attribution over {rep['requests']} "
              f"traced request(s), p50/p99 us: {attr}")
    for name in ("slo.requests", "slo.shed", "slo.breach.serve_p99",
                 "slo.breach.serve_avail"):
        c = metrics_mod._REGISTRY._counters.get(name)
        if c is not None:
            trace_rows[name] = c.value()
    sc.close()
    try:
        pub_cl.close()
    except (OSError, RuntimeError):
        pass
    stop_shards(servers)

    with lat_mu:
        lats = sorted(lat_ms)
    pct = lambda q: lats[min(len(lats) - 1, int(q * len(lats)))] \
        if lats else float("nan")  # noqa: E731
    out = {
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "completed": len(lats), "shed": shed[0] + int(st["shed"]),
        "swaps": st["swaps"], "pull_failures": st["pull_failures"],
        "published": publisher.committed, "publish_failed": publisher.failed,
        "torn_reads": torn[0], "stale_beyond_keep": stale_beyond_keep,
        "rejoined_new_port": rejoined,
    }
    out.update(trace_rows)
    return out


def main(argv=None) -> int:
    args = parse_args(argv)
    if native.load() is None:
        print("serve_bench: native runtime unavailable", file=sys.stderr)
        return 1
    t0 = time.time()
    result: dict = {"model_mb": args.model_mb}
    failures = []

    # phase 1: pull-bandwidth scaling 1 -> 4 control-plane shards
    leaves = model_leaves(args.model_mb)
    r1 = bench_pull(args, 1, leaves)
    r4 = bench_pull(args, 4, leaves)
    scaling = r4["mbps"] / max(1e-9, r1["mbps"])
    result.update({
        "pull_mbps_1shard": round(r1["mbps"], 1),
        "pull_mbps_4shard": round(r4["mbps"], 1),
        "pull_scaling_x": round(scaling, 2),
        "counter_verified": r1["counter_verified"] and
        r4["counter_verified"],
    })
    result["cores"] = os.cpu_count() or 1
    print(f"serve_bench: pull {args.model_mb:.0f} MB: "
          f"1 shard {r1['mbps']:.0f} MB/s, 4 shards {r4['mbps']:.0f} MB/s "
          f"({scaling:.2f}x unconstrained on {result['cores']} core(s), "
          f"counters "
          f"{'verified' if result['counter_verified'] else 'MISMATCH'})")
    if not result["counter_verified"]:
        failures.append("wire-byte counter deltas disagree with pulled "
                        "payload sizes")

    # paced pass: per-endpoint link capacity modeled, so the fan-out
    # overlap is visible even when one core serializes the local copies
    if args.net_mbps > 0:
        p1 = bench_pull(args, 1, leaves, pace_mbps=args.net_mbps)
        p4 = bench_pull(args, 4, leaves, pace_mbps=args.net_mbps)
        net_scaling = p4["mbps"] / max(1e-9, p1["mbps"])
        result.update({
            "net_mbps_model": args.net_mbps,
            "pull_mbps_1shard_net": round(p1["mbps"], 1),
            "pull_mbps_4shard_net": round(p4["mbps"], 1),
            "pull_scaling_x_net": round(net_scaling, 2),
        })
        print(f"serve_bench: pull at a {args.net_mbps:.0f} MB/s/endpoint "
              f"link model: 1 shard {p1['mbps']:.0f} MB/s, 4 shards "
              f"{p4['mbps']:.0f} MB/s ({net_scaling:.2f}x)")
        if net_scaling < 1.6:
            failures.append(
                f"paced pull scaling {net_scaling:.2f}x < 1.6x — the "
                "endpoint fan-out is not overlapping pulls")

    # phase 2: int8 vs raw wire bytes (exact, from the same counters)
    int8 = codec_mod.state_codec_for(codec_mod.resolve("int8"))
    ri = bench_pull(args, 4, leaves, codec=int8)
    ratio = r4["wire_bytes"] / max(1, ri["wire_bytes"])
    result.update({"int8_wire_ratio": round(ratio, 2),
                   "raw_wire_bytes": r4["wire_bytes"],
                   "int8_wire_bytes": ri["wire_bytes"]})
    print(f"serve_bench: codec: raw {r4['wire_bytes']} B vs int8 "
          f"{ri['wire_bytes']} B per pull = {ratio:.2f}x fewer bytes")
    if not ri["counter_verified"]:
        failures.append("int8 wire-byte counter deltas disagree")

    # phase 3: open-loop latency under straggler + kill/rejoin churn
    if not args.skip_latency:
        lat = bench_latency(args)
        result.update(lat)
        print(f"serve_bench: open loop {args.rate:.0f} req/s x "
              f"{args.duration:.0f}s under churn: p50 {lat['p50_ms']:.1f} ms"
              f" p99 {lat['p99_ms']:.1f} ms, {lat['completed']} completed, "
              f"{lat['shed']} shed, {lat['swaps']} hot-swaps, "
              f"{lat['published']} versions published "
              f"({lat['publish_failed']} publish attempts hit the outage)")
        if lat["torn_reads"]:
            failures.append(f"{lat['torn_reads']} TORN reads")
        if lat["stale_beyond_keep"]:
            failures.append("client stale beyond the keep window after "
                            "churn settled")
        if not lat["rejoined_new_port"]:
            failures.append("rejoin-on-new-port never executed")
        if lat["completed"] == 0:
            failures.append("no request ever completed")

    result["wall_s"] = round(time.time() - t0, 1)
    print("BF_SERVE_BENCH " + json.dumps(result), flush=True)
    if failures:
        print("serve_bench: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  {f}", file=sys.stderr)
        return 1
    print(f"serve_bench: PASS ({result['wall_s']}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
