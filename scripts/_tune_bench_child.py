"""Child process for scripts/tune_bench.py (one of 4 controllers).

Runs the optimizer-shaped gossip loop — healed send/receive tables with
the self-tuning controller's demoted edges dropped from the send side
(the exact tables ``optimizers._gossip`` builds) — over the REAL hosted
window wire, under ``BLUEFOG_CP_FAULT delay_edges`` asymmetry, with one
rank straggling by a per-round sleep. Free-running rounds (no per-round
barrier): the straggler genuinely falls behind in published ``opt.step``,
which is the step-counter-spread signal the controller's in-degree lever
consumes. Controller ticks ride the production funnels (heartbeat tail +
the per-round ``tuner.maybe_tick`` the optimizer step tail mirrors).

The jax mesh stays single-device per controller (CPU multiprocess
collectives are unavailable — the win_microbench constraint), so the
gossip rides numpy rows through the window plane exactly like
scripts/_win_microbench_child.py.

Configuration via env (set by the parent): BLUEFOG_TB_CONFIG (row
label), BLUEFOG_TB_SECONDS (timed duration), BLUEFOG_TB_STRAGGLER
(rank), BLUEFOG_TB_STRAGGLE_MS (its per-round sleep).
"""

import json
import os
import struct
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np  # noqa: E402

import jax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu import optimizers as O  # noqa: E402
from bluefog_tpu.ops import windows as win_mod  # noqa: E402
from bluefog_tpu.runtime import control_plane  # noqa: E402
from bluefog_tpu.runtime import metrics as mx  # noqa: E402
from bluefog_tpu.runtime import tuner  # noqa: E402

N = 4
ELEMS = 4096  # 16 KB f32 rows: wire-meaningful, mailbox-cap safe
WARMUP = 3

CONFIG = os.environ.get("BLUEFOG_TB_CONFIG") or "static-none"
DURATION = float(os.environ.get("BLUEFOG_TB_SECONDS", "12"))
STRAGGLER = int(os.environ.get("BLUEFOG_TB_STRAGGLER", "3"))
STRAGGLE_MS = float(os.environ.get("BLUEFOG_TB_STRAGGLE_MS", "150"))


def put_f(cl, key, v):
    cl.put(key, struct.unpack("<q", struct.pack("<d", float(v)))[0])


def get_f(cl, key):
    return struct.unpack("<d", struct.pack("<q", cl.get(key)))[0]


def main() -> int:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N and control_plane.world() == N
    bf.set_topology(bf.topology_util.ExponentialTwoGraph(N))
    cl = control_plane.client()

    x = np.zeros((N, ELEMS), np.float32)
    x[:] = np.arange(N, dtype=np.float32)[:, None]
    name = "tb.win"
    assert bf.win_create(x, name, zero_init=True)
    win = win_mod._get_window(name)
    control_plane.barrier("tb.sync")

    def gossip_round():
        # the optimizer gossip shape (optimizers._gossip): demoted edges
        # drop from the send table — skipping the deposit is where the
        # demotion saves both the wire bytes and the injected edge delay
        demoted = tuner.demoted_edges()
        send = O._healed_send_table(win, set(), None, demoted)
        sw, nw = O._healed_recv_weights(win, set(), None, None, demoted)
        bf.win_put(x, name, dst_weights=send)
        bf.win_update(name, sw, nw)

    for _ in range(WARMUP):
        gossip_round()
    control_plane.barrier("tb.warm")

    bytes0 = mx.counter("win.deposit_bytes").value
    rounds = 0
    first_demote = None
    t_start = time.monotonic()
    t_end = t_start + DURATION
    while time.monotonic() < t_end:
        gossip_round()
        rounds += 1
        mx.gauge("opt.step").set(rounds)
        mx.maybe_publish(cl)
        tuner.maybe_tick(cl)
        if first_demote is None and tuner.demoted_edges():
            first_demote = time.monotonic() - t_start
        if pid == STRAGGLER:
            time.sleep(STRAGGLE_MS / 1e3)
    wire_mb = (mx.counter("win.deposit_bytes").value - bytes0) / 1e6

    put_f(cl, f"tb.rounds.{pid}", rounds)
    put_f(cl, f"tb.wire.{pid}", wire_mb)
    put_f(cl, f"tb.tdem.{pid}", -1.0 if first_demote is None
          else first_demote)
    control_plane.barrier("tb.done")
    if pid == 0:
        per_rounds = [int(get_f(cl, f"tb.rounds.{p}")) for p in range(N)]
        per_wire = [round(get_f(cl, f"tb.wire.{p}"), 2) for p in range(N)]
        tdems = [get_f(cl, f"tb.tdem.{p}") for p in range(N)]
        tdems = [t for t in tdems if t >= 0]
        healthy = [per_rounds[p] for p in range(N) if p != STRAGGLER]
        row = {
            "config": CONFIG,
            "seconds": DURATION,
            "rounds": per_rounds,
            "healthy_steps_per_s": round(sum(healthy) / DURATION, 1),
            "straggler_steps_per_s": round(
                per_rounds[STRAGGLER] / DURATION, 1),
            "wire_mb": per_wire,
            "time_to_first_demotion_s": (round(min(tdems), 2)
                                         if tdems else None),
            "demoted_final": sorted(list(e)
                                    for e in tuner.demoted_edges()),
        }
        try:
            blob = cl.get_bytes(tuner.TUNE_KEY_FMT.format(rank=0))
            if blob:
                doc = json.loads(bytes(blob).decode())
                row["decision_trail"] = [
                    d for d in doc.get("decisions", [])
                    if d.get("status") == "applied"]
        except OSError:
            pass
        print(json.dumps(row), flush=True)
    control_plane.barrier("tb.exit")
    # Skip bf.shutdown() + the jax.distributed atexit teardown: on the
    # single-core CI box the staggered interpreter exits can hold one
    # task past the coordination-service heartbeat window while it sits
    # in the shutdown barrier, SIGABRTing the whole job AFTER every
    # result is posted. All rows are on the wire by the barrier above;
    # a hard exit is the reliable teardown for this harness.
    time.sleep(1.0)  # let the slowest rank observe the barrier release
    sys.stdout.flush()
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
