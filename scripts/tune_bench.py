"""Self-tuning controller benchmark: BLUEFOG_TUNE=1 vs static configs
under an injected straggler + per-edge delay asymmetry (the PR 16
acceptance experiment, PERF.md "self-tuning" section).

Launches 4 REAL controller processes through ``bfrun`` (auth ON, the
win_microbench pattern) three times — identical fault injection each
run, only the tuning config differs:

  static-none   no wire codec, no controller
  static-int8   BLUEFOG_WIN_CODEC=int8 (the best static answer that
                doesn't change the graph)
  tuned         BLUEFOG_TUNE=1 with bench-cadence rules (straggler_for=2,
                dwell=5, keep_in=1; codec lever parked via slow_ratio=0 —
                transit percentiles live in the receiver's store, so the
                in-degree lever is the one under test here)

Fault shape (BLUEFOG_CP_FAULT delay_edges + a sleeping rank):

  * every deposit on 0>1, 1>3 and 2>3 pays +60 ms — each healthy rank
    owns exactly ONE delayed out-edge, so their untuned round rates are
    comparable and any win is attributable to the controller;
  * rank 3 additionally sleeps 150 ms per round — the sustained
    straggler whose step-counter spread the in-degree lever demotes.

Static configs pay the delayed edges forever (int8 shrinks bytes but a
fixed per-deposit delay doesn't care). The tuned run's leader demotes
the straggler's slowest in-edges with total-preserving renorm; the
freed senders skip both the bytes AND the injected delay, so healthy
aggregate steps/s must beat both statics — that number, plus wire MB,
time-to-first-demotion, and rank 0's decision trail, is the output.

Each child prints one JSON row (rank 0 only); this parent relays them
and renders the PERF.md markdown table at the end.

Usage:  python scripts/tune_bench.py [--quick] [--seconds N]
  --quick: 4 s timed window per config — shakes out harness bugs in
           ~30 s; numbers are NOT meaningful for PERF.md.
"""

import argparse
import json
import os
import secrets
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

DELAY_SPEC = "delay_edges=0>1:60,1>3:60,2>3:60"
TUNED_RULES = "slow_ratio=0,straggler_for=2,dwell=5,keep_in=1"

CONFIGS = [
    ("static-none", {}),
    ("static-int8", {"BLUEFOG_WIN_CODEC": "int8"}),
    ("tuned", {"BLUEFOG_TUNE": "1",
               "BLUEFOG_TUNE_INTERVAL": "0.5",
               "BLUEFOG_TUNE_RULES": TUNED_RULES}),
]


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_config(label: str, extra_env: dict, seconds: float) -> dict:
    env = os.environ.copy()
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT", "BLUEFOG_WIN_CODEC",
              "BLUEFOG_TUNE", "BLUEFOG_TUNE_INTERVAL",
              "BLUEFOG_TUNE_RULES", "BLUEFOG_CP_FAULT"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["BLUEFOG_CP_SECRET"] = secrets.token_hex(16)
    env["BLUEFOG_CP_FAULT"] = DELAY_SPEC
    # bench cadences: publish/tick fast enough that a 12 s window holds
    # detection (straggler_for=2 sustained) + dwell + recovery headroom
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.5"
    env["BLUEFOG_METRICS_INTERVAL"] = "0.5"
    env["BLUEFOG_TS_INTERVAL"] = "0.5"
    env["BLUEFOG_TB_CONFIG"] = label
    env["BLUEFOG_TB_SECONDS"] = str(seconds)
    env.update(extra_env)

    port = free_port()
    child = str(REPO / "scripts" / "_tune_bench_child.py")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "bluefog_tpu.launcher", "-np", "4",
             "--coordinator", f"127.0.0.1:{port}", "--process-id", str(i),
             "--simulate", "1", "--", sys.executable, child],
            env=env,
            stdout=subprocess.PIPE if i == 0 else subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        for i in range(4)
    ]
    row = None
    out, _ = procs[0].communicate(timeout=600)
    for p in procs[1:]:
        p.wait(timeout=600)
    for line in out.decode(errors="replace").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            if doc.get("config") == label:
                row = doc
    rcs = [p.returncode for p in procs]
    if any(rcs) or row is None:
        raise SystemExit(f"tune_bench: config {label} failed "
                         f"(rcs={rcs}, row={'ok' if row else 'missing'})")
    return row


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seconds", type=float, default=None)
    args = ap.parse_args()
    seconds = args.seconds or (4.0 if args.quick else 12.0)

    rows = []
    for label, extra in CONFIGS:
        print(f"# tune_bench: {label} ({seconds:g}s timed)...",
              file=sys.stderr, flush=True)
        row = run_config(label, extra, seconds)
        print(json.dumps(row), flush=True)
        rows.append(row)

    by = {r["config"]: r for r in rows}
    tuned, none_, int8 = by["tuned"], by["static-none"], by["static-int8"]
    print("\n| config | healthy steps/s (sum of 3) | straggler steps/s "
          "| wire MB (per rank) | first demotion |")
    print("|---|---|---|---|---|")
    for r in rows:
        t = r.get("time_to_first_demotion_s")
        print(f"| {r['config']} | {r['healthy_steps_per_s']} "
              f"| {r['straggler_steps_per_s']} "
              f"| {', '.join(str(w) for w in r['wire_mb'])} "
              f"| {t if t is not None else '—'} s |"
              .replace("| None s |", "| — |"))
    best_static = max(none_["healthy_steps_per_s"],
                      int8["healthy_steps_per_s"])
    win = tuned["healthy_steps_per_s"] / best_static if best_static else 0
    print(f"\n# tuned vs best static: {win:.2f}x healthy throughput; "
          f"demoted_final={tuned.get('demoted_final')}", flush=True)
    if not args.quick:
        assert tuned["healthy_steps_per_s"] > best_static, (
            "acceptance: BLUEFOG_TUNE=1 must beat both static configs "
            f"({tuned['healthy_steps_per_s']} vs {best_static})")
        assert tuned.get("demoted_final"), \
            "tuned run ended with no demoted edges"
    print("TUNE_BENCH_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
