#!/usr/bin/env python
"""Raw time-series publisher child (obs-smoke's SIGKILL victim).

Attaches a plain control-plane client (no jax, no mesh join) and
publishes a minimal ``bf.ts.<rank>`` delta stream on a short cadence —
a stand-in for a remote controller's heartbeat-tick publication. The
harness SIGKILLs it and asserts ``bfrun --top`` names the rank SILENT
once the stream goes stale.

Usage: _ts_pub_child.py HOST PORT RANK INTERVAL_SEC
"""

import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from bluefog_tpu.runtime import timeseries as ts  # noqa: E402
from bluefog_tpu.runtime.native import ControlPlaneClient  # noqa: E402


def main() -> int:
    host, port, rank, interval = (sys.argv[1], int(sys.argv[2]),
                                  int(sys.argv[3]), float(sys.argv[4]))
    import os

    cl = ControlPlaneClient(host, port, 0,
                            secret=os.environ.get("BLUEFOG_CP_SECRET", ""),
                            streams=1)
    store = ts.TimeSeriesStore()
    step = 0
    print("TS_CHILD_READY", flush=True)
    while True:
        now = time.time()
        step += 1
        store.series("opt.step", "gauge", "last").add(now, step)
        store._record_rate("opt.step", now, float(step))
        doc = store.build_doc(rank, 0, now, interval)
        cl.put_bytes(ts.TS_KEY_FMT.format(rank=rank), ts.pack_doc(doc))
        time.sleep(interval)


if __name__ == "__main__":
    sys.exit(main())
