"""Child process for scripts/win_microbench.py (one of 4 controllers).

Times the HOSTED window data plane — the cross-controller (DCN-analog)
transport where every put/accumulate ships tensor bytes through the
authenticated control-plane server and win_update drains them (VERDICT r4
weak #1: this plane had zero performance evidence).

Each config creates a 4-rank window (1 rank per controller) on a
bidirectional ring, so every win_put/win_accumulate deposits the full row
to 2 remote owners and every win_update drains 2 slots. Per-op wall times
go to the control plane; controller 0 aggregates and prints one JSON line
per (config, op).

Reference analog: the win_put path the reference benchmarked as its
headline async mode (examples/pytorch_benchmark.py:52-60) rode chunked
MPI_Put with BLUEFOG_MAX_WIN_SENT_LENGTH (mpi_controller.cc:41-46,
932-1034); this is the measurement that holds our transport to the same
standard.
"""

import json
import os
import struct
import time

import numpy as np
import ml_dtypes

import jax

import bluefog_tpu as bf
from bluefog_tpu.runtime import control_plane

N = 4

# (tag, dtype, elements). Rows sized per VERDICT r4 #1: ResNet-50-ish
# (102 MB of f32) and small (1 MB); the bf16 config exposes the wire-dtype
# cost (acc-dtype deposits ship 2x the window bytes).
CONFIGS = [
    ("f32_102MB", np.float32, 25_600_000, 4),
    ("f32_1MB", np.float32, 262_144, 30),
    ("bf16_51MB", ml_dtypes.bfloat16, 25_600_000, 4),
]
# Explicit warmup ops excluded from every timed series (r7, de-noising):
# the first ops of a kind pay compile + connection-pool + allocator +
# page-cache costs that r6's medians let leak into win_put (37.9 vs 51.5
# MB/s run-to-run on identical configs) — measured, the server/client
# heaps take ~3 full 1.2 GB rounds to reach steady state on the CI box.
# Timed rounds are steady-state medians.
WARMUP = 3
# --quick (CI smoke): tiny rows, 2 rounds — the full op/probe matrix still
# runs, the numbers just don't mean anything
if os.environ.get("BLUEFOG_WB_QUICK") == "1":
    CONFIGS = [
        ("f32_256KB", np.float32, 65_536, 2),
        ("bf16_32KB", ml_dtypes.bfloat16, 16_384, 2),
    ]
    WARMUP = 1


def barrier():
    # Control-plane rendezvous, NOT bf.barrier(): the compiled psum barrier
    # needs multiprocess XLA collectives (unimplemented on the CPU
    # backend), and this bench synchronizes PROCESSES around host-plane
    # ops, not device work — the named barrier is the right primitive.
    control_plane.barrier("wb.sync")


def put_f(cl, key, v):
    cl.put(key, struct.unpack("<q", struct.pack("<d", float(v)))[0])


def get_f(cl, key):
    return struct.unpack("<d", struct.pack("<q", cl.get(key)))[0]


def report(cl, pid, config, op, times, wire_bytes, codec=None):
    """Post my median; pid 0 prints the slowest controller's number.

    ``codec`` rows come from the ``--codec`` sweep: ``mbps`` stays the
    EFFECTIVE rate (app-level payload bytes / wall time — the acceptance
    metric for the compressed wire), while the shrunken on-wire byte
    count shows up as wall time, not in ``wire_mb``."""
    med = float(np.median(times))
    key = f"wb.{config}.{codec or ''}.{op}.{pid}"
    put_f(cl, key, med)
    barrier()
    if pid == 0:
        meds = [get_f(cl, f"wb.{config}.{codec or ''}.{op}.{p}")
                for p in range(N)]
        worst = max(meds)
        row = {
            "config": config, "op": op,
            "median_ms": round(worst * 1e3, 3),
            "mbps": round(wire_bytes / worst / 1e6, 1) if wire_bytes else None,
            "wire_mb": round(wire_bytes / 1e6, 2),
            "per_controller_ms": [round(m * 1e3, 3) for m in meds],
        }
        if codec:
            row["codec"] = codec
        print(json.dumps(row), flush=True)
    barrier()


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N and control_plane.world() == N
    bf.set_topology(bf.topology_util.RingGraph(N))
    cl = control_plane.client()

    for tag, dtype, elems, rounds in CONFIGS:
        row_bytes = elems * np.dtype(dtype).itemsize
        x = np.zeros((N, elems), dtype)
        x[:] = np.arange(N, dtype=np.float32)[:, None].astype(dtype)
        name = f"wb.{tag}"
        assert bf.win_create(x, name, zero_init=True)
        barrier()

        # -- win_put: 2 remote deposits + 1 self publish per op ------------
        ts = []
        for r in range(WARMUP + rounds):
            barrier()
            t0 = time.perf_counter()
            bf.win_put(x, name)
            if r >= WARMUP:
                ts.append(time.perf_counter() - t0)
            # keep server memory bounded: drain between rounds
            barrier()
            bf.win_update(name)
        # wire bytes OUT per op: 2 deposits + 1 publish (deposit payload
        # dtype is whatever the transport ships — report the app-level
        # window bytes so before/after MB/s are comparable)
        report(cl, pid, tag, "win_put", ts, 3 * row_bytes)

        # -- win_accumulate ------------------------------------------------
        ts = []
        for r in range(WARMUP + rounds):
            barrier()
            t0 = time.perf_counter()
            bf.win_accumulate(x, name)
            if r >= WARMUP:
                ts.append(time.perf_counter() - t0)
            barrier()
            bf.win_update(name)
        report(cl, pid, tag, "win_accumulate", ts, 3 * row_bytes)

        # -- win_update with 2 pending deposits per slot -------------------
        ts = []
        for r in range(WARMUP + rounds):
            bf.win_put(x, name)
            barrier()  # all deposits on the server before the drain
            t0 = time.perf_counter()
            bf.win_update(name)
            if r >= WARMUP:
                ts.append(time.perf_counter() - t0)
            barrier()
        report(cl, pid, tag, "win_update", ts, 2 * row_bytes)

        # -- win_get: pull 2 published remote rows -------------------------
        ts = []
        for r in range(WARMUP + rounds):
            barrier()
            t0 = time.perf_counter()
            bf.win_get(name)
            if r >= WARMUP:
                ts.append(time.perf_counter() - t0)
        report(cl, pid, tag, "win_get", ts, 2 * row_bytes)

        barrier()
        bf.win_free(name)

        # -- transport ceiling: raw put_bytes/get_bytes of one row, at the
        # full striped pool (the default client) AND pinned to ONE stream
        # (a dedicated streams=1 client) — the r7 raw-ceiling probe, so a
        # transport regression in either regime shows up in the same run.
        blob = x[0].tobytes()
        cl1 = control_plane.extra_client(streams=1)
        for label, c in (("", cl), ("_1s", cl1)):
            ts = []
            for r in range(WARMUP + rounds):
                barrier()
                t0 = time.perf_counter()
                c.put_bytes(f"wb.raw.{pid}", blob)
                if r >= WARMUP:
                    ts.append(time.perf_counter() - t0)
            report(cl, pid, tag, f"raw_put_bytes{label}", ts, row_bytes)
            ts = []
            for r in range(WARMUP + rounds):
                barrier()
                t0 = time.perf_counter()
                c.get_bytes(f"wb.raw.{pid}")
                if r >= WARMUP:
                    ts.append(time.perf_counter() - t0)
            report(cl, pid, tag, f"raw_get_bytes{label}", ts, row_bytes)
        cl1.close()
        cl.put_bytes(f"wb.raw.{pid}", b"")

        # -- fold-vs-stream isolation (r6): the same 2-deposit drain load,
        # timed as (a) the raw socket take alone and (b) the numpy fold
        # alone. The gap between win_update and max(stream, fold) is the
        # serialization the pipelined drain removes; BOTH numbers together
        # bound what any drain implementation can reach.
        chunk = 16 << 20  # the default BLUEFOG_MAX_WIN_SENT_LENGTH framing
        blob = x[0].tobytes()
        recs = [blob[o:o + chunk] for o in range(0, len(blob), chunk)] * 2
        key = f"wb.fvs.{pid}"
        staging = np.empty(2 * row_bytes, np.uint8)
        acc = np.zeros(elems, np.float32)
        t_stream, t_fold = [], []
        for _ in range(rounds):
            cl.append_bytes_many([key] * len(recs), recs)
            barrier()
            t0 = time.perf_counter()
            got = []
            while True:  # >64 MiB backlogs drain over multiple takes
                part = cl.take_bytes(key)
                if not part:
                    break
                got.extend(part)
            t1 = time.perf_counter()
            off = 0
            for r_ in got:
                staging[off:off + len(r_)] = np.frombuffer(r_, np.uint8)
                off += len(r_)
            for dep in range(2):
                contrib = staging[dep * row_bytes:(dep + 1) * row_bytes] \
                    .view(dtype)
                np.add(acc, contrib.astype(np.float32, copy=False), out=acc)
            t2 = time.perf_counter()
            t_stream.append(t1 - t0)
            t_fold.append(t2 - t1)
        report(cl, pid, tag, "drain_stream", t_stream, 2 * row_bytes)
        report(cl, pid, tag, "drain_fold", t_fold, 2 * row_bytes)

    # -- compressed-wire sweep (--codec, ISSUE r15): replay the win_put /
    # win_update series of the FIRST (headline) config under each codec.
    # mbps stays payload-bytes / wall-time, so `codec != none` rows read
    # directly as EFFECTIVE throughput against the same-run uncompressed
    # numbers above (the >= 2x int8 win_update acceptance bar); the extra
    # compression_ratio field reports raw/wire bytes from the metrics
    # registry.
    codecs = [c for c in os.environ.get("BLUEFOG_WB_CODECS", "").split(",")
              if c and c != "none"]
    if codecs:
        from bluefog_tpu.runtime import metrics as _metrics

        tag, dtype, elems, rounds = CONFIGS[0]
        row_bytes = elems * np.dtype(dtype).itemsize
        x = np.zeros((N, elems), dtype)
        x[:] = np.arange(N, dtype=np.float32)[:, None].astype(dtype)
        def _codec_counters():
            c = _metrics.snapshot().get("counters", {})
            return (c.get("win.codec.raw_bytes", 0.0),
                    c.get("win.codec.wire_bytes", 0.0))

        for codec in codecs:
            os.environ["BLUEFOG_WIN_CODEC"] = codec
            name = f"wb.cx.{codec}"
            raw0, wire0 = _codec_counters()
            try:
                assert bf.win_create(x, name, zero_init=True)
                barrier()
                ts = []
                for r in range(WARMUP + rounds):
                    barrier()
                    t0 = time.perf_counter()
                    bf.win_put(x, name)
                    if r >= WARMUP:
                        ts.append(time.perf_counter() - t0)
                    barrier()
                    bf.win_update(name)
                report(cl, pid, tag, "win_put", ts, 3 * row_bytes,
                       codec=codec)
                ts = []
                for r in range(WARMUP + rounds):
                    bf.win_put(x, name)
                    barrier()
                    t0 = time.perf_counter()
                    bf.win_update(name)
                    if r >= WARMUP:
                        ts.append(time.perf_counter() - t0)
                    barrier()
                report(cl, pid, tag, "win_update", ts, 2 * row_bytes,
                       codec=codec)
                if pid == 0:
                    # delta vs the sweep start: counters are cumulative
                    # process-global, and earlier codecs' bytes would
                    # otherwise blend into this codec's ratio
                    raw1, wire1 = _codec_counters()
                    raw, wire = raw1 - raw0, wire1 - wire0
                    print(json.dumps({
                        "config": tag, "op": "compression_ratio",
                        "codec": codec,
                        "ratio": round(raw / wire, 2) if wire else None,
                    }), flush=True)
                barrier()
                bf.win_free(name)

                # wire-leg isolation (the codec analog of the
                # fold-vs-stream probe): socket-take the SAME 2-deposit
                # backlog in its ENCODED form and decode it — the leg
                # the codec compresses, reported at the app-level
                # effective rate. On wire-bound paths this ratio is what
                # a full win_update converges to; on a CPU-bound
                # loopback box the full-op number also pays the
                # combine/publish legs the codec cannot shrink
                # (PERF.md r15 discusses both).
                from bluefog_tpu.ops import codec as _cd

                cobj = _cd.resolve(codec)
                enc = cobj.encode(x[0]).tobytes()
                chunk = 16 << 20
                recs2 = [enc[o:o + chunk]
                         for o in range(0, len(enc), chunk)] * 2
                key = f"wb.cfvs.{pid}"
                ts = []
                dec_out = np.empty(elems, np.float32)
                for _ in range(rounds):
                    cl.append_bytes_many([key] * len(recs2), recs2)
                    barrier()
                    t0 = time.perf_counter()
                    got = []
                    while True:
                        part = cl.take_bytes(key)
                        if not part:
                            break
                        got.extend(part)
                    buf = b"".join(bytes(r) for r in got)
                    for dep in range(2):
                        seg = np.frombuffer(
                            buf, np.uint8)[dep * len(enc):
                                           (dep + 1) * len(enc)]
                        cobj.decode(seg, np.float32, elems, out=dec_out)
                    ts.append(time.perf_counter() - t0)
                report(cl, pid, tag, "drain_stream", ts, 2 * row_bytes,
                       codec=codec)
            finally:
                os.environ.pop("BLUEFOG_WIN_CODEC", None)

    # -- sharded-window sweep (--sharded, ISSUE r17): replay win_put /
    # win_update on shard-row-sized windows and COUNTER-DELTA-VERIFY the
    # wire-byte claim — shard factor S cuts per-op deposit bytes by
    # ≥ 0.9·S (win.deposit_bytes counts exactly the bytes handed to the
    # server wire, headers included, per controller). mbps rows report
    # the shard row's payload rate at the same op shape as the full
    # window's series above (docs/sharded_windows.md).
    factors = [int(f) for f in os.environ.get("BLUEFOG_WB_SHARD",
                                              "").split(",") if f]
    if factors:
        from bluefog_tpu.ops import windows as _win_ops
        from bluefog_tpu.runtime import metrics as _metrics2

        tag, dtype, elems, rounds = CONFIGS[0]

        def dep_bytes():
            return _metrics2.snapshot().get("counters", {}).get(
                "win.deposit_bytes", 0.0)

        per_op: dict = {}
        for S in [1] + factors:
            rl = -(-elems // S)
            xs = np.zeros((N, rl), dtype)
            xs[:] = np.arange(N, dtype=np.float32)[:, None].astype(dtype)
            name = f"wb.sh.{S}"
            assert bf.win_create(xs, name, zero_init=True)
            win = _win_ops._get_window(name)
            if S > 1:
                win.bind_shard(S)
            barrier()
            nops = WARMUP + rounds
            ts = []
            b0 = dep_bytes()
            for r in range(nops):
                barrier()
                t0 = time.perf_counter()
                bf.win_put(xs, name)
                if r >= WARMUP:
                    ts.append(time.perf_counter() - t0)
                barrier()
                bf.win_update(name)
                if S > 1:
                    win.set_active_shard((r + 1) % S)  # rotate like the
                    # optimizer's comm-round schedule
            per_op[S] = (dep_bytes() - b0) / nops
            row_b = rl * np.dtype(dtype).itemsize
            report(cl, pid, tag, f"sharded_s{S}.win_put", ts, 3 * row_b)
            if pid == 0:
                print(json.dumps({
                    "config": tag, "op": "win_put", "sharded": S,
                    "wire_bytes_per_op": round(per_op[S], 1)}), flush=True)
            barrier()
            bf.win_free(name)
        if pid == 0:
            for S in factors:
                red = per_op[1] / per_op[S] if per_op[S] else 0.0
                ok = red >= 0.9 * S
                print(json.dumps({
                    "config": tag, "op": "shard_wire_reduction",
                    "sharded": S, "reduction_x": round(red, 2),
                    "bar": round(0.9 * S, 2), "ok": bool(ok)}), flush=True)
                assert ok, (
                    f"shard factor {S} cut win-op wire bytes only "
                    f"{red:.2f}x (< 0.9*S): {per_op[1]:.0f} -> "
                    f"{per_op[S]:.0f} B/op")

    bf.shutdown()
    if pid == 0:
        print("WIN_MICROBENCH_OK", flush=True)


if __name__ == "__main__":
    main()
