#!/usr/bin/env python
"""Telemetry-plane smoke test (`make metrics-smoke`).

A 2-rank in-process job with the control plane + hosted window plane
forced on and metrics publication enabled, asserting the acceptance
surface of the telemetry plane end to end:

  * the metrics hot path stays cheap: a counter increment costs < 100 ns
    (the disabled-by-default publication gate has nothing to gate — the
    increment IS the whole cost);
  * a push-sum optimizer job publishes a non-empty packed snapshot to the
    control-plane KV and a non-empty, well-formed Prometheus scrape file;
  * ``bf.cluster_health()`` reports per-rank step counters and exact mass
    conservation;
  * ``bfrun --status`` prints the same view from a SEPARATE process.

Exits non-zero (with a message) on any violated assertion.
"""

import os
import re
import socket
import subprocess
import sys
import tempfile
import timeit

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

_s = socket.socket()
_s.bind(("127.0.0.1", 0))
PORT = _s.getsockname()[1]
_s.close()

PROM = os.path.join(tempfile.mkdtemp(prefix="bf_metrics_"), "scrape.prom")
os.environ.update({
    "BLUEFOG_CP_HOST": "127.0.0.1",
    "BLUEFOG_CP_PORT": str(PORT),
    "BLUEFOG_CP_WORLD": "1",
    "BLUEFOG_CP_RANK": "0",
    "BLUEFOG_WIN_HOST_PLANE": "1",
    "BLUEFOG_METRICS_INTERVAL": "1",
    "BLUEFOG_METRICS_PROM": PROM,
})

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu.runtime import metrics as metrics_mod  # noqa: E402


def check(cond, msg):
    if not cond:
        print(f"metrics-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def microbench_counter_ns() -> float:
    """Per-call cost of a bound counter increment.

    Two de-noising measures: the calls are unrolled 10x per loop
    iteration so timeit's own for-loop scaffolding (~15-20 ns/iter on
    this interpreter) amortizes out of the per-call figure, and the min
    is taken over many SHORT windows — the true cost is the fastest
    window, and on a loaded CI box a 2 ms quiet slice is far likelier
    than a 150 ms one."""
    c = metrics_mod.counter("smoke.bench")
    unroll = 10
    n = 2_000
    stmt = ";".join(["inc()"] * unroll)
    best = min(timeit.repeat(stmt, globals={"inc": c.inc},
                             number=n, repeat=60)) / (n * unroll)
    return best * 1e9


def main() -> int:
    # 1) hot path: the increment is the entire cost, telemetry on or off
    ns = microbench_counter_ns()
    print(f"counter increment: {ns:.0f} ns/call")
    check(ns < 100.0, f"counter increment costs {ns:.0f} ns (budget 100)")

    # 2) a real 2-rank job publishing through the control plane
    bf.init(devices=jax.devices("cpu")[:2])

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zloss,
                                         window_prefix="smoke.ps")
    state = opt.init({"w": jnp.ones((8,), jnp.float32)})
    for _ in range(4):
        state, _ = opt.step(state, jnp.zeros((2, 1), jnp.float32))

    snap = metrics_mod.publish_now()
    check(snap is not None, "publish_now returned nothing")
    check(snap["counters"] or snap["gauges"], "empty snapshot")

    # KV scrape is non-empty and unpacks
    from bluefog_tpu.runtime import control_plane as cp
    blob = cp.client().get_bytes("bf.metrics.0")
    check(len(blob) > 0, "no packed snapshot under bf.metrics.0")
    back = metrics_mod.unpack_snapshot(blob)
    check(back["gauges"].get("opt.step") == 4.0,
          f"published step gauge wrong: {back['gauges'].get('opt.step')}")

    # 3) cluster health: per-rank steps + exact mass conservation
    health = bf.cluster_health()
    print(metrics_mod.format_health(health))
    check(health["ranks"], "cluster_health reported no ranks")
    check(health["ranks"][0]["step"] == 4, "per-rank step counter wrong")
    check(health["mass"] is not None and health["mass"]["conserved"],
          f"push-sum mass not conserved: {health['mass']}")
    check(not health["stragglers"], "phantom straggler on a healthy job")

    # 4) prometheus scrape file: non-empty, format-linted
    with open(PROM) as f:
        text = f.read()
    check(text.strip(), "prometheus scrape file is empty")
    metric_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+(\s+\d+)?$")
    prom_lines = text.strip().splitlines()
    for i, line in enumerate(prom_lines):
        if line.startswith("# TYPE"):
            check(re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                           r"(counter|gauge|histogram)$", line),
                  f"bad TYPE line: {line!r}")
            # every family must be self-describing: HELP precedes TYPE
            m = line.split()[2]
            check(i > 0 and prom_lines[i - 1].startswith(f"# HELP {m} "),
                  f"TYPE without a preceding HELP line: {line!r}")
        elif line.startswith("#"):
            check(re.match(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S", line),
                  f"bad HELP line: {line!r}")
        else:
            check(metric_re.match(line), f"bad metric line: {line!r}")
    check("bluefog_opt_step" in text, "opt.step missing from the scrape")

    # 5) bfrun --status from a separate process sees the same view
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--status"],
        env=env, capture_output=True, text=True, timeout=120)
    print(out.stdout, end="")
    check(out.returncode == 0, f"bfrun --status failed: {out.stderr}")
    check("rank 0" in out.stdout and "step 4" in out.stdout,
          f"--status output missing rank/step: {out.stdout!r}")
    check("conserved" in out.stdout, "--status output missing mass check")

    # --strict on a HEALTHY job must still exit 0 (it only reds on
    # dead/straggler/mass-drift findings)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--status",
         "--strict"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0,
          f"--status --strict nonzero on a healthy job: {out.stderr}")

    opt.free()
    bf.shutdown()
    print("metrics-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
