# Test/bench targets, the analog of the reference's Makefile (whose targets
# wrap pytest under mpirun; here the multi-process harness is the 8-device
# CPU-simulated mesh — see tests/conftest.py and SURVEY.md §4).

PYTEST      = python -m pytest
MESH_ENV    = JAX_PLATFORMS='' XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: test test_fast test_ops test_win_ops test_optimizers test_parallel \
        test_launcher test_models bench chaos dryrun native scaling \
        lm_bench metrics-smoke flight-smoke soak-smoke obs-smoke \
        tune-smoke serve-smoke slo-smoke perf-gate lint bfcheck check \
        tsan asan

# Test files replayed under the sanitizers: the chaos suite (reconnect /
# dedup / fencing churn) plus the striped-transport + hosted-window stress
# tests — the paths that hammer the native layer's concurrency.
SANITIZE_TESTS = tests/test_chaos.py tests/test_hosted_windows.py

test:            ## full suite (~15 min on the single-core CI box)
	$(PYTEST) tests/ -q

test_fast:       ## the pre-commit gate: quick subset (skips @slow)
	$(PYTEST) tests/ -q -m "not slow"

# per-area targets mirroring the reference's test_torch_ops / test_torch_win_ops / ...
test_ops:
	$(PYTEST) tests/test_ops.py tests/test_basics.py tests/test_topology.py -q

test_win_ops:
	$(PYTEST) tests/test_win_ops.py -q

test_optimizers:
	$(PYTEST) tests/test_optimizers.py tests/test_optimization.py -q

test_parallel:
	$(PYTEST) tests/test_parallel.py tests/test_transformer_cp.py \
	    tests/test_tensor_parallel.py tests/test_pipeline_parallel.py \
	    tests/test_expert_parallel.py tests/test_flash.py -q

test_launcher:
	$(PYTEST) tests/test_launcher.py tests/test_heartbeat.py -q

test_models:
	$(PYTEST) tests/test_models.py tests/test_torch_interop.py -q

bench:           ## headline benchmark on the default backend (real chip)
	python bench.py

metrics-smoke:   ## telemetry-plane acceptance: 2-rank in-process job with a
                 ## non-empty KV scrape + health snapshot + prometheus lint,
                 ## bfrun --status from a separate process, and the < 100 ns
                 ## counter-increment microbench
	JAX_PLATFORMS=cpu python scripts/metrics_smoke.py

flight-smoke:    ## flight-recorder acceptance: < 1500 ns ring-record
                 ## microbench, step-time attribution over a real hosted
                 ## job, parseable dumps, and bfrun --dump retrieving a
                 ## merged clock-synced trace from a separate process
	JAX_PLATFORMS=cpu python scripts/flight_smoke.py

obs-smoke:       ## live-telemetry-plane acceptance: < 2 µs/record ring
                 ## sampling microbench, a 2-rank job streaming non-empty
                 ## bf.ts.* deltas (consensus gauge + per-edge
                 ## estimators), bfrun --top one-shot render from a
                 ## separate process naming a SIGKILLed publisher SILENT,
                 ## ts_export JSON-lines + OpenMetrics lint, and
                 ## step_attribution --live without a dump
	JAX_PLATFORMS=cpu python scripts/obs_smoke.py

tune-smoke:      ## self-tuning-controller acceptance: 4-rank in-process
                 ## job with armed delay_edges asymmetry — zero decisions
                 ## while healthy, slow-edge codec escalation, straggler
                 ## demotion within 4 ticks with numpy-oracle parity of
                 ## the healed tables, exact demote->promote round-trip,
                 ## and the bf.tune.* trail rendered by bfrun --top
	JAX_PLATFORMS=cpu python scripts/tune_smoke.py

serve-smoke:     ## serving-plane acceptance: 2-rank trainer publishing
                 ## every comm step + one read-only serve client — hot-swap
                 ## on fence bumps while training continues, batched
                 ## replies matching a numpy oracle on the swapped-in
                 ## snapshot, queue_full shedding with every admitted
                 ## future still resolving, and bfrun --serve/--status
                 ## attaching from a separate process (docs/serving.md)
	JAX_PLATFORMS=cpu python scripts/serve_smoke.py

slo-smoke:       ## request-path tracing + SLO-engine acceptance
                 ## (docs/slo.md): < 2 µs per-request trace record gate,
                 ## a publisher child + traced serve client where a
                 ## fault-injected pull delay fires the staleness
                 ## burn-rate alert (bfrun --top shows the SLO section,
                 ## --status --strict exits 2 on budget exhaustion) and
                 ## recovery clears it; the client+publisher flight
                 ## rings merge into ONE chrome trace with a cross-
                 ## process stripe flow pair and the snapshot lineage
                 ## resolving to its exact train step
	JAX_PLATFORMS=cpu python scripts/slo_smoke.py

soak-smoke:      ## durable sharded-control-plane churn soak, quick mode
                 ## (<= 4 min): WAL-replicated shard server processes,
                 ## ~64 raw clients with incarnation churn, one injected
                 ## SIGKILL — asserts ZERO lost deposit mass, exactly-once
                 ## counters continuous across the failover, health
                 ## convergence, bounded server RSS; then a second pass
                 ## with --rejoin (kill + in-place restart with snapshot
                 ## catch-up, ring converges back); then the quorum
                 ## (R=3) passes: --kill-pairs SIGKILLs a shard AND its
                 ## ring successor simultaneously (still zero loss), and
                 ## --partition arms the deterministic 2|2 network cut
                 ## (typed QuorumLostError during the window, exact
                 ## ledgers after heal). No JAX anywhere; full mode:
                 ## scripts/cp_soak.py --clients 5000 --churn --rejoin
	python scripts/cp_soak.py --quick
	python scripts/cp_soak.py --quick --rejoin
	python scripts/cp_soak.py --quick --kill-pairs
	python scripts/cp_soak.py --quick --partition

perf-gate:       ## perf regression gate: quick win_microbench +
                 ## opt_matrix_bench medians vs the committed
                 ## PERF_BASELINE.json (red beyond the band; seeded
                 ## slowdown self-check: BLUEFOG_PERF_GATE_DELAY_MS=50
                 ## must turn this target RED)
	JAX_PLATFORMS=cpu python scripts/perf_gate.py --quick

lint:            ## ruff (curated rule set, pyproject.toml) when installed;
                 ## otherwise bfcheck's stdlib-only fallback linter
	@if command -v ruff >/dev/null 2>&1; then \
	    ruff check bluefog_tpu scripts tests; \
	else \
	    echo "ruff not installed; using bfcheck's fallback linter"; \
	    python scripts/bfcheck --lint; \
	fi

bfcheck:         ## project-invariant static analysis (wire protocol, knob
                 ## registry, lock/thread discipline — docs/static_analysis.md)
	python scripts/bfcheck

check: lint bfcheck  ## the full static gate (make check = lint + bfcheck)

tsan:            ## ThreadSanitizer build of csrc + chaos/striped-stress replay
                 ## (zero reports required; csrc findings are bugs, never
                 ## suppressed — csrc/tsan.supp covers third-party libs only)
	SANITIZE=thread bash csrc/build.sh
	env BLUEFOG_NATIVE_SO=$(abspath csrc/build/libbf_runtime.tsan.so) \
	    LD_PRELOAD=$$(gcc -print-file-name=libtsan.so) \
	    TSAN_OPTIONS="exitcode=66 halt_on_error=0 suppressions=$(abspath csrc/tsan.supp)" \
	    JAX_PLATFORMS=cpu $(PYTEST) $(SANITIZE_TESTS) -q -m "not slow"

asan:            ## AddressSanitizer build of csrc + the same replay.
                 ## detect_leaks=0: CPython intentionally leaks at exit.
                 ## libstdc++ rides LD_PRELOAD next to libasan because the
                 ## python binary doesn't link it — without it ASan's init
                 ## can't resolve the real __cxa_throw and CHECK-aborts on
                 ## jaxlib/MLIR's first C++ exception.
	SANITIZE=address bash csrc/build.sh
	env BLUEFOG_NATIVE_SO=$(abspath csrc/build/libbf_runtime.asan.so) \
	    LD_PRELOAD="$$(gcc -print-file-name=libasan.so) $$(gcc -print-file-name=libstdc++.so)" \
	    ASAN_OPTIONS="detect_leaks=0 exitcode=66" \
	    JAX_PLATFORMS=cpu $(PYTEST) $(SANITIZE_TESTS) -q -m "not slow"

chaos: check metrics-smoke flight-smoke obs-smoke tune-smoke serve-smoke slo-smoke soak-smoke perf-gate  ## tier-1 chaos subset, fault injection replayed at TWO
                 ## seed offsets (BLUEFOG_CHAOS_SEED shifts every armed drop
                 ## point, so reconnect/dedup/fencing — and the telemetry
                 ## counters asserted against them — face different drop sites)
	JAX_PLATFORMS=cpu BLUEFOG_CHAOS_SEED=3 $(PYTEST) tests/test_chaos.py -q -m "not slow"
	JAX_PLATFORMS=cpu BLUEFOG_CHAOS_SEED=11 $(PYTEST) tests/test_chaos.py -q -m "not slow"

dryrun:          ## multi-chip sharding validation on the simulated mesh
	$(MESH_ENV) python -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('dryrun ok')"

native:          ## build the native runtime extension
	bash csrc/build.sh

scaling:         ## regenerate SCALING.md (compile-time scaling evidence)
	JAX_PLATFORMS=cpu python -m bluefog_tpu.scaling

lm_bench:        ## transformer tokens/s + MFU headline (real chip)
	python scripts/lm_bench.py
