"""Load torch/torchvision-format ResNet weights into the flax model zoo.

The reference's users train torchvision models (its benchmark loads
``torchvision.models.resnet50``); migrating to this framework should not
strand their checkpoints. ``resnet_from_torch`` maps a torchvision-format
``state_dict`` (``conv1.weight``, ``layer1.0.conv1.weight``, ...,
``fc.weight`` — plain tensors/ndarrays, no torch import required here)
onto the flax ResNet parameter tree, transposing conv kernels OIHW→HWIO
and splitting batch-norm affine/running-stat pairs into params/batch_stats.

The flax ResNets use torch-compatible explicit conv padding (see
models/resnet.py), so converted weights reproduce the torch forward
numerically — asserted against a torch oracle in
tests/test_torch_interop.py.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Tuple

import numpy as np

# stage layouts per torchvision depth: (stage_sizes, bottleneck?)
_LAYOUTS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
}


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv(t) -> np.ndarray:
    return _np(t).transpose(2, 3, 1, 0)  # OIHW -> HWIO


def _bn(sd: Mapping, prefix: str) -> Tuple[Dict, Dict]:
    params = {"scale": _np(sd[f"{prefix}.weight"]),
              "bias": _np(sd[f"{prefix}.bias"])}
    stats = {"mean": _np(sd[f"{prefix}.running_mean"]),
             "var": _np(sd[f"{prefix}.running_var"])}
    return params, stats


def resnet_from_torch(state_dict: Mapping, depth: int) -> Dict[str, Any]:
    """torchvision-format ResNet state_dict -> ``{"params", "batch_stats"}``.

    ``depth`` is 18/34/50/101. Apply the result directly::

        variables = resnet_from_torch(torch_model.state_dict(), 50)
        logits = ResNet50(num_classes=...).apply(variables, x, train=False)
    """
    if depth not in _LAYOUTS:
        raise ValueError(f"unsupported depth {depth}; choose {sorted(_LAYOUTS)}")
    stages, bottleneck = _LAYOUTS[depth]
    block_name = "BottleneckBlock" if bottleneck else "BasicBlock"
    convs_per_block = 3 if bottleneck else 2

    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}

    try:
        return _convert(state_dict, depth, stages, block_name,
                        convs_per_block, params, stats)
    except KeyError as exc:
        raise ValueError(
            f"state_dict is missing {exc} — not a complete depth-{depth} "
            f"torchvision ResNet checkpoint; pass the matching depth"
        ) from None


def _convert(state_dict, depth, stages, block_name, convs_per_block,
             params, stats):
    params["conv_init"] = {"kernel": _conv(state_dict["conv1.weight"])}
    params["bn_init"], stats["bn_init"] = _bn(state_dict, "bn1")

    idx = 0
    for stage, count in enumerate(stages, start=1):
        for b in range(count):
            tprefix = f"layer{stage}.{b}"
            name = f"{block_name}_{idx}"
            bp: Dict[str, Any] = {}
            bs: Dict[str, Any] = {}
            for c in range(convs_per_block):
                bp[f"Conv_{c}"] = {
                    "kernel": _conv(state_dict[f"{tprefix}.conv{c + 1}.weight"])}
                bp[f"BatchNorm_{c}"], bs[f"BatchNorm_{c}"] = _bn(
                    state_dict, f"{tprefix}.bn{c + 1}")
            if f"{tprefix}.downsample.0.weight" in state_dict:
                bp["conv_proj"] = {
                    "kernel": _conv(state_dict[f"{tprefix}.downsample.0.weight"])}
                bp["norm_proj"], bs["norm_proj"] = _bn(
                    state_dict, f"{tprefix}.downsample.1")
            params[name] = bp
            stats[name] = bs
            idx += 1

    params["head"] = _dense(state_dict, "fc")

    # a deeper/shallower checkpoint than `depth` would convert "cleanly"
    # into semantically wrong weights — make the mismatch loud instead
    leftover = [k for k in state_dict
                if k.startswith("layer") and "num_batches_tracked" not in k
                and not _consumed_layer_key(k, stages)]
    if leftover:
        raise ValueError(
            f"state_dict has blocks beyond a depth-{depth} ResNet "
            f"(e.g. {leftover[0]}); pass the matching depth")
    return {"params": params, "batch_stats": stats}


def _consumed_layer_key(key: str, stages) -> bool:
    parts = key.split(".")
    stage = int(parts[0][len("layer"):])
    block = int(parts[1])
    return stage <= len(stages) and block < stages[stage - 1]


# torchvision VGG cfgs: the single source of truth lives next to the model
# (models/vgg.py) so converter and model can never drift
from ..models.vgg import _CFGS as _VGG_CFGS  # noqa: E402


def _dense(sd: Mapping, prefix: str) -> Dict[str, np.ndarray]:
    return {"kernel": _np(sd[f"{prefix}.weight"]).T,
            "bias": _np(sd[f"{prefix}.bias"])}


def vgg_from_torch(state_dict: Mapping, depth: int):
    """torchvision-format VGG state_dict -> flax VGG variables.

    ``depth`` is 11/16/19; the batch-norm variant is detected from the
    checkpoint (presence of ``features.<i>.running_mean``). Returns
    ``{"params": ...}`` (plain) or ``{"params", "batch_stats"}`` (BN)::

        variables = vgg_from_torch(torch_model.state_dict(), 16)
        logits = VGG16(num_classes=...).apply(variables, x, train=False)

    A plain (non-BN) checkpoint has no "batch_stats"; construct the flax
    model with ``batch_norm=False`` to match.

    Key subtlety: torchvision flattens the 7x7x512 feature map in CHW
    order before ``classifier.0`` while the flax model (NHWC) flattens in
    HWC order — the first dense kernel's input axis is permuted
    accordingly, so converted weights reproduce the torch forward exactly
    (asserted against a torch oracle in tests/test_torch_interop.py).
    The flax VGG keeps conv biases in the BN variant precisely because
    torchvision does (models/vgg.py).
    """
    if depth not in _VGG_CFGS:
        raise ValueError(
            f"unsupported depth {depth}; choose {sorted(_VGG_CFGS)}")
    cfg = _VGG_CFGS[depth]
    batch_norm = any(k.endswith("running_mean") for k in state_dict
                     if k.startswith("features."))

    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    t_idx = 0  # index into torchvision's features Sequential
    try:
        for i, v in enumerate(cfg):
            if v == "M":
                t_idx += 1
                continue
            conv = f"features.{t_idx}"
            params[f"conv_{i}"] = {
                "kernel": _conv(state_dict[f"{conv}.weight"]),
                "bias": _np(state_dict[f"{conv}.bias"]),
            }
            if params[f"conv_{i}"]["kernel"].shape[-1] != v:
                raise ValueError(
                    f"{conv}.weight has {params[f'conv_{i}']['kernel'].shape[-1]}"
                    f" output channels, expected {v} — not a depth-{depth} "
                    "checkpoint; pass the matching depth")
            t_idx += 1
            if batch_norm:
                params[f"bn_{i}"], stats[f"bn_{i}"] = _bn(
                    state_dict, f"features.{t_idx}")
                t_idx += 1
            t_idx += 1  # ReLU

        # classifier.0 consumes torch's CHW flatten of [512, 7, 7]; the
        # flax model flattens NHWC -> HWC, so permute the input axis
        w0 = _np(state_dict["classifier.0.weight"])  # [4096, 512*7*7]
        w0 = w0.reshape(4096, 512, 7, 7).transpose(2, 3, 1, 0)
        params["fc_0"] = {"kernel": w0.reshape(7 * 7 * 512, 4096),
                          "bias": _np(state_dict["classifier.0.bias"])}
        params["fc_1"] = _dense(state_dict, "classifier.3")
        params["head"] = _dense(state_dict, "classifier.6")
    except KeyError as exc:
        raise ValueError(
            f"state_dict is missing {exc} — not a complete depth-{depth} "
            "torchvision VGG checkpoint; pass the matching depth"
        ) from None
    except ValueError as exc:
        # a mis-declared depth walks t_idx onto the wrong module kind (e.g.
        # _conv transposing a 1-D BN weight) — keep the diagnosis loud
        raise ValueError(
            f"state_dict does not match a depth-{depth} torchvision VGG "
            f"layout ({exc}); pass the matching depth") from None

    leftover = [k for k in state_dict
                if k.startswith("features.")
                and "num_batches_tracked" not in k
                and int(k.split(".")[1]) >= t_idx]
    if leftover:
        raise ValueError(
            f"state_dict has feature layers beyond a depth-{depth} VGG "
            f"(e.g. {leftover[0]}); pass the matching depth")

    out: Dict[str, Any] = {"params": params}
    if batch_norm:
        out["batch_stats"] = stats
    return out
