"""Local rank-stack <-> global rank-stacked array bridging.

The frontends (``bluefog_tpu.torch``, ``bluefog_tpu.keras``) speak in
THIS controller's rank rows: a host array whose leading dim is the number
of ranks this controller owns (== ``size()`` in single-controller jobs).
These helpers move that local view onto the mesh and back:

* :func:`to_global` — assemble the global rank-stacked jax array, each
  controller contributing exactly its addressable shards (no
  cross-process data movement);
* :func:`to_local` — gather a jax array's addressable rows back into the
  local host stack, in global rank order.

Ownership comes from the runtime's mesh-resolved process index (the same
helper the window subsystem uses) — never the default backend's, which
can disagree when an accelerator plugin is registered alongside a CPU
mesh.
"""

from __future__ import annotations

from typing import List

import numpy as np

import jax

from ..runtime import control_plane as _cp
from ..runtime.state import _global_state


def owned_ranks() -> List[int]:
    """Global rank indexes whose devices belong to THIS controller."""
    st = _global_state()
    return _cp.owned_ranks(st.devices, st.process_index)


def to_global(host: np.ndarray):
    """Local rank-stack (leading dim = owned rank count) -> global array."""
    st = _global_state()
    owned = owned_ranks()
    host = np.asarray(host)
    if host.shape[0] != len(owned):
        raise ValueError(
            f"expected this controller's rank-stacked view with leading "
            f"dim {len(owned)} (its owned ranks), got shape "
            f"{tuple(host.shape)}")
    from ..ops.plan import rank_sharding

    sh = rank_sharding(st.mesh)
    if len(owned) == st.size:  # single controller: place the whole stack
        return jax.device_put(host, sh)
    local_of = {r: i for i, r in enumerate(owned)}
    shape = (st.size,) + host.shape[1:]
    return jax.make_array_from_callback(
        shape, sh, lambda idx: host[local_of[idx[0].start or 0]][None])


def to_local(a) -> np.ndarray:
    """Global jax array -> this controller's rows (host), global order.

    Returns a freshly-allocated writable array in the multi-controller
    case; the single-controller fast path may return a read-only view of
    the jax buffer — callers that mutate must copy.
    """
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        rows = sorted(((s.index[0].start or 0, np.asarray(s.data))
                       for s in a.addressable_shards), key=lambda p: p[0])
        return np.concatenate([v for _, v in rows], axis=0)
    return np.asarray(a)
