"""JAX version compatibility shims.

The framework targets the current jax API surface but must run on the
images actually in the fleet. Centralizing the fallbacks here keeps every
call site on one import instead of scattering try/excepts.

``shard_map``: promoted to ``jax.shard_map`` in newer releases; older
jax (e.g. 0.4.x) ships it as ``jax.experimental.shard_map.shard_map``
with the same (f, mesh, in_specs, out_specs) surface. The newer
``check_vma`` kwarg (varying-manual-axes check, nee ``check_rep``) is
translated or dropped for releases that predate it.
"""

from __future__ import annotations

import inspect

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax <= 0.4.x
    from jax.experimental.shard_map import shard_map as _shard_map

    _params = inspect.signature(_shard_map).parameters
    if "check_vma" in _params:
        shard_map = _shard_map
    else:
        def shard_map(*args, **kwargs):
            # old releases call the same knob check_rep; map it through so
            # call sites can stay on the current-jax spelling
            if "check_vma" in kwargs:
                v = kwargs.pop("check_vma")
                if "check_rep" in _params:
                    kwargs["check_rep"] = v
            return _shard_map(*args, **kwargs)

# ``jax.typeof``: aval accessor added in newer releases; get_aval is the
# long-standing equivalent (callers only read metadata like ``.vma``, which
# simply doesn't exist on old avals — getattr-with-default handles that).
typeof = jax.typeof if hasattr(jax, "typeof") else jax.core.get_aval

# ``AbstractMesh``: newer releases construct from (axis_sizes, axis_names);
# 0.4.x takes one shape_tuple of (name, size) pairs — passing the new form
# there silently lands the names in axis_types and dies inside mesh
# internals. Dispatch once on the signature.
from jax.sharding import AbstractMesh as _AbstractMesh  # noqa: E402

_am_params = list(inspect.signature(_AbstractMesh.__init__).parameters)
if "shape_tuple" in _am_params:  # jax <= 0.4.x
    def abstract_mesh(axis_sizes, axis_names) -> "_AbstractMesh":
        return _AbstractMesh(tuple(zip(axis_names, axis_sizes)))
else:
    def abstract_mesh(axis_sizes, axis_names) -> "_AbstractMesh":
        return _AbstractMesh(tuple(axis_sizes), tuple(axis_names))

abstract_mesh.__doc__ = (
    "AbstractMesh(axis_sizes, axis_names) across the jax API change "
    "(0.4.x used a single ((name, size), ...) shape_tuple).")

# 0.4.x AOT lowering cannot resolve a device assignment for AbstractMesh
# arg shardings (`_device_assignment is not implemented`); the shard_map
# in_specs carry the partitioning into the lowered module regardless, so
# AOT callers drop the ShapeDtypeStruct shardings there.
ABSTRACT_MESH_ARG_SHARDINGS = "shape_tuple" not in _am_params

__all__ = ["shard_map", "typeof", "abstract_mesh",
           "ABSTRACT_MESH_ARG_SHARDINGS"]
