"""Host input pipeline: prefetched, double-buffered device feeding.

The reference benchmark feeds synthetic batches through a torch DataLoader
(reference: examples/pytorch_benchmark.py) — host memory to device every
step. The JAX analog: ``jax.device_put`` is asynchronous, so keeping a small
queue of in-flight transfers ahead of the consumer overlaps host->HBM copies
with the previous step's compute. This is the standard flax
``prefetch_to_device`` recipe, shaped for rank-stacked bluefog batches.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator

import jax


def prefetch_to_device(iterator: Iterable, size: int = 2,
                       sharding=None) -> Iterator:
    """Yield device-resident batches, keeping ``size`` transfers in flight.

    ``iterator`` yields host batches (pytrees of numpy arrays);
    ``sharding`` (e.g. ``bf.rank_sharding(bf.mesh())``) places every leaf —
    None uses the default device. With ``size >= 2`` the copy of batch
    ``t+1`` rides the wire while the step consumes batch ``t``
    (double buffering); device arrays pass through untouched.
    """
    # validate HERE (not inside the generator) so a bad size raises at the
    # call site instead of at the consumer's first next()
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")

    def gen():
        queue: collections.deque = collections.deque()

        def put(batch):
            return jax.tree_util.tree_map(
                lambda x: x if isinstance(x, jax.Array) and sharding is None
                else jax.device_put(x, sharding), batch)

        for batch in iterator:
            queue.append(put(batch))
            if len(queue) >= size:
                yield queue.popleft()
        while queue:
            yield queue.popleft()

    return gen()
