"""Parameter/state synchronization helpers.

TPU-native rebuild of the reference's ``torch/utility.py``:
``broadcast_parameters`` (utility.py:22-56), ``allreduce_parameters``
(utility.py:59-80), ``broadcast_optimizer_state`` (utility.py:83-160). The
reference walks a torch ``state_dict``; here the arguments are rank-stacked
pytrees and each helper is one collective over the mesh.
"""

from .data import prefetch_to_device
from .params import (
    allreduce_parameters,
    broadcast_optimizer_state,
    broadcast_parameters,
)
from .torch_interop import resnet_from_torch, vgg_from_torch

__all__ = [
    "broadcast_parameters",
    "allreduce_parameters",
    "broadcast_optimizer_state",
    "resnet_from_torch",
    "prefetch_to_device",
    "vgg_from_torch",
]
