"""Broadcast / average whole parameter pytrees across the mesh."""

from __future__ import annotations

from typing import Any


from ..ops import collectives as _collectives


def broadcast_parameters(params: Any, root_rank: int = 0) -> Any:
    """Overwrite every rank's slice with ``root_rank``'s values.

    The initial-state synchronization of decentralized training (reference:
    utility.py:22-56; called at the top of every example script). ``params``
    is a rank-stacked pytree; returns the broadcast result (functional — JAX
    arrays are immutable, unlike the in-place torch version).
    """
    return _collectives.broadcast(params, root_rank, name="broadcast.parameters")


def allreduce_parameters(params: Any) -> Any:
    """Replace every rank's slice with the global average.

    Reference: utility.py:59-80 (used to synchronize models periodically or
    before evaluation in decentralized runs).
    """
    return _collectives.allreduce(params, average=True, name="allreduce.parameters")


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0) -> Any:
    """Broadcast an optax state pytree from ``root_rank``.

    The reference version (utility.py:83-160) walks torch optimizer
    ``state_dict`` entries and special-cases non-tensor scalars by wrapping
    them in tensors; optax states are already pytrees of arrays, so this is
    the same one collective as ``broadcast_parameters``.
    """
    return _collectives.broadcast(
        opt_state, root_rank, name="broadcast.optimizer_state"
    )
