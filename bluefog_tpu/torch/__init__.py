"""Live torch-tensor bindings: drive the framework from a torch loop.

The reference's ``bluefog.torch`` frontend wraps every op for live torch
tensors (adapter: torch/adapter.h:32-92; op surface: torch/mpi_ops.py) so
a torch training loop can call ``bf.neighbor_allreduce(p.data)`` directly.
Round 4 shipped checkpoint-format interop only (utils/torch_interop.py —
"bring your weights"); this subpackage closes the remaining gap: bring
your *training loop*.

Mapping to the TPU-native execution model: the reference runs one process
per rank, so its torch API is per-rank. Here a controller owns one or
more ranks of the SPMD mesh, and every torch-facing function takes the
RANK-STACKED view of THIS CONTROLLER'S ranks — leading dim = ``size()``
in single-controller jobs, and the controller's local rank count in
multi-controller jobs (every controller calls every op, SPMD-style, each
holding its own rows; results come back as the same local view). Tensors
convert torch→jax at the boundary (bf16 via a bit-level view: numpy has
no bfloat16), the op runs as the usual compiled SPMD program, and the
result converts back to a torch tensor. The compute path is unchanged —
this is a *frontend*, exactly like the reference's torch layer over its
C++ core.

Covered surface (reference torch/mpi_ops.py parity where TPU-meaningful):
collectives (allreduce / neighbor_allreduce / broadcast / allgather /
neighbor_allgather, with the reference's dynamic-topology kwargs), the
one-sided window family (win_create/put/get/accumulate/update/free), and
the high-level hooks torch loops actually use: ``broadcast_parameters`` /
``broadcast_optimizer_state`` (reference torch/utility.py) and
``DistributedTorchOptimizer`` — a torch.optim wrapper that mixes
parameters with the neighbor graph after each ``step()`` (reference
torch/optimizers.py's CommunicatedOptimizer family).
"""

from __future__ import annotations

import weakref
from typing import Dict, Optional

import numpy as np

import torch

import jax

import bluefog_tpu as _api  # the jax-facing surface (parent package)
from ..ops import windows as _windows

try:  # optional: bf16 bridging
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    ml_dtypes = None
    _BF16 = None

__all__ = [
    "owned_ranks",
    "to_jax", "to_torch", "allreduce", "neighbor_allreduce", "broadcast",
    "allgather", "neighbor_allgather", "win_create", "win_put", "win_get",
    "win_accumulate", "win_update", "win_update_then_collect", "win_free",
    "broadcast_parameters", "broadcast_optimizer_state",
    "DistributedTorchOptimizer",
]


# ---------------------------------------------------------------------------
# tensor bridging
# ---------------------------------------------------------------------------

from ..utils.local_view import owned_ranks, to_global, to_local  # noqa: E402


def _np_of(t: "torch.Tensor") -> np.ndarray:
    x = t.detach()
    if x.device.type != "cpu":
        x = x.cpu()
    x = x.contiguous()
    if x.dtype == torch.bfloat16:
        if _BF16 is None:  # pragma: no cover
            raise RuntimeError("bfloat16 bridging needs ml_dtypes")
        return x.view(torch.uint16).numpy().view(_BF16)
    return x.numpy()


def to_jax(t):
    """torch.Tensor (or pytree of them) -> global jax array on the mesh.

    ``t`` carries THIS controller's rank rows (leading dim = local rank
    count); each controller contributes exactly its addressable shards,
    so the global array assembles without cross-process data movement
    (utils/local_view.py). bf16 crosses as a uint16 bit-view (numpy has
    no bfloat16 dtype).
    """
    if isinstance(t, dict):
        return {k: to_jax(v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        return type(t)(to_jax(v) for v in t)
    if not isinstance(t, torch.Tensor):
        return t
    return to_global(_np_of(t))


def to_torch(a) -> torch.Tensor:
    """jax array (or pytree) -> torch CPU tensor holding THIS controller's
    rank rows (the full stack in single-controller jobs; bf16 preserved)."""
    if isinstance(a, dict):
        return {k: to_torch(v) for k, v in a.items()}
    if isinstance(a, (list, tuple)):
        return type(a)(to_torch(v) for v in a)
    fresh = isinstance(a, jax.Array) and not a.is_fully_addressable
    host = to_local(a)  # fresh (writable) iff the multi-controller gather
    if _BF16 is not None and host.dtype == _BF16:
        u16 = host.view(np.uint16)
        return torch.from_numpy(u16 if fresh else u16.copy()).view(
            torch.bfloat16)
    if fresh:
        return torch.from_numpy(host)
    # copy: arrays exported by jax are read-only buffers, and torch tensors
    # aliasing them would warn (and invite undefined behavior on write)
    return torch.from_numpy(np.ascontiguousarray(host).copy())


def _wrap(op):
    def run(tensor, *args, **kwargs):
        out = to_torch(op(to_jax(tensor), *args, **kwargs))
        # restore the caller's dtype: JAX's default config computes f64
        # inputs in f32 (jax_enable_x64 unset); the torch caller still
        # gets back the dtype it sent, like the reference frontend
        if isinstance(tensor, torch.Tensor) and isinstance(
                out, torch.Tensor) and out.dtype != tensor.dtype:
            out = out.to(tensor.dtype)
        return out
    run.__name__ = op.__name__
    run.__doc__ = (f"torch frontend of bluefog_tpu.{op.__name__} — accepts "
                   "and returns torch tensors (see this module's docstring "
                   "for the rank-stacked convention; float64 computes in "
                   "f32 unless jax_enable_x64 is set).\n\n" +
                   (op.__doc__ or ""))
    return run


allreduce = _wrap(_api.allreduce)
neighbor_allreduce = _wrap(_api.neighbor_allreduce)
broadcast = _wrap(_api.broadcast)
allgather = _wrap(_api.allgather)
neighbor_allgather = _wrap(_api.neighbor_allgather)


# ---------------------------------------------------------------------------
# windows (one-sided) — torch tensors in, torch tensors out
# ---------------------------------------------------------------------------

def win_create(tensor: torch.Tensor, name: str,
               zero_init: bool = False) -> bool:
    return _windows.win_create(to_jax(tensor), name, zero_init=zero_init)


def win_put(tensor: torch.Tensor, name: str, **kw) -> int:
    return _windows.win_put(to_jax(tensor), name, **kw)


def win_accumulate(tensor: torch.Tensor, name: str, **kw) -> int:
    return _windows.win_accumulate(to_jax(tensor), name, **kw)


def win_get(name: str, **kw) -> int:
    return _windows.win_get(name, **kw)


def win_update(name: str, **kw) -> torch.Tensor:
    return to_torch(_windows.win_update(name, **kw))


def win_update_then_collect(name: str, **kw) -> torch.Tensor:
    return to_torch(_windows.win_update_then_collect(name, **kw))


def win_free(name: Optional[str] = None) -> bool:
    return _windows.win_free(name)


# ---------------------------------------------------------------------------
# module / optimizer hooks (reference torch/utility.py + optimizers.py)
# ---------------------------------------------------------------------------

class _CommPlan:
    """Cached stack/scatter plan for one fixed list of module replicas.

    Rebuilding the name->param maps and allocating fresh stacked tensors
    on EVERY communicate was measured at ~31 ms of the torch frontend's
    43 ms per-step host tax (PERF.md r6 frontend probe). The plan caches
    the validated parameter order, the per-rank parameter OBJECTS (robust
    to in-place ``p.data`` updates and to ``p.data = ...`` rebinding —
    ``.data`` is read at stack time), and one preallocated stacked buffer
    per parameter that ``torch.stack(out=)`` refills in place. Entries
    evict when any replica is garbage-collected (weakref callbacks), so
    the cache cannot pin dead models or confuse a reused ``id``.

    ``device`` (optional, :class:`_DevicePlan`): the r13 device-resident
    mode — the remaining ~20 ms/communicate stack/scatter host round-trip
    disappears because the parameters themselves live in jax-owned
    buffers behind torch dlpack views."""

    __slots__ = ("names", "params", "bufs", "refs", "device")

    def __init__(self, names, params, refs) -> None:
        self.names = names    # parameter names, shared order
        self.params = params  # params[rank][i] <-> names[i]
        self.bufs: Dict[str, torch.Tensor] = {}
        self.refs = refs
        self.device = None    # _DevicePlan when residency is installed


class _DevicePlan:
    """jax-owned parameter storage with zero-copy torch dlpack views.

    Each rank's row of every parameter lives in one jax-owned ``[1, ...]``
    buffer placed on that rank's mesh device; the module parameter's
    ``.data`` is rebound to a dlpack VIEW of it, so the torch optimizer's
    in-place updates write straight into device-resident memory. A
    communicate then assembles the global rank-stacked array from the row
    buffers (metadata only — no stack), runs the compiled op, and copies
    the mixed rows back through the views (one in-place row copy each) —
    no per-parameter ``torch.stack``, no host gather, no per-rank scatter
    (the structural fix PERF.md r7 named)."""

    __slots__ = ("rows", "views")

    def __init__(self) -> None:
        self.rows: Dict[str, list] = {}   # name -> [jax [1, ...] buffers]
        self.views: Dict[str, list] = {}  # name -> [torch row views]


def _install_device_rows(plan: _CommPlan) -> bool:
    """Move a plan's parameters into jax-owned buffers with dlpack views.

    Returns False (leaving the host stack/scatter path untouched) when the
    replica count does not match this controller's owned ranks, a dtype
    would not round-trip (e.g. float64 demoted to f32 under the default
    x64-off config), or the dlpack bridge is unavailable."""
    from ..runtime.state import _global_state

    st = _global_state()
    owned = owned_ranks()
    if len(plan.params) != len(owned):
        return False
    try:
        from torch.utils import dlpack as _tdl

        rows: Dict[str, list] = {}
        views: Dict[str, list] = {}
        staged = []  # (param, view) — rebind only after full success
        for i, nm in enumerate(plan.names):
            rs, vs = [], []
            for r in range(len(owned)):
                p = plan.params[r][i]
                host = _np_of(p.data)
                arr = jax.device_put(np.ascontiguousarray(host)[None],
                                     st.devices[owned[r]])
                if np.dtype(arr.dtype) != host.dtype:
                    return False
                view = _tdl.from_dlpack(arr)[0]
                staged.append((p, view))
                rs.append(arr)
                vs.append(view)
            rows[nm] = rs
            views[nm] = vs
        for p, view in staged:
            p.data = view
        dev = _DevicePlan()
        dev.rows = rows
        dev.views = views
        plan.device = dev
        return True
    except Exception:  # noqa: BLE001 — residency is an optimization only
        return False


def _device_sync(plan: _CommPlan) -> bool:
    """Re-anchor parameters that user code rebound (``p.data = ...``):
    copy the current value into the jax row through the view and rebind.
    Returns False when a shape/dtype changed — residency is abandoned and
    the host path takes over."""
    dev = plan.device
    for i, nm in enumerate(plan.names):
        for r in range(len(plan.params)):
            p = plan.params[r][i]
            v = dev.views[nm][r]
            if p.data.data_ptr() == v.data_ptr():
                continue
            if p.data.shape != v.shape or p.data.dtype != v.dtype:
                plan.device = None
                return False
            with torch.no_grad():
                v.copy_(p.data)
            p.data = v
    return True


def _device_communicate(plan: _CommPlan, **kw) -> None:
    """One neighbor_allreduce over every parameter, entirely through the
    device-resident rows; mixed values land back in the SAME buffers the
    module parameters view."""
    from ..ops.plan import rank_sharding
    from ..runtime.state import _global_state
    from torch.utils import dlpack as _tdl

    st = _global_state()
    sh = rank_sharding(st.mesh)
    for nm in plan.names:
        rs = plan.device.rows[nm]
        shape = (st.size,) + tuple(rs[0].shape[1:])
        ga = jax.make_array_from_single_device_arrays(shape, sh, rs)
        mixed = _api.neighbor_allreduce(ga, **kw)
        shards = sorted(((s.index[0].start or 0, s.data)
                         for s in mixed.addressable_shards),
                        key=lambda q: q[0])
        with torch.no_grad():
            for (_, data), v in zip(shards, plan.device.views[nm]):
                v.copy_(_tdl.from_dlpack(data)[0])


_plan_cache: Dict[tuple, _CommPlan] = {}


def _comm_plan(modules) -> _CommPlan:
    key = tuple(id(m) for m in modules)
    plan = _plan_cache.get(key)
    if plan is not None and all(r() is not None for r in plan.refs):
        return plan
    named = [dict(m.named_parameters()) for m in modules]
    names = list(named[0])
    for d in named[1:]:
        if list(d) != names:
            raise ValueError("modules must share an identical parameter set")
    params = [[d[nm] for nm in names] for d in named]
    refs = [weakref.ref(m, lambda _r, k=key: _plan_cache.pop(k, None))
            for m in modules]
    plan = _plan_cache[key] = _CommPlan(names, params, refs)
    return plan


def _stacked_params(modules) -> Dict[str, torch.Tensor]:
    """[per-rank nn.Module] -> {name: rank-stacked tensor} (plan-cached)."""
    plan = _comm_plan(modules)
    out: Dict[str, torch.Tensor] = {}
    for i, nm in enumerate(plan.names):
        rows = [plan.params[r][i].data for r in range(len(plan.params))]
        buf = plan.bufs.get(nm)
        if (buf is None or buf.shape != (len(rows),) + tuple(rows[0].shape)
                or buf.dtype != rows[0].dtype):
            buf = plan.bufs[nm] = torch.empty(
                (len(rows),) + tuple(rows[0].shape), dtype=rows[0].dtype)
        torch.stack(rows, out=buf)
        out[nm] = buf
    return out


def _write_back(modules, mixed: Dict[str, torch.Tensor]) -> None:
    plan = _comm_plan(modules)
    with torch.no_grad():
        for i, nm in enumerate(plan.names):
            col = mixed[nm]
            for r in range(len(plan.params)):
                plan.params[r][i].data.copy_(col[r])


def broadcast_parameters(modules, root_rank: int = 0) -> None:
    """Overwrite every rank's module parameters with root_rank's.

    ``modules``: one nn.Module per rank this controller owns (a single
    module is accepted for the 1-rank case). Reference:
    torch/utility.py broadcast_parameters.
    """
    if isinstance(modules, torch.nn.Module):
        modules = [modules]
    stacked = _stacked_params(modules)
    mixed = {nm: broadcast(t, root_rank=root_rank)
             for nm, t in stacked.items()}
    _write_back(modules, mixed)


def broadcast_optimizer_state(optimizer: "torch.optim.Optimizer", modules,
                              root_rank: int = 0) -> None:
    """Broadcast rank ``root_rank``'s optimizer state to every rank.

    With per-rank module replicas, a torch optimizer's per-param state
    (momentum buffers, Adam moments) is ALSO per-rank: the state entry of
    rank r's parameter is rank r's state. This stacks each named
    parameter's state tensors across the replica ranks, broadcasts, and
    writes root_rank's values back onto every rank's entries — the
    reference's broadcast_optimizer_state contract (torch/utility.py:
    137-230) restated for the replica model. Scalar entries (step
    counters) copy from root_rank directly.
    """
    if isinstance(modules, torch.nn.Module):
        modules = [modules]
    named = [dict(m.named_parameters()) for m in modules]
    for nm in named[0]:
        states = [optimizer.state.get(d[nm]) for d in named]
        if not states[root_rank]:
            continue  # root has nothing to broadcast for this param
        missing = [r for r, st in enumerate(states) if not st]
        if missing:
            raise ValueError(
                f"optimizer state for parameter '{nm}' exists on rank "
                f"{root_rank} but not on ranks {missing} — run one "
                "optimizer step everywhere (or broadcast parameters and "
                "re-init the optimizer) before broadcasting state")
        for k, root_v in states[root_rank].items():
            if isinstance(root_v, torch.Tensor) and root_v.ndim >= 1:
                stacked = torch.stack([st[k] for st in states])
                mixed = broadcast(stacked, root_rank=root_rank)
                for r, st in enumerate(states):
                    st[k] = mixed[r].clone()
            elif isinstance(root_v, torch.Tensor):
                # 0-dim tensors (Adam's 'step') must be CLONED per rank:
                # aliasing one tensor across ranks would make every
                # in-place `step += 1` advance a shared counter N times
                for st in states:
                    st[k] = root_v.clone()
            else:
                for st in states:
                    st[k] = root_v


class DistributedTorchOptimizer:
    """Decentralized wrapper for a torch optimizer driving per-rank modules.

    The reference's ``DistributedNeighborAllreduceOptimizer`` for torch
    (torch/optimizers.py): after every local ``step()``, each rank's
    parameters are averaged with its in-neighbors under the current
    topology. Here the controller owns all of its ranks' module replicas;
    communication is one rank-stacked neighbor_allreduce per parameter.

    ``num_steps_per_communication`` matches the reference knob (local
    steps between mixings).

    ``device_resident`` (default True): hold the parameters in jax-owned
    buffers behind torch dlpack views (:func:`_install_device_rows`) so
    the per-communicate stack/scatter host round-trip disappears. Falls
    back to the host path transparently when the bridge is unavailable
    (dtype would not round-trip, replica count mismatch).
    """

    def __init__(self, optimizer: "torch.optim.Optimizer", modules,
                 num_steps_per_communication: int = 1,
                 device_resident: bool = True) -> None:
        if isinstance(modules, torch.nn.Module):
            modules = [modules]
        self.optimizer = optimizer
        self.modules = list(modules)
        self.num_steps_per_communication = num_steps_per_communication
        self._counter = 0
        self.device_resident = device_resident
        self._device_failed = False
        # dynamic-topology knobs, same surface as the jax optimizers
        self.self_weight = None
        self.neighbor_weights = None
        self.send_neighbors = None

    def zero_grad(self, *a, **k):
        return self.optimizer.zero_grad(*a, **k)

    def step(self, *a, **k):
        out = self.optimizer.step(*a, **k)
        self._counter += 1
        if self._counter % self.num_steps_per_communication == 0:
            # forward whichever knobs are set: static-topology custom
            # weights are legal without send_neighbors
            kw = {key: val for key, val in (
                ("self_weight", self.self_weight),
                ("neighbor_weights", self.neighbor_weights),
                ("send_neighbors", self.send_neighbors),
            ) if val is not None}
            plan = _comm_plan(self.modules)
            if self.device_resident and not self._device_failed and \
                    plan.device is None:
                self._device_failed = not _install_device_rows(plan)
            if plan.device is not None and _device_sync(plan):
                _device_communicate(plan, **kw)
            else:
                stacked = _stacked_params(self.modules)
                mixed = {nm: neighbor_allreduce(t, **kw)
                         for nm, t in stacked.items()}
                _write_back(self.modules, mixed)
        return out

    def __getattr__(self, name):  # passthrough (param_groups, state, ...)
        if "optimizer" not in self.__dict__:
            # e.g. unpickling probes dunders before __init__ ran; a plain
            # AttributeError here instead of infinite __getattr__ recursion
            raise AttributeError(name)
        return getattr(self.optimizer, name)
