"""Keras 3 frontend: the TF-family migration target, on the JAX backend.

The reference shipped a TensorFlow frontend — async ``AsyncOpKernel``s
(tensorflow/mpi_ops.cc:46-212), a TF op surface (tensorflow/mpi_ops.py:
77-213), and ``DistributedOptimizer`` / ``DistributedGradientTape``
(tensorflow/optimizers.py:1-203). TF itself has no TPU-native place in
this stack (MIGRATION.md documents the drop), but the USERS of that
frontend — people with Keras models and Keras optimizers — do: Keras 3
runs natively on the JAX backend, and this subpackage gives them the
reference's high-level surface on top of this framework's compiled ops:

  * :func:`broadcast_variables` — reference tensorflow's
    ``broadcast_variables`` (utility.py): root rank's weights to all;
  * :class:`DistributedOptimizer` — the reference TF
    ``DistributedOptimizer`` semantics (average gradients across ranks
    before applying, optimizers.py:118-160) plus the decentralized modes
    this framework adds (``communication_type="neighbor.allreduce"``
    mixes weights with the topology after each apply, the
    decentralized-SGD contract);
  * models are per-rank replicas, exactly like the torch frontend
    (``bluefog_tpu.torch``) — a controller owns its ranks' replicas
    (all of them in single-controller jobs, its owned ranks' in
    multi-controller ones; utils/local_view.py assembles the global
    arrays from each controller's shards) and communication is one
    rank-stacked compiled op per variable.

Requires ``KERAS_BACKEND=jax`` (anything else would put keras tensors on
a different framework than the mesh); import fails loudly otherwise.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Sequence

import numpy as np

import keras

import bluefog_tpu as _api
from ..utils.local_view import (owned_ranks as _owned_ranks,
                                to_global as _to_global,
                                to_local as _to_local)

if keras.backend.backend() != "jax":  # pragma: no cover - env-dependent
    raise ImportError(
        "bluefog_tpu.keras needs the Keras JAX backend; set "
        "KERAS_BACKEND=jax before importing keras (got "
        f"'{keras.backend.backend()}')")

__all__ = ["broadcast_variables", "DistributedOptimizer"]


class _CommPlan:
    """Cached stack/scatter plan for one fixed list of model replicas.

    Re-walking ``trainable_variables + non_trainable_variables``,
    re-validating shapes, and allocating fresh ``np.stack`` outputs on
    EVERY communicate was a measured slice of the keras frontend's ~53 ms
    per-step host tax (PERF.md r6 frontend probe). The plan keeps the
    validated per-replica variable lists and one preallocated stacked
    buffer per variable, refilled in place each call. Entries evict when
    any replica is garbage-collected (weakref callbacks). Mutating a
    model's variable STRUCTURE mid-training (adding layers) is out of
    contract, as it is for the reference's broadcast hooks.

    ``device`` (optional, :class:`_DevicePlan`): the torch frontend's r13
    device-resident mode ported to keras — per-rank variable rows live as
    jax arrays committed to their rank's mesh device, the communicate
    assembles the global array metadata-only, and mixed shards assign
    straight back to the variables, so the per-communicate
    host-gather / ``np.stack`` / host-scatter round-trip disappears."""

    __slots__ = ("per", "shapes", "bufs", "refs", "device")

    def __init__(self, per, shapes, refs) -> None:
        self.per = per        # per[replica][i] -> keras variable
        self.shapes = shapes
        self.bufs: List[np.ndarray] = [None] * len(shapes)
        self.refs = refs
        self.device = None    # _DevicePlan when residency is installed


class _DevicePlan:
    """Device-resident variable rows behind jax arrays (torch r13 pattern).

    Keras variables on the jax backend hold immutable jax arrays — there
    is no dlpack view to rebind as with torch params — so residency here
    means: each variable's row is KEPT as a ``[1, ...]`` jax array
    committed to its rank's mesh device, refreshed only when the keras
    optimizer rebound the variable's value since the last write-back
    (identity check against ``written``). A communicate is then a
    metadata-only global assembly + one compiled op + per-replica
    ``assign`` of the mixed device shard — no host gather, no
    ``np.stack``, no per-rank host scatter (the carried-over ROADMAP item
    r13 fixed for torch)."""

    __slots__ = ("rows", "written")

    def __init__(self, nvars: int, nreps: int) -> None:
        self.rows = [[None] * nreps for _ in range(nvars)]
        self.written = [[None] * nreps for _ in range(nvars)]


def _install_device_rows(plan: _CommPlan) -> bool:
    """Seed the device plan: every variable row onto its rank's device.

    Returns False (host stack/scatter path untouched) when the replica
    count does not match this controller's owned ranks or any placement
    fails — residency is an optimization, never a requirement."""
    from ..runtime.state import _global_state

    import jax

    st = _global_state()
    owned = _owned_ranks()
    if len(plan.per) != len(owned):
        return False
    try:
        dev = _DevicePlan(len(plan.shapes), len(owned))
        for i in range(len(plan.shapes)):
            for r in range(len(owned)):
                v = plan.per[r][i]
                dev.rows[i][r] = jax.device_put(
                    np.asarray(v)[None], st.devices[owned[r]])
                dev.written[i][r] = v.value
        plan.device = dev
        return True
    except Exception:  # noqa: BLE001 — residency is an optimization only
        return False


def _device_sync(plan: _CommPlan) -> bool:
    """Refresh rows whose variable was rebound since the last write-back
    (a keras optimizer ``assign`` mints a NEW jax array every step — the
    identity check finds exactly those). Returns False on a shape/dtype
    change: residency is abandoned and the host path takes over."""
    from ..runtime.state import _global_state

    import jax

    st = _global_state()
    owned = _owned_ranks()
    dev = plan.device
    for i in range(len(plan.shapes)):
        for r in range(len(plan.per)):
            v = plan.per[r][i]
            cur = v.value
            if cur is dev.written[i][r]:
                continue  # untouched since our last assign: row is current
            if tuple(cur.shape) != tuple(dev.rows[i][r].shape[1:]) or \
                    cur.dtype != dev.rows[i][r].dtype:
                plan.device = None
                return False
            dev.rows[i][r] = jax.device_put(cur, st.devices[owned[r]])[None]
            dev.written[i][r] = cur
    return True


def _device_communicate(plan: _CommPlan) -> None:
    """One neighbor_allreduce per variable, entirely device-side: global
    arrays assemble from the resident rows (metadata only), and the mixed
    per-rank shards assign straight back to the replicas' variables."""
    from ..ops.plan import rank_sharding
    from ..runtime.state import _global_state

    import jax

    st = _global_state()
    sh = rank_sharding(st.mesh)
    dev = plan.device
    for i in range(len(plan.shapes)):
        rs = dev.rows[i]
        shape = (st.size,) + tuple(rs[0].shape[1:])
        ga = jax.make_array_from_single_device_arrays(shape, sh, rs)
        mixed = _api.neighbor_allreduce(ga)
        shards = sorted(((s.index[0].start or 0, s.data)
                         for s in mixed.addressable_shards),
                        key=lambda q: q[0])
        for r, (_, data) in enumerate(shards):
            v = plan.per[r][i]
            v.assign(data[0])
            dev.rows[i][r] = data
            dev.written[i][r] = v.value


_plan_cache = {}


def _comm_plan(models) -> _CommPlan:
    key = tuple(id(m) for m in models)
    plan = _plan_cache.get(key)
    if plan is not None and all(r() is not None for r in plan.refs):
        return plan
    per = [m.trainable_variables + m.non_trainable_variables for m in models]
    shapes = [tuple(v.shape) for v in per[0]]
    for vs in per[1:]:
        if [tuple(v.shape) for v in vs] != shapes:
            raise ValueError("models must share an identical variable set")
    refs = [weakref.ref(m, lambda _r, k=key: _plan_cache.pop(k, None))
            for m in models]
    plan = _plan_cache[key] = _CommPlan(per, shapes, refs)
    return plan


def _stacked(models: Sequence["keras.Model"]) -> List[np.ndarray]:
    """[per-owned-rank model] -> per-variable LOCAL rank stacks
    (positional: keras auto-numbers layer names per replica, so variable
    PATHS differ across structurally identical models; plan-cached)."""
    owned = _owned_ranks()
    if len(models) != len(owned):
        raise ValueError(
            f"need one model replica per rank this controller owns "
            f"({len(owned)}), got {len(models)}")
    plan = _comm_plan(models)
    out = []
    for i in range(len(plan.shapes)):
        rows = [np.asarray(vs[i]) for vs in plan.per]
        buf = plan.bufs[i]
        if (buf is None or buf.shape != (len(rows),) + rows[0].shape
                or buf.dtype != rows[0].dtype):
            buf = plan.bufs[i] = np.empty(
                (len(rows),) + rows[0].shape, rows[0].dtype)
        for r, row in enumerate(rows):
            buf[r] = row
        out.append(buf)
    return out


def _write_back(models, mixed: List[np.ndarray]) -> None:
    plan = _comm_plan(models)
    for r in range(len(plan.per)):
        for i, v in enumerate(plan.per[r]):
            v.assign(mixed[i][r])


def broadcast_variables(models, root_rank: int = 0) -> None:
    """Overwrite every rank's model variables with ``root_rank``'s
    (reference: tensorflow utility.py broadcast_variables)."""
    if isinstance(models, keras.Model) or not isinstance(
            models, (list, tuple)):
        models = [models]
    mixed = [_to_local(_api.broadcast(_to_global(t), root_rank=root_rank))
             for t in _stacked(models)]
    _write_back(models, mixed)


class DistributedOptimizer:
    """Wrap a keras optimizer with cross-rank communication.

    ``communication_type="allreduce"`` (default) averages the incoming
    gradients across ranks before applying — the reference TF
    ``DistributedOptimizer`` (tensorflow/optimizers.py:118-160). Gradients
    arrive per replica: call :meth:`apply_stacked` with one gradient list
    per replica (``apply_gradients`` is accepted only in the 1-replica
    case and raises otherwise — a raw per-replica apply would silently
    skip the communication).

    ``communication_type="neighbor.allreduce"`` applies local gradients
    untouched and then mixes each variable with the rank's in-neighbors
    under the current topology — the decentralized family the reference
    only offered on torch, available to keras here.

    ``device_resident`` (default True): hold the variable rows as jax
    arrays on their ranks' mesh devices (:func:`_install_device_rows`,
    the torch frontend's r13 ``_DevicePlan`` pattern) so the neighbor
    communicate skips the per-step host gather / ``np.stack`` / host
    scatter round-trip. Falls back to the host path transparently when
    residency cannot install (replica count mismatch, shape change).
    """

    def __init__(self, optimizer, models,
                 communication_type: str = "allreduce",
                 num_steps_per_communication: int = 1,
                 device_resident: bool = True) -> None:
        if isinstance(models, keras.Model):
            models = [models]
        if communication_type not in ("allreduce", "neighbor.allreduce"):
            raise ValueError(f"unknown communication_type "
                             f"'{communication_type}'")
        self.models = list(models)
        # A keras optimizer binds to the variables it was built with, so
        # per-rank replicas need per-rank optimizer instances. Accept a
        # zero-arg FACTORY (one instance minted per replica), a list (one
        # per replica), or a single instance for the 1-replica case.
        if callable(optimizer) and not isinstance(
                optimizer, keras.optimizers.Optimizer):
            self.optimizers = [optimizer() for _ in self.models]
        elif isinstance(optimizer, (list, tuple)):
            if len(optimizer) != len(self.models):
                raise ValueError("need one optimizer per model replica")
            self.optimizers = list(optimizer)
        elif len(self.models) == 1:
            self.optimizers = [optimizer]
        else:
            raise ValueError(
                "pass a zero-arg optimizer factory (e.g. lambda: "
                "keras.optimizers.SGD(0.1)) or one optimizer per replica "
                "— a single keras optimizer cannot drive several models")
        self.communication_type = communication_type
        self.num_steps_per_communication = num_steps_per_communication
        self._counter = 0
        self.device_resident = device_resident
        self._device_failed = False

    @property
    def optimizer(self):
        return self.optimizers[0]

    # -- gradient-averaging mode -------------------------------------------

    def apply_stacked(self, grads_per_rank: List[list]) -> None:
        """Apply per-rank gradient lists (one list per model replica).

        allreduce mode: grads are rank-averaged first (the TF reference's
        semantic); neighbor mode: applied locally, then weights mix. Both
        communicate every ``num_steps_per_communication``-th call (local
        steps in between, like the reference's knob).
        """
        if len(grads_per_rank) != len(self.models):
            raise ValueError("need one gradient list per model replica")
        self._counter += 1
        communicate = \
            self._counter % self.num_steps_per_communication == 0
        if communicate and self.communication_type == "allreduce":
            stacked = [np.stack([np.asarray(g[i]) for g in grads_per_rank])
                       for i in range(len(grads_per_rank[0]))]
            averaged = [_to_local(_api.allreduce(_to_global(s), average=True))
                        for s in stacked]
            grads_per_rank = [[a[r] for a in averaged]
                              for r in range(len(self.models))]
        for opt, m, grads in zip(self.optimizers, self.models,
                                 grads_per_rank):
            opt.apply_gradients(
                zip([keras.ops.convert_to_tensor(g) for g in grads],
                    m.trainable_variables))
        if communicate and self.communication_type == "neighbor.allreduce":
            plan = _comm_plan(self.models)
            if self.device_resident and not self._device_failed and \
                    plan.device is None:
                self._device_failed = not _install_device_rows(plan)
            if plan.device is not None and _device_sync(plan):
                _device_communicate(plan)
            else:
                mixed = [_to_local(_api.neighbor_allreduce(_to_global(t)))
                         for t in _stacked(self.models)]
                _write_back(self.models, mixed)

    def apply_gradients(self, grads_and_vars) -> None:
        """Single-replica convenience; multi-replica callers must use
        :meth:`apply_stacked` (a raw per-replica apply would bypass the
        cross-rank communication silently)."""
        if len(self.models) != 1:
            raise RuntimeError(
                "apply_gradients on a multi-replica DistributedOptimizer "
                "would skip communication; use apply_stacked with one "
                "gradient list per replica")
        pairs = list(grads_and_vars)
        self.apply_stacked([[g for g, _ in pairs]])

    def __getattr__(self, name):  # passthrough (learning_rate, ...)
        if "optimizers" not in self.__dict__:
            # unpickling probes dunders before __init__ ran; raise rather
            # than recurse through self.optimizers
            raise AttributeError(name)
        return getattr(self.optimizers[0], name)
