"""Pipeline parallelism — GPipe microbatching over a "pipe" mesh axis.

Net-new vs the reference (data-parallel only, SURVEY §2.6). The TPU-native
shape of pipeline parallelism: transformer blocks are *stage-stacked* (the
same rank-stacked idiom the collectives use — leaf ``x[s]`` is stage s's
layer chunk, sharded one stage per device), activations hand off between
stages with one ``lax.ppermute`` per tick, and the whole GPipe schedule
(fill, steady state, drain — M + S - 1 ticks for M microbatches over S
stages) is a single ``lax.scan`` inside one compiled program. Every stage
runs the same SPMD code; "stage 0 ingests" / "last stage records" are
``lax.select`` on ``axis_index``, not control flow.

Embedding, final norm, and the LM head are replicated and run outside the
pipelined block stack (they are a few percent of the FLOPs; the block stack
is the memory that forces pipelining).

Memory model, stated honestly: the plain forward (:func:`_pp_fwd`,
``pp_apply``) shards *parameters* (one stage chunk per device) but
replicates the microbatch activation buffer and recorded outputs across
stages — fine for exactness demos. The TRAINING path offers the real GPipe
memory discipline via ``pp_train_step_fn(..., fused_loss=True)``
(:func:`_pp_fused_loss`): stage 0 embeds its next microbatch inside each
tick (only tiny int32 tokens are replicated), the last stage folds each
drained microbatch straight into the cross-entropy scalar, and the scan
carry is one [mb, seq, d] activation per stage — with per-layer
``jax.checkpoint`` remat in both paths.

Exact by construction: the pipeline computes the same composition of blocks
as the dense model, so tests assert equality with the single-device oracle.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import optax

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .context import _pvary, reference_attention
from ..utils.compat import shard_map


def pp_mesh(n_stages: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D ``("pipe",)`` mesh over ``n_stages`` devices."""
    from .context import mesh_1d
    return mesh_1d(n_stages, "pipe", devices)


def pp_stack_params(params, n_stages: int):
    """Split TransformerLM params into (stage-stacked blocks, shared rest).

    ``params["block_i"]`` subtrees are stacked along a new leading stage
    axis as ``[n_stages, layers_per_stage, ...]`` leaves; everything else
    (embed, final_norm, lm_head) is returned as-is for the replicated
    prologue/epilogue.
    """
    blocks = sorted(
        (k for k in params if k.startswith("block_")),
        key=lambda k: int(k.split("_")[1]))
    n_layers = len(blocks)
    if n_layers == 0 or n_layers % n_stages:
        raise ValueError(
            f"num_layers {n_layers} must be a positive multiple of "
            f"n_stages {n_stages}")
    per = n_layers // n_stages
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, per) + leaves[0].shape),
        *[params[k] for k in blocks])
    rest = {k: v for k, v in params.items() if not k.startswith("block_")}
    return stacked, rest


def _mirror_modules(model):
    """(block, embed, final_norm, lm_head) mirroring TransformerLM's
    submodules — the ONE place the prologue/epilogue coupling lives (the
    pp-vs-oracle exactness tests pin it against TransformerLM.apply)."""
    # deferred: models.transformer imports parallel.context at package
    # import time, so a top-level import here would be circular
    from ..models.transformer import Block
    import flax.linen as nn

    block = Block(
        model.num_heads, model.d_ff, model.dtype,
        model.attn_fn or functools.partial(reference_attention, causal=True))
    emb = nn.Embed(model.vocab_size, model.d_model, dtype=model.dtype,
                   param_dtype=jnp.float32)
    norm = nn.RMSNorm(dtype=model.dtype, param_dtype=jnp.float32)
    head = nn.Dense(model.vocab_size, dtype=model.dtype,
                    param_dtype=jnp.float32, use_bias=False)
    return block, emb, norm, head


def _chunk_applier(block, stage_params):
    """Per-layer-rematted scan over this stage's layer chunk: the backward
    recomputes each block instead of storing its internals for every tick
    of the schedule — the activation-memory discipline GPipe needs."""
    sp = jax.tree_util.tree_map(lambda x: x[0], stage_params)

    def apply_chunk(x, positions):
        @jax.checkpoint
        def body(h, p):
            return block.apply({"params": p}, h, positions), None
        out, _ = lax.scan(body, x, sp)
        return out

    return apply_chunk


@functools.lru_cache(maxsize=16)
def _pp_fwd(model, mesh: Mesh, n_stages: int, n_micro: int):
    """Unjitted pipelined forward (the differentiable building block)."""
    block, emb_mod, norm_mod, head_mod = _mirror_modules(model)

    def per_stage(stage_params, mb_acts, positions):
        # stage_params: [1, per, ...] this stage's layer chunk
        # mb_acts:      [n_micro, mb, seq, d_model] (replicated)
        me = lax.axis_index("pipe")
        apply_chunk = _chunk_applier(block, stage_params)

        zero = jnp.zeros_like(mb_acts[0])
        outputs = jnp.zeros_like(mb_acts)

        def tick(carry, t):
            x_cur, outputs = carry
            y = apply_chunk(x_cur, positions)
            # last stage records microbatch t-(S-1) when it has drained
            idx = t - (n_stages - 1)
            rec = lax.dynamic_update_index_in_dim(
                outputs, y, jnp.clip(idx, 0, n_micro - 1), axis=0)
            outputs = jnp.where(
                jnp.logical_and(me == n_stages - 1, idx >= 0), rec, outputs)
            # hand y to the next stage; stage 0's incoming slot is fed the
            # next microbatch instead (the wrap-around edge carries garbage)
            nxt = lax.ppermute(
                y, "pipe", [(s, (s + 1) % n_stages) for s in range(n_stages)])
            ingest = lax.dynamic_index_in_dim(
                mb_acts, jnp.clip(t + 1, 0, n_micro - 1), axis=0,
                keepdims=False)
            x_next = jnp.where(me == 0,
                               jnp.where(t + 1 < n_micro, ingest, zero), nxt)
            return (x_next, outputs), None

        x0 = jnp.where(me == 0, mb_acts[0], zero)  # varying via me
        # the replicated zero-init output buffer becomes stage-varying
        # inside the loop; declare it up front so the scan carry types match
        outputs = _pvary(outputs, ("pipe",))
        (_, outputs), _ = lax.scan(
            tick, (x0, outputs), jnp.arange(n_micro + n_stages - 1))
        # replicate the recorded outputs off the last stage
        return lax.psum(
            jnp.where(me == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            "pipe")

    spec_stage = P("pipe")
    mapped = shard_map(
        per_stage, mesh=mesh,
        in_specs=(spec_stage, P(), P()),
        out_specs=P(),
    )

    def fwd(stacked_blocks, rest, tokens):
        b, seq = tokens.shape
        if b % n_micro:
            raise ValueError(
                f"batch {b} must divide into {n_micro} microbatches")
        positions = jnp.arange(seq)
        x = emb_mod.apply({"params": rest["embed"]}, tokens)
        mb = x.reshape((n_micro, b // n_micro) + x.shape[1:])
        out = mapped(stacked_blocks, mb, positions)
        x = out.reshape((b, seq, out.shape[-1]))
        x = norm_mod.apply({"params": rest["final_norm"]}, x)
        logits = head_mod.apply({"params": rest["lm_head"]}, x)
        return logits.astype(jnp.float32)

    return fwd


@functools.lru_cache(maxsize=16)
def _pp_fn(model, mesh: Mesh, n_stages: int, n_micro: int):
    return jax.jit(_pp_fwd(model, mesh, n_stages, n_micro))


def pp_forward_fn(model, mesh: Mesh, n_micro: int = 2):
    """Compiled pipelined forward: ``fwd(stacked_blocks, rest, tokens)``.

    The step-over-step training path: stage-stack and place the params ONCE
    (:func:`pp_stack_params` + :func:`pp_place_params`), then call the
    returned function every step without restacking.
    """
    return _pp_fn(model, mesh, mesh.shape["pipe"], n_micro)


def pp_place_params(stacked, mesh: Mesh):
    """Put a stage-stacked block tree on the mesh, one stage per device."""
    return jax.device_put(stacked, NamedSharding(mesh, P("pipe")))


@functools.lru_cache(maxsize=16)
def _pp_fused_loss(model, mesh: Mesh, n_stages: int, n_micro: int):
    """Loss-fused, activation-light pipelined training loss.

    The plain forward (:func:`_pp_fwd`) replicates the microbatch
    activation buffer and the recorded outputs across stages — fine for
    exactness demos, wrong memory model for training. This builder keeps
    only O(mb · seq · d) live per stage:

      * stage 0 EMBEDS its next microbatch inside each tick (tokens are
        replicated int32 — a few KB — instead of a replicated activation
        buffer; other stages compute the same cheap gather and discard it,
        the standard SPMD select idiom);
      * the LAST stage consumes each drained microbatch immediately —
        final norm + lm_head + cross-entropy inside the tick — and
        accumulates a scalar loss instead of recording logits;
      * the scan carry is one [mb, seq, d] activation per stage, the true
        GPipe boundary-activation footprint, with per-layer remat inside
        the block chunk.

    Gradients of the replicated prologue/epilogue params are psum'd by
    shard_map's transpose automatically. Returns
    ``loss(stacked_blocks, rest, (tokens, targets)) -> scalar``.
    """
    block, emb_mod, norm_mod, head_mod = _mirror_modules(model)

    def per_stage(stage_params, rest, tokens_mb, targets_mb):
        # stage_params [1, per, ...]; rest replicated; tokens/targets
        # [n_micro, mb, seq] replicated int32 (tiny)
        me = lax.axis_index("pipe")
        apply_chunk = _chunk_applier(block, stage_params)

        seq = tokens_mb.shape[2]
        positions = jnp.arange(seq)

        def embed(i):
            toks = lax.dynamic_index_in_dim(tokens_mb, i, axis=0,
                                            keepdims=False)
            return emb_mod.apply({"params": rest["embed"]}, toks)

        # rematted: without the checkpoint the scan backward would stash a
        # per-tick fp32 [mb, seq, vocab] logits residual on every stage —
        # larger than the buffers this schedule exists to avoid. Shape [1],
        # not scalar: jax-0.4.x's shard_map partial-eval promotes scalar
        # remat/scan residuals incorrectly (the stage-varying names land on
        # a rank-0 aval) and grad dies in _check_names with _SpecError, so
        # no float scalar may cross a checkpoint/scan boundary here
        @jax.checkpoint
        def microbatch_loss(y, idx):
            h = norm_mod.apply({"params": rest["final_norm"]}, y)
            logits = head_mod.apply({"params": rest["lm_head"]},
                                    h).astype(jnp.float32)
            tgts = lax.dynamic_index_in_dim(targets_mb, idx, axis=0,
                                            keepdims=False)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgts).mean(keepdims=True).reshape((1,))

        def tick(carry, t):
            x_cur, loss_acc = carry
            y = apply_chunk(x_cur, positions)
            idx = t - (n_stages - 1)
            # every stage computes the epilogue (RMSNorm + d x vocab head
            # matmul + CE) and non-last stages discard it via the mask —
            # the SPMD select idiom. Stated cost: the epilogue is paid
            # S x (M+S-1)/M times vs once in the plain path; a per-device
            # lax.cond would skip it but aborts XLA at runtime (collective
            # -free branches notwithstanding), so uniformity wins here.
            contrib = microbatch_loss(y, jnp.clip(idx, 0, n_micro - 1))
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(me == n_stages - 1, idx >= 0), contrib,
                jnp.zeros_like(contrib))
            nxt = lax.ppermute(
                y, "pipe", [(s, (s + 1) % n_stages) for s in range(n_stages)])
            ingest = embed(jnp.clip(t + 1, 0, n_micro - 1))
            x_next = jnp.where(
                me == 0,
                jnp.where(t + 1 < n_micro, ingest, jnp.zeros_like(ingest)),
                nxt)
            return (x_next, loss_acc), None

        x0 = jnp.where(me == 0, embed(0), jnp.zeros_like(embed(0)))
        loss0 = _pvary(jnp.zeros((1,), jnp.float32), ("pipe",))
        (_, loss_acc), _ = lax.scan(
            tick, (x0, loss0), jnp.arange(n_micro + n_stages - 1))
        # only the last stage accumulated a nonzero partial. Return the
        # per-stage partial ([1], stage-varying) and reduce OUTSIDE the
        # shard_map: an in-body lax.psum of the total trips jax-0.4.x's
        # pre-vma replication checker under grad (the jvp/partial-eval
        # rewrite loses track of the psum'd value's rep and rejects the
        # P() out_spec with _SpecError), while a stage-varying out_spec
        # has nothing to prove — and the transposed ingest/epilogue psums
        # the checker inserts itself are handled fine either way.
        return loss_acc

    mapped = shard_map(
        per_stage, mesh=mesh,
        in_specs=(P("pipe"), P(), P(), P()),
        out_specs=P("pipe"),
    )

    def loss(stacked_blocks, rest, batch):
        tokens, targets = batch
        b, seq = tokens.shape
        if b % n_micro:
            raise ValueError(
                f"batch {b} must divide into {n_micro} microbatches")
        mb = b // n_micro
        # the explicit psum placement: cross-stage total as a sharded sum
        # in the outer program (grads flow back uniformly to every stage)
        return jnp.sum(mapped(stacked_blocks, rest,
                              tokens.reshape(n_micro, mb, seq),
                              targets.reshape(n_micro, mb, seq))) / n_micro

    return loss


def pp_loss_fn(model, mesh: Mesh, n_micro: int = 2):
    """Next-token cross-entropy through the pipelined forward.

    ``loss(stacked_blocks, rest, (tokens, targets)) -> scalar``, fully
    differentiable: autodiff through the GPipe scan runs the backward
    pipeline in reverse tick order (gradient handoffs are the transposed
    ppermutes), with per-layer rematerialization (``jax.checkpoint``) so
    activation memory stays per-tick, not per-schedule.
    """
    fwd = _pp_fwd(model, mesh, mesh.shape["pipe"], n_micro)

    def loss(stacked_blocks, rest, batch):
        tokens, targets = batch
        logits = fwd(stacked_blocks, rest, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    return loss


def pp_train_step_fn(model, mesh: Mesh, optimizer, n_micro: int = 2,
                     fused_loss: bool = False):
    """Compiled pipelined TRAINING step (net-new; SURVEY §2.6 PP row).

    Build ONCE and reuse across the training loop (like ``jax.jit``): each
    call constructs a fresh jitted step, so calling this inside the loop
    recompiles the whole GPipe schedule every iteration.

    ``fused_loss=True`` uses the activation-light schedule
    (:func:`_pp_fused_loss`): stage 0 embeds its next microbatch inside
    each tick and the last stage folds each drained microbatch straight
    into the cross-entropy — per-stage live memory is O(mb·seq·d) instead
    of the replicated full-batch activation buffers of the plain forward.
    Same numerics (loss curves match to fp tolerance).

    ``step(stacked_blocks, rest, opt_state, batch) -> (stacked, rest,
    opt_state, loss)`` where ``batch = (tokens, targets)``; gradients flow
    through the whole GPipe schedule (microbatch accumulation is implicit:
    the loss averages over every microbatch, so its gradient IS the
    accumulated per-microbatch gradient), the optax update runs on both the
    stage-sharded block stack and the replicated prologue/epilogue params,
    and state is donated. Init with :func:`pp_stack_params` +
    :func:`pp_place_params`; numerics match the single-device step exactly
    (tests/test_pipeline_parallel.py pins the loss curve).
    """
    if fused_loss:
        loss = _pp_fused_loss(model, mesh, mesh.shape["pipe"], n_micro)
    else:
        loss = pp_loss_fn(model, mesh, n_micro)

    def step(stacked_blocks, rest, opt_state, batch):
        l, grads = jax.value_and_grad(
            lambda s, r: loss(s, r, batch), argnums=(0, 1))(
                stacked_blocks, rest)
        updates, opt_state = optimizer.update(
            grads, opt_state, (stacked_blocks, rest))
        stacked_blocks, rest = optax.apply_updates(
            (stacked_blocks, rest), updates)
        return stacked_blocks, rest, opt_state, l

    return jax.jit(step, donate_argnums=(0, 1, 2))


def pp_train_init(model, mesh: Mesh, params, optimizer):
    """(stacked_blocks placed on the pipe mesh, rest, opt_state) for
    :func:`pp_train_step_fn` from a plain TransformerLM param dict.

    ``rest`` and ``opt_state`` are explicitly placed mesh-replicated: the
    train step's outputs come back with mesh shardings, so placing the
    inputs the same way avoids a full second compile on step 2 — and since
    the step donates its state, placement also COPIES ``rest`` so donation
    can never invalidate the caller's original param arrays."""
    stacked, rest = pp_stack_params(params, mesh.shape["pipe"])
    stacked = pp_place_params(stacked, mesh)
    rep = NamedSharding(mesh, P())
    # jitted copy-with-placement: device_put may alias an already-placed
    # input even with may_alias=False, and the donating train step must
    # never be able to invalidate the caller's original param arrays — an
    # XLA copy guarantees fresh buffers with the steady-state sharding
    rest = jax.jit(
        lambda t: jax.tree_util.tree_map(jnp.copy, t),
        out_shardings=rep)(rest)
    # Optimizer state must enter the step with the SAME shardings the step
    # outputs (stage-sharded moments for stacked params, replicated for the
    # rest) or call 2 pays a full recompile. optax's init builds moments as
    # shape-only constants, so sharding does not propagate from the params —
    # place param-shaped state leaves like their params explicitly, and
    # sweep the param-independent leaves (e.g. adam's count) to
    # mesh-replicated (plain init would drop them on the default device,
    # which may not even belong to the mesh).
    opt_state = optimizer.init((stacked, rest))
    opt_state = optax.tree_utils.tree_map_params(
        optimizer, lambda s, p: jax.device_put(s, p.sharding), opt_state,
        (stacked, rest))
    opt_state = jax.tree_util.tree_map(
        lambda x: x if isinstance(getattr(x, "sharding", None), NamedSharding)
        else jax.device_put(x, rep), opt_state)
    return stacked, rest, opt_state


def pp_apply(model, params, tokens, mesh: Mesh, n_micro: int = 2):
    """One-shot pipelined forward: GPipe schedule over the "pipe" axis.

    ``params`` is the plain TransformerLM param dict; it is stage-stacked
    and placed on every call — convenient for evaluation. For training
    loops use :func:`pp_forward_fn` with pre-placed params.
    """
    n_stages = mesh.shape["pipe"]
    stacked, rest = pp_stack_params(params, n_stages)
    return pp_forward_fn(model, mesh, n_micro)(
        pp_place_params(stacked, mesh), rest, tokens)
