"""Long-context / sequence parallelism over the device mesh.

The reference framework predates long-context work and has no attention code
(SURVEY.md §5.7) — but its core machinery, ring/exponential-graph neighbor
exchange, is exactly the substrate context parallelism rides on. This package
makes that substrate a first-class capability of the rebuild:

  * ``ring_attention`` — blockwise flash attention with K/V blocks rotating
    around the mesh ring by ``ppermute`` (one ICI hop per step), online
    softmax renormalization, O(S/n) memory per chip.
  * ``ulysses_attention`` — all-to-all sequence parallelism: re-shard
    sequence -> heads, run dense local attention, re-shard back.
  * ``sequence_sharding`` — place [B, S, H, D] arrays sequence-sharded.

Plus the rest of the parallelism axes: tensor parallelism (``tensor.py``,
Megatron layout via GSPMD annotations over a 2-D (data, model) mesh),
pipeline parallelism (``pipeline.py``, GPipe microbatching with ppermute
stage handoffs), and expert parallelism (``expert.py``, Switch MoE with
all_to_all dispatch).
"""

from .context import (
    reference_attention,
    ring_attention,
    ring_attention_shard,
    sequence_sharding,
    ulysses_attention,
    ulysses_attention_shard,
)
from .expert import (
    SwitchFFN,
    ep_apply,
    ep_lm_apply,
    ep_lm_init,
    ep_lm_loss_fn,
    ep_mesh,
    ep_place_params,
    load_balance_loss,
    moe_param_specs,
    switch_dispatch,
)
from .flash import flash_attention, flash_block
from .lm import chunked_ce_loss, cp_apply, cp_loss_fn
from .pipeline import (
    pp_apply,
    pp_forward_fn,
    pp_loss_fn,
    pp_mesh,
    pp_place_params,
    pp_stack_params,
    pp_train_init,
    pp_train_step_fn,
)
from .tensor import (
    LM_TP_RULES,
    tp_apply,
    tp_loss_fn,
    tp_mesh,
    tp_shard_params,
)

__all__ = [
    "flash_attention",
    "flash_block",
    "ring_attention",
    "ring_attention_shard",
    "ulysses_attention",
    "ulysses_attention_shard",
    "reference_attention",
    "sequence_sharding",
    "cp_apply",
    "cp_loss_fn",
    "LM_TP_RULES",
    "tp_apply",
    "tp_loss_fn",
    "tp_mesh",
    "tp_shard_params",
    "pp_apply",
    "pp_forward_fn",
    "pp_place_params",
    "pp_mesh",
    "pp_stack_params",
    "chunked_ce_loss",
    "pp_loss_fn",
    "pp_train_init",
    "pp_train_step_fn",
    "SwitchFFN",
    "ep_apply",
    "ep_lm_apply",
    "ep_lm_init",
    "ep_lm_loss_fn",
    "ep_place_params",
    "ep_mesh",
    "load_balance_loss",
    "moe_param_specs",
    "switch_dispatch",
]
