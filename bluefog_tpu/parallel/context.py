"""Context parallelism: ring attention and Ulysses all-to-all attention.

Ring attention (Liu et al. 2023) maps 1:1 onto the framework's ring-topology
machinery: the mesh's rank axis forms the ring, K/V shards hop one neighbor
per step via ``lax.ppermute`` (a single ICI hop on a TPU torus), and each
chip folds the arriving block into a numerically stable online softmax.
Peak memory per chip is O(S/n) for activations and O(Sq/n * Sk/n) for the
score block, so sequence length scales linearly with the ring size.

Layout contract: ``[batch, seq, heads, head_dim]``, sequence sharded over
the mesh axis. Compute runs in float32 accumulation regardless of input
dtype (bf16 in, f32 softmax statistics — the standard MXU recipe).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.compat import shard_map

# Python scalar, not jnp.float32(...): a concrete array here would initialize
# the XLA backend at import time, breaking jax.distributed.initialize() in
# multi-controller jobs (it must run before any backend touch).
_NEG = -1e30

if hasattr(lax, "pcast"):
    def _pvary(x, axes):
        return lax.pcast(x, axes, to="varying")
elif hasattr(lax, "pvary"):  # jax < 0.9: pcast absent, pvary not deprecated
    def _pvary(x, axes):
        return lax.pvary(x, axes)
else:  # jax <= 0.4.x: no varying-type system at all — shard_map does not
    # track device-varying annotations, so the marker is a no-op
    def _pvary(x, axes):
        return x


def reference_attention(q, k, v, causal: bool = False):
    """Dense single-device attention; the correctness oracle for the tests."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(Sq)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ring_attention_shard(q, k, v, *, axis_name: str, causal: bool = False,
                         use_flash: bool = False, interpret: bool = False):
    """Per-device ring attention body; call INSIDE shard_map.

    ``q/k/v``: this chip's sequence shard [B, S/n, H, D]. K and V make one
    full trip around the ring; each step computes a [Sq/n, Sk/n] score block
    against the currently held K/V block and renormalizes the running
    (max, sum, out) accumulators — flash attention's streaming update with
    the stream order given by ring position.

    ``use_flash=True`` computes each block's partials with the pallas VMEM
    kernel (parallel.flash.flash_block) instead of XLA einsums: scores never
    reach HBM, which is what lets per-chip K/V blocks grow long. ``interpret``
    runs that kernel in interpreter mode (CPU test meshes). Both paths
    differentiate through the same reverse-rotation ring backward schedule
    (``_ring_backward``): one more K/V trip around the ring with gradient
    blocks traveling alongside — residuals and carries are O(S/n) per chip.
    The flash path's per-step block gradients run in the pallas backward
    kernels (``flash.flash_block_bwd``, flash-attention-2 dq + dk/dv
    passes), so probability tiles stay in VMEM in the backward too; the
    einsum path materializes one [S/n, S/n] f32 block per step via XLA.
    Reverse-mode only: the custom VJP means ``jax.jvp``/forward-over-reverse
    is unsupported on both ring paths.
    """
    if use_flash:
        return _ring_flash_diff(q, k, v, axis_name, causal, interpret)
    return _ring_einsum_diff(q, k, v, axis_name, causal)


def _axis_index(axis_name: str):
    """``lax.axis_index`` that also lowers on the jax-0.4.x CPU backend.

    There, the ring bodies' axis index emits a PartitionId HLO that the
    SPMD partitioner rejects (``UNIMPLEMENTED: PartitionId``). An
    all_to_all over an iota is equivalent — device i keeps element i of
    ``arange(n)`` — and lowers on every backend; it costs one n-element
    int32 exchange outside the scan, so keep the native lowering where it
    works.
    """
    if jax.default_backend() != "cpu":
        return lax.axis_index(axis_name)
    n = lax.psum(1, axis_name)
    return lax.all_to_all(jnp.arange(n, dtype=jnp.int32), axis_name,
                          split_axis=0, concat_axis=0, tiled=True)[0]


def _ring_einsum_partials(q, k, v, axis_name: str, causal: bool):
    """Einsum ring forward; returns (normalized out, row max m, row sum l),
    m/l in [B, Sq, H] layout — the backward's softmax reconstruction keys."""
    n = lax.psum(1, axis_name)
    me = _axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale
    q_pos = me * Sq + jnp.arange(Sq)

    # K/V travel "backwards" (rank i -> i+1) so that at step t rank ``me``
    # holds block (me - t) % n.
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, t):
        o, m, l, kc, vc = carry
        blk = (me - t) % n
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kc.astype(jnp.float32))
        k_pos = blk * Sk + jnp.arange(Sk)
        if causal:
            allowed = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(allowed[None, None], s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            # guard fully-masked rows: never let masked scores contribute
            p = jnp.where(allowed[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, vc.astype(jnp.float32))
        o_new = o * jnp.moveaxis(corr, 1, -1)[..., None] + pv
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o_new, m_new, l_new, kc, vc), None

    # pvary: the accumulators are device-varying from step 0 (shard_map's
    # varying-manual-axes check requires carry types to match body outputs).
    o0 = _pvary(jnp.zeros((B, Sq, H, D), jnp.float32), (axis_name,))
    m0 = _pvary(jnp.full((B, H, Sq), _NEG, jnp.float32), (axis_name,))
    l0 = _pvary(jnp.zeros((B, H, Sq), jnp.float32), (axis_name,))
    (o, m, l, _, _), _ = lax.scan(body, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.moveaxis(l, 1, -1)[..., None]
    return (out.astype(q.dtype),
            jnp.moveaxis(m, 1, -1), jnp.moveaxis(l, 1, -1))


def _ring_backward(axis_name: str, causal: bool, res, g,
                   use_flash: bool = False, interpret: bool = False):
    """Reverse-rotation ring-attention backward.

    One more K/V trip around the ring: per-block softmax probabilities are
    reconstructed from the saved final (m, l) row statistics, and each K/V
    block's gradient accumulates on a buffer that TRAVELS with the block —
    after n steps every gradient block is back on its home chip. Residuals
    and carries are all O(S/n) per chip; nothing quadratic, nothing
    sequence-global (the standard ring-attention backward schedule).

    ``use_flash=True`` computes each step's (dq, dk, dv) partials with the
    pallas backward kernels (``flash.flash_block_bwd``, flash-attention-2
    dq + dk/dv passes) instead of XLA einsums — probability tiles live in
    VMEM only, restoring the kernel forward's scores-never-reach-HBM
    property for the backward as well.
    """
    q, k, v, out, m, l = res
    n = lax.psum(1, axis_name)
    me = _axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    gf = g.astype(jnp.float32)
    # D_i = sum_d g_i * out_i: the softmax-jacobian projection term
    d_term = jnp.sum(gf * out.astype(jnp.float32), axis=-1)  # [B, Sq, H]
    perm = [(i, (i + 1) % n) for i in range(n)]
    q_off = me * Sq

    def block_grads_einsum(kc, vc, blk):
        qf = q.astype(jnp.float32) * scale
        m_b = jnp.moveaxis(m, -1, 1)          # [B, H, Sq]
        inv_l = 1.0 / jnp.moveaxis(l, -1, 1)  # l > 0 for every valid row
        d_b = jnp.moveaxis(d_term, -1, 1)
        q_pos = me * Sq + jnp.arange(Sq)
        kcf = kc.astype(jnp.float32)
        vcf = vc.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, kcf)
        k_pos = blk * Sk + jnp.arange(Sk)
        if causal:
            allowed = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(allowed[None, None], s, _NEG)
        p = jnp.exp(s - m_b[..., None]) * inv_l[..., None]
        if causal:
            p = jnp.where(allowed[None, None], p, 0.0)
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vcf)
        ds = p * (dp - d_b[..., None])
        return (jnp.einsum("bhqk,bkhd->bqhd", ds, kcf) * scale,
                jnp.einsum("bhqk,bqhd->bkhd", ds, qf),  # qf carries scale
                jnp.einsum("bhqk,bqhd->bkhd", p, gf))

    def block_grads_flash(kc, vc, blk):
        from .flash import flash_block_bwd
        return flash_block_bwd(q, kc, vc, gf, d_term, m, l,
                               q_off, blk * Sk, causal=causal,
                               interpret=interpret)

    block_grads = block_grads_flash if use_flash else block_grads_einsum

    def body(carry, t):
        dq, kc, vc, dkc, dvc = carry
        blk = (me - t) % n
        dq_blk, dk_blk, dv_blk = block_grads(kc, vc, blk)
        dq = dq + dq_blk
        dkc = dkc + dk_blk
        dvc = dvc + dv_blk
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dkc = lax.ppermute(dkc, axis_name, perm)
        dvc = lax.ppermute(dvc, axis_name, perm)
        return (dq, kc, vc, dkc, dvc), None

    dq0 = _pvary(jnp.zeros((B, Sq, H, D), jnp.float32), (axis_name,))
    dk0 = _pvary(jnp.zeros((B, Sk, H, D), jnp.float32), (axis_name,))
    dv0 = _pvary(jnp.zeros((B, Sk, H, D), jnp.float32), (axis_name,))
    (dq, _, _, dk, dv), _ = lax.scan(
        body, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ring_einsum_diff(q, k, v, axis_name, causal):
    out, _, _ = _ring_einsum_partials(q, k, v, axis_name, causal)
    return out


def _ring_einsum_fwd(q, k, v, axis_name, causal):
    out, m, l = _ring_einsum_partials(q, k, v, axis_name, causal)
    return out, (q, k, v, out, m, l)


def _ring_einsum_bwd(axis_name, causal, res, g):
    return _ring_backward(axis_name, causal, res, g)


_ring_einsum_diff.defvjp(_ring_einsum_fwd, _ring_einsum_bwd)


def _ring_attention_flash(q, k, v, *, axis_name: str, causal: bool,
                          interpret: bool):
    """Ring loop whose per-block compute is the pallas flash kernel.

    Returns (normalized out, m, l) — the same partials contract as
    :func:`_ring_einsum_partials`, so both forwards share
    :func:`_ring_backward`.
    """
    from .flash import flash_block

    n = lax.psum(1, axis_name)
    me = _axis_index(axis_name)
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    q_off = me * Sq
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(t, carry):
        o, m, l, kc, vc = carry
        blk = (me - t) % n
        bo, bm, bl = flash_block(q, kc, vc, q_off, blk * Sk,
                                 causal=causal, interpret=interpret)
        m_new = jnp.maximum(m, bm)                      # [B, Sq, H]
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(bm - m_new)
        l_new = l * c_old + bl * c_blk
        o_new = o * c_old[..., None] + bo * c_blk[..., None]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o_new, m_new, l_new, kc, vc

    o0 = _pvary(jnp.zeros((B, Sq, H, D), jnp.float32), (axis_name,))
    m0 = _pvary(jnp.full((B, Sq, H), _NEG, jnp.float32), (axis_name,))
    l0 = _pvary(jnp.zeros((B, Sq, H), jnp.float32), (axis_name,))
    o, m, l, _, _ = lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    return (o / l[..., None]).astype(q.dtype), m, l


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash_diff(q, k, v, axis_name, causal, interpret):
    out, _, _ = _ring_attention_flash(q, k, v, axis_name=axis_name,
                                      causal=causal, interpret=interpret)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, interpret):
    out, m, l = _ring_attention_flash(q, k, v, axis_name=axis_name,
                                      causal=causal, interpret=interpret)
    return out, (q, k, v, out, m, l)


def _ring_flash_bwd(axis_name, causal, interpret, res, g):
    # same reverse-rotation schedule as the einsum ring (the flash kernel's
    # (m, l) partials are the identical softmax statistics), with the
    # per-block math in the pallas backward kernels
    return _ring_backward(axis_name, causal, res, g,
                          use_flash=True, interpret=interpret)


_ring_flash_diff.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ulysses_attention_shard(q, k, v, *, axis_name: str, causal: bool = False):
    """Per-device Ulysses body; call INSIDE shard_map.

    All-to-all re-shards sequence -> heads, dense attention runs on full
    sequence with H/n local heads, all-to-all re-shards back. One big
    bisection-bandwidth exchange instead of n ring hops — better when heads
    are plentiful and the interconnect is fat; requires H % n == 0.
    """
    n = lax.psum(1, axis_name)
    # [B, S/n, H, D] -> [B, S, H/n, D]
    q, k, v = (
        lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)
        for x in (q, k, v)
    )
    out = reference_attention(q, k, v, causal=causal)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def sequence_sharding(mesh: Mesh, axis: str = "rank") -> NamedSharding:
    """Sharding for [B, S, H, D] arrays, sequence dim over the mesh axis."""
    return NamedSharding(mesh, P(None, axis))


def mesh_1d(n: int, axis: str, devices=None) -> Mesh:
    """A 1-D mesh of ``n`` devices under the given axis name (shared by the
    pipe/expert mesh builders)."""
    import numpy as _np

    if devices is None:
        devices = jax.devices()
    if len(devices) < n:
        raise ValueError(f"need {n} devices, have {len(devices)}")
    return Mesh(_np.asarray(devices[:n]), (axis,))


@functools.lru_cache(maxsize=32)
def _cp_fn(mesh: Mesh, axis: str, causal: bool, kind: str,
           use_flash: bool = False, interpret: bool = False):
    if kind == "ring":
        body = functools.partial(ring_attention_shard, axis_name=axis,
                                 causal=causal, use_flash=use_flash,
                                 interpret=interpret)
    else:
        body = functools.partial(ulysses_attention_shard, axis_name=axis,
                                 causal=causal)
    spec = P(None, axis)
    mapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # The pallas HLO *interpreter* (CPU tests) mis-propagates vma through
        # the kernel's mixed varying/uniform operands and aborts; real TPU
        # lowering handles it (flash.py declares vma on out_shape). Disable
        # the check only for interpret mode, per the JAX-suggested
        # workaround.
        check_vma=not (use_flash and interpret),
    )
    return jax.jit(mapped)


def _cp_call(kind: str, q, k, v, mesh: Optional[Mesh], axis: str,
             causal: bool, use_flash: bool = False, interpret: bool = False):
    if mesh is None:
        from ..runtime.state import _global_state
        st = _global_state()
        st.check_initialized()
        mesh = st.mesh
        axis = "rank"
    n = mesh.shape[axis]
    if q.shape[1] % n or k.shape[1] % n:
        raise ValueError(
            f"sequence length must divide the {axis} axis size {n}; got "
            f"q seq {q.shape[1]}, k seq {k.shape[1]}")
    if kind == "ulysses" and q.shape[2] % n:
        raise ValueError(
            f"ulysses needs heads % {n} == 0; got {q.shape[2]} heads")
    return _cp_fn(mesh, axis, causal, kind, use_flash, interpret)(q, k, v)


def ring_attention(q, k, v, mesh: Optional[Mesh] = None, axis: str = "rank",
                   causal: bool = False, use_flash: bool = False,
                   interpret: bool = False):
    """Ring attention over global [B, S, H, D] arrays (S sharded on ``axis``).

    Uses the initialized runtime's rank mesh when ``mesh`` is None.
    ``use_flash`` routes each block through the pallas VMEM kernel.
    """
    return _cp_call("ring", q, k, v, mesh, axis, causal, use_flash, interpret)


def ulysses_attention(q, k, v, mesh: Optional[Mesh] = None,
                      axis: str = "rank", causal: bool = False):
    """All-to-all (Ulysses) context-parallel attention over [B, S, H, D]."""
    return _cp_call("ulysses", q, k, v, mesh, axis, causal)
