"""Expert parallelism — Switch-style MoE with all_to_all dispatch.

Net-new vs the reference (data-parallel only, SURVEY §2.6). The GShard/
Switch recipe in its TPU-native form: one expert FFN per device along an
"expert" mesh axis, top-1 gating, capacity-bounded dispatch expressed as
static-shape einsums, and exactly two ``lax.all_to_all`` hops per layer
(tokens to their expert, results back). Everything is static shapes — the
capacity bound C is what makes data-dependent routing compile.

Semantics (standard Switch): each token goes to its top-scoring expert,
scaled by the gate probability; tokens beyond an expert's capacity are
dropped (output zero) — choose ``capacity_factor >= num_experts`` to make
dropping impossible, which is how the exactness tests pin the SPMD path to
the dense oracle (``SwitchFFN``'s plain ``__call__``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def ep_mesh(n_experts: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D ``("expert",)`` mesh over ``n_experts`` devices."""
    from .context import mesh_1d
    return mesh_1d(n_experts, "expert", devices)


class SwitchFFN(nn.Module):
    """Mixture-of-experts FFN, top-1 (Switch) routing.

    ``__call__`` is the dense single-device oracle: it evaluates every
    expert on every token and selects with a one-hot — O(E) FLOPs, used for
    init, small models, and as the correctness reference for
    :func:`ep_apply`, which computes the same function sparsely across the
    expert mesh.
    """

    num_experts: int
    d_ff: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        gate = self.param("gate", nn.initializers.lecun_normal(),
                          (d, self.num_experts), jnp.float32)
        up = self.param("up", nn.initializers.lecun_normal(),
                        (self.num_experts, d, self.d_ff), jnp.float32)
        down = self.param("down", nn.initializers.lecun_normal(),
                          (self.num_experts, self.d_ff, d), jnp.float32)
        in_dtype = x.dtype
        x = x.astype(self.dtype)
        probs = jax.nn.softmax(
            (x @ gate.astype(self.dtype)).astype(jnp.float32), axis=-1)
        best = jnp.argmax(probs, axis=-1)                       # [..,]
        sel = jax.nn.one_hot(best, self.num_experts, dtype=self.dtype)
        h = jnp.einsum("...d,edf->...ef", x, up.astype(self.dtype))
        h = nn.gelu(h)
        y = jnp.einsum("...ef,efd->...ed", h, down.astype(self.dtype))
        p_best = jnp.max(probs, axis=-1).astype(self.dtype)
        out = jnp.einsum("...ed,...e->...d", y, sel) * p_best[..., None]
        return out.astype(in_dtype)


def load_balance_loss(probs, best, num_experts: int):
    """Switch aux loss: ``E * sum_e f_e * P_e`` (Fedus et al. 2021, eq. 4)."""
    f = jnp.mean(jax.nn.one_hot(best, num_experts, dtype=jnp.float32),
                 axis=tuple(range(best.ndim)))
    pbar = jnp.mean(probs, axis=tuple(range(probs.ndim - 1)))
    return num_experts * jnp.sum(f * pbar)


@functools.lru_cache(maxsize=16)
def _ep_fn(mesh: Mesh, num_experts: int, capacity: int, dtype):
    def per_device(gate, up, down, x):
        # gate [d, E] replicated; up [1, d, d_ff] / down [1, d_ff, d] = this
        # device's expert; x [b_local, s, d] = this device's tokens.
        b, s, d = x.shape
        t = b * s
        xt = x.reshape(t, d).astype(dtype)
        probs = jax.nn.softmax(
            (xt @ gate.astype(dtype)).astype(jnp.float32), axis=-1)
        best = jnp.argmax(probs, axis=-1)                        # [t]
        p_best = jnp.max(probs, axis=-1).astype(dtype)
        sel = jax.nn.one_hot(best, num_experts, dtype=jnp.int32)  # [t, E]
        # position of each token within its expert's send buffer
        pos = jnp.cumsum(sel, axis=0) * sel - 1                   # [t, E]
        keep = (pos < capacity) & (sel > 0)
        # dispatch[t, e, c]: token t occupies slot c of the buffer to e
        disp = keep[..., None] & (
            jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                           dtype=jnp.int32) > 0)
        disp = disp.astype(dtype)                                 # [t, E, C]
        send = jnp.einsum("tec,td->ecd", disp, xt)                # [E, C, d]
        # tokens to their expert: device e receives one [C, d] block per peer
        recv = lax.all_to_all(send, "expert", split_axis=0, concat_axis=0,
                              tiled=True)                         # [E, C, d]
        h = nn.gelu(jnp.einsum("ncd,df->ncf", recv, up[0].astype(dtype)))
        y = jnp.einsum("ncf,fd->ncd", h, down[0].astype(dtype))   # [E, C, d]
        # results back to the token-owning devices
        back = lax.all_to_all(y, "expert", split_axis=0, concat_axis=0,
                              tiled=True)                         # [E, C, d]
        out = jnp.einsum("tec,ecd->td", disp, back) * p_best[:, None]
        aux = load_balance_loss(probs, best, num_experts)
        return out.reshape(b, s, d).astype(x.dtype), aux[None]

    mapped = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P("expert"), P("expert"), P("expert")),
        out_specs=(P("expert"), P("expert")),
    )
    return jax.jit(lambda g, u, dn, x: mapped(g, u, dn, x))


def ep_place_params(params, mesh: Mesh):
    """Place a SwitchFFN param dict on the expert mesh ONCE (gate
    replicated, up/down one expert per device); re-placing already-placed
    arrays is a no-op, so training loops can pass the result to
    :func:`ep_apply` every step without transfers."""
    return {
        "gate": jax.device_put(params["gate"], NamedSharding(mesh, P())),
        "up": jax.device_put(params["up"], NamedSharding(mesh, P("expert"))),
        "down": jax.device_put(params["down"],
                               NamedSharding(mesh, P("expert"))),
    }


def ep_apply(params, x, mesh: Mesh, capacity_factor: float = 2.0,
             dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel SwitchFFN forward.

    ``params`` is a :class:`SwitchFFN` param dict (``gate``/``up``/``down``)
    with ``num_experts == mesh.shape["expert"]``; ``x`` is ``[B, S, d]``
    with B divisible by the expert-axis size (tokens ride the same devices
    as experts, the standard DP+EP co-location). Returns ``(y, aux)`` where
    ``aux`` is the per-device Switch load-balance loss ``[n]``.

    ``dtype`` is the compute dtype and must match the ``SwitchFFN.dtype``
    used as the oracle (default: ``x.dtype``, which equals the module
    default of float32 for float32 inputs).

    Capacity per expert and source device is
    ``ceil(capacity_factor * local_tokens / num_experts)``; overflowed
    tokens get zero output (Switch semantics). ``capacity_factor >=
    num_experts`` guarantees no drops.
    """
    n = mesh.shape["expert"]
    if params["up"].shape[0] != n:
        raise ValueError(
            f"params have {params['up'].shape[0]} experts but the mesh "
            f"axis is {n}")
    b, s, d = x.shape
    if b % n:
        raise ValueError(f"batch {b} must divide the expert axis size {n}")
    local_tokens = (b // n) * s
    capacity = int(np.ceil(capacity_factor * local_tokens / n))
    placed = ep_place_params(params, mesh)
    x = jax.device_put(x, NamedSharding(mesh, P("expert")))
    return _ep_fn(mesh, n, capacity, jnp.dtype(dtype or x.dtype).name)(
        placed["gate"], placed["up"], placed["down"], x)
