"""Expert parallelism — Switch-style MoE with all_to_all dispatch.

Net-new vs the reference (data-parallel only, SURVEY §2.6). The GShard/
Switch recipe in its TPU-native form: one expert FFN per device along an
"expert" mesh axis, top-1 gating, capacity-bounded dispatch expressed as
static-shape einsums, and exactly two ``lax.all_to_all`` hops per layer
(tokens to their expert, results back). Everything is static shapes — the
capacity bound C is what makes data-dependent routing compile.

Semantics (standard Switch): each token goes to its top-scoring expert,
scaled by the gate probability; tokens beyond an expert's capacity are
dropped (output zero) — choose ``capacity_factor >= num_experts`` to make
dropping impossible, which is how the exactness tests pin the SPMD path to
the dense oracle (``SwitchFFN``'s plain ``__call__``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from ..utils.compat import shard_map


def ep_mesh(n_experts: int, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D ``("expert",)`` mesh over ``n_experts`` devices."""
    from .context import mesh_1d
    return mesh_1d(n_experts, "expert", devices)


class SwitchFFN(nn.Module):
    """Mixture-of-experts FFN, top-1 (Switch) routing.

    Two execution modes sharing one gating function:

    * ``expert_axis=None`` (default): the dense single-device oracle — it
      evaluates every expert on every token and selects with a one-hot.
      O(E) FLOPs; used for init, small models, and as the correctness
      reference for the sparse path.
    * ``expert_axis="expert"``: the module is being applied INSIDE a
      ``shard_map`` over that mesh axis (one expert per device, ``up`` /
      ``down`` arriving as this device's local ``[1, ...]`` shard via a
      ``P(axis)`` in_spec). Tokens route to their expert and back with
      two ``lax.all_to_all`` hops — the GShard/Switch dispatch, usable as
      a drop-in FFN inside a larger sharded model (``MoETransformerLM``).

    In the sparse mode the Switch load-balance aux loss is sowed under
    ``intermediates/moe_aux`` (per-device scalar).
    """

    num_experts: int
    d_ff: int
    dtype: Any = jnp.float32
    expert_axis: Optional[str] = None
    capacity_factor: float = 2.0

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        e_local = 1 if self.expert_axis else self.num_experts
        gate = self.param("gate", nn.initializers.lecun_normal(),
                          (d, self.num_experts), jnp.float32)
        up = self.param("up", nn.initializers.lecun_normal(),
                        (e_local, d, self.d_ff), jnp.float32)
        down = self.param("down", nn.initializers.lecun_normal(),
                          (e_local, self.d_ff, d), jnp.float32)
        if self.expert_axis:
            leading = x.shape[:-1]
            t = int(np.prod(leading))
            capacity = int(np.ceil(
                self.capacity_factor * t / self.num_experts))
            out, aux = switch_dispatch(
                gate, up, down, x.reshape(t, d), self.expert_axis,
                self.num_experts, capacity, self.dtype)
            self.sow("intermediates", "moe_aux", aux)
            return out.reshape(leading + (d,))
        in_dtype = x.dtype
        x = x.astype(self.dtype)
        probs = jax.nn.softmax(
            (x @ gate.astype(self.dtype)).astype(jnp.float32), axis=-1)
        best = jnp.argmax(probs, axis=-1)                       # [..,]
        sel = jax.nn.one_hot(best, self.num_experts, dtype=self.dtype)
        h = jnp.einsum("...d,edf->...ef", x, up.astype(self.dtype))
        h = nn.gelu(h)
        y = jnp.einsum("...ef,efd->...ed", h, down.astype(self.dtype))
        p_best = jnp.max(probs, axis=-1).astype(self.dtype)
        out = jnp.einsum("...ed,...e->...d", y, sel) * p_best[..., None]
        return out.astype(in_dtype)


def switch_dispatch(gate, up_local, down_local, xt, axis: str,
                    num_experts: int, capacity: int, dtype):
    """The sparse Switch body for ONE device inside a shard_map over
    ``axis``: top-1 gate, capacity-bounded dispatch, all_to_all to the
    owning expert, FFN, all_to_all back. ``xt`` is this device's tokens
    ``[t, d]``; ``up_local``/``down_local`` are its expert's weights
    ``[1, d, d_ff]`` / ``[1, d_ff, d]``. Returns ``([t, d], aux_scalar)``.
    Shared by :func:`ep_apply` and the ``expert_axis`` mode of
    :class:`SwitchFFN`."""
    in_dtype = xt.dtype
    xt = xt.astype(dtype)
    probs = jax.nn.softmax(
        (xt @ gate.astype(dtype)).astype(jnp.float32), axis=-1)
    best = jnp.argmax(probs, axis=-1)                        # [t]
    p_best = jnp.max(probs, axis=-1).astype(dtype)
    sel = jax.nn.one_hot(best, num_experts, dtype=jnp.int32)  # [t, E]
    # position of each token within its expert's send buffer
    pos = jnp.cumsum(sel, axis=0) * sel - 1                   # [t, E]
    keep = (pos < capacity) & (sel > 0)
    # dispatch[t, e, c]: token t occupies slot c of the buffer to e
    disp = keep[..., None] & (
        jax.nn.one_hot(jnp.clip(pos, 0, capacity - 1), capacity,
                       dtype=jnp.int32) > 0)
    disp = disp.astype(dtype)                                 # [t, E, C]
    send = jnp.einsum("tec,td->ecd", disp, xt)                # [E, C, d]
    # tokens to their expert: device e receives one [C, d] block per peer
    recv = lax.all_to_all(send, axis, split_axis=0, concat_axis=0,
                          tiled=True)                         # [E, C, d]
    h = nn.gelu(jnp.einsum("ncd,df->ncf", recv, up_local[0].astype(dtype)))
    y = jnp.einsum("ncf,fd->ncd", h, down_local[0].astype(dtype))
    # results back to the token-owning devices
    back = lax.all_to_all(y, axis, split_axis=0, concat_axis=0,
                          tiled=True)                         # [E, C, d]
    out = jnp.einsum("tec,ecd->td", disp, back) * p_best[:, None]
    aux = load_balance_loss(probs, best, num_experts)
    return out.astype(in_dtype), aux


def load_balance_loss(probs, best, num_experts: int):
    """Switch aux loss: ``E * sum_e f_e * P_e`` (Fedus et al. 2021, eq. 4)."""
    f = jnp.mean(jax.nn.one_hot(best, num_experts, dtype=jnp.float32),
                 axis=tuple(range(best.ndim)))
    pbar = jnp.mean(probs, axis=tuple(range(probs.ndim - 1)))
    return num_experts * jnp.sum(f * pbar)


@functools.lru_cache(maxsize=16)
def _ep_fn(mesh: Mesh, num_experts: int, capacity: int, dtype):
    def per_device(gate, up, down, x):
        # gate [d, E] replicated; up [1, d, d_ff] / down [1, d_ff, d] = this
        # device's expert; x [b_local, s, d] = this device's tokens.
        b, s, d = x.shape
        out, aux = switch_dispatch(gate, up, down, x.reshape(b * s, d),
                                   "expert", num_experts, capacity, dtype)
        return out.reshape(b, s, d), aux[None]

    mapped = shard_map(
        per_device, mesh=mesh,
        in_specs=(P(), P("expert"), P("expert"), P("expert")),
        out_specs=(P("expert"), P("expert")),
    )
    return jax.jit(lambda g, u, dn, x: mapped(g, u, dn, x))


def moe_param_specs(params, axis: str = "expert"):
    """PartitionSpec tree for a model containing :class:`SwitchFFN`
    submodules: expert weights (``up``/``down`` leaves of a SwitchFFN,
    named ``moe`` inside :class:`models.transformer.MoEBlock`) shard on
    the expert axis; the gate and every dense/attention/embedding param
    stay replicated. (A dense FFN's ``up``/``down`` *modules* hold a
    ``kernel`` leaf, so their paths end in ``kernel`` and fall through to
    replicated.)"""
    def spec(path, leaf):  # noqa: ARG001
        keys = [str(getattr(k, "key", k)) for k in path]
        if keys and keys[-1] in ("up", "down") and (
                "moe" in keys or any(k.startswith("SwitchFFN")
                                     for k in keys)):
            return P(axis)
        return P()
    return jax.tree_util.tree_map_with_path(spec, params)


def _sum_intermediates(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    total = jnp.zeros((), jnp.float32)
    for leaf in leaves:
        total = total + jnp.sum(jnp.asarray(leaf, jnp.float32))
    return total


def ep_lm_init(model, rng, tokens):
    """Init params for an ``expert_axis`` MoE model via its dense twin.

    The sparse variant declares per-device ``[1, ...]`` expert shards, so
    it cannot init outside the mesh; the dense twin (same config,
    ``expert_axis=None``) declares the full ``[E, ...]`` weights with the
    SAME tree structure and rng stream. Shard the result with
    :func:`moe_param_specs` (P(axis) splits the leading expert dim back
    into the per-device views the sparse apply expects)."""
    import dataclasses
    twin = dataclasses.replace(model, expert_axis=None)
    return twin.init(rng, tokens)["params"]


def ep_lm_apply(model, params, tokens, mesh: Mesh, axis: str = "expert"):
    """Expert-parallel forward of a ``expert_axis=axis`` MoE LM.

    One ``shard_map`` over the whole model: the batch and every MoE
    layer's experts ride the same 1-D mesh axis (DP+EP co-location, the
    GShard deployment); attention and dense blocks compute data-parallel
    on the local batch, each MoE layer does its two all_to_all hops.
    Returns ``(logits [B, S, V], aux)`` with ``aux`` the summed Switch
    load-balance loss averaged over devices.
    """
    _check_moe_model(model, mesh, axis)
    n = mesh.shape[axis]
    if tokens.shape[0] % n:
        raise ValueError(f"batch {tokens.shape[0]} must divide the "
                         f"{axis} axis size {n}")
    logits, aux = _ep_lm_fn(model, mesh, axis)(params, tokens)
    return logits, aux[0]


def _check_moe_model(model, mesh: Mesh, axis: str) -> None:
    if model.expert_axis != axis:
        raise ValueError(f"model.expert_axis={model.expert_axis!r}; "
                         f"construct the model with expert_axis={axis!r}")
    n = mesh.shape[axis]
    ne = getattr(model, "num_experts", None)
    if ne is not None and ne != n:
        raise ValueError(
            f"model has {ne} experts but the {axis!r} mesh axis is {n} — "
            "one expert per device is the supported layout")


@functools.lru_cache(maxsize=16)
def _ep_lm_fn(model, mesh: Mesh, axis: str):
    """Cached jitted forward (keyed on the model config and mesh) — a
    fresh shard_map+jit per call would retrace and recompile the whole
    model every invocation. The param specs are path-derived inside the
    traced call, so one cache entry serves any param tree structure (jit
    itself retraces on structure changes)."""

    def body(p, toks):
        logits, inter = model.apply({"params": p}, toks,
                                    mutable=["intermediates"])
        aux = lax.pmean(_sum_intermediates(inter), axis)
        return logits, aux[None]

    def call(p, toks):
        mapped = shard_map(
            body, mesh=mesh, in_specs=(moe_param_specs(p, axis), P(axis)),
            out_specs=(P(axis), P(axis)))
        return mapped(p, toks)

    return jax.jit(call)


def ep_lm_loss_fn(model, mesh: Mesh, axis: str = "expert",
                  aux_weight: float = 0.01):
    """``loss_fn(params, (tokens, targets)) -> scalar`` for the
    expert-parallel MoE LM: next-token cross-entropy + the Switch
    load-balance aux term. Differentiable straight through the
    ``shard_map`` (``jax.grad(loss_fn)`` gives correct expert-sharded
    grads for up/down and batch-averaged grads for everything else), so
    it plugs into the same optimizer wrappers as ``cp_loss_fn``."""
    _check_moe_model(model, mesh, axis)

    def loss_fn(params, batch):
        tokens, targets = batch
        specs = moe_param_specs(params, axis)

        def body(p, toks, tgts):
            logits, inter = model.apply({"params": p}, toks,
                                        mutable=["intermediates"])
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            ce = -jnp.mean(jnp.take_along_axis(
                logp, tgts[..., None], axis=-1))
            aux = _sum_intermediates(inter)
            return (ce + aux_weight * aux)[None]

        mapped = shard_map(
            body, mesh=mesh, in_specs=(specs, P(axis), P(axis)),
            out_specs=P(axis))
        # per-device local losses; equal local batches -> mean is global
        return mapped(params, tokens, targets).mean()

    return loss_fn


def ep_place_params(params, mesh: Mesh):
    """Place a SwitchFFN param dict on the expert mesh ONCE (gate
    replicated, up/down one expert per device); re-placing already-placed
    arrays is a no-op, so training loops can pass the result to
    :func:`ep_apply` every step without transfers."""
    return {
        "gate": jax.device_put(params["gate"], NamedSharding(mesh, P())),
        "up": jax.device_put(params["up"], NamedSharding(mesh, P("expert"))),
        "down": jax.device_put(params["down"],
                               NamedSharding(mesh, P("expert"))),
    }


def ep_apply(params, x, mesh: Mesh, capacity_factor: float = 2.0,
             dtype=None) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel SwitchFFN forward.

    ``params`` is a :class:`SwitchFFN` param dict (``gate``/``up``/``down``)
    with ``num_experts == mesh.shape["expert"]``; ``x`` is ``[B, S, d]``
    with B divisible by the expert-axis size (tokens ride the same devices
    as experts, the standard DP+EP co-location). Returns ``(y, aux)`` where
    ``aux`` is the per-device Switch load-balance loss ``[n]``.

    ``dtype`` is the compute dtype and must match the ``SwitchFFN.dtype``
    used as the oracle (default: ``x.dtype``, which equals the module
    default of float32 for float32 inputs).

    Capacity per expert and source device is
    ``ceil(capacity_factor * local_tokens / num_experts)``; overflowed
    tokens get zero output (Switch semantics). ``capacity_factor >=
    num_experts`` guarantees no drops.
    """
    n = mesh.shape["expert"]
    if params["up"].shape[0] != n:
        raise ValueError(
            f"params have {params['up'].shape[0]} experts but the mesh "
            f"axis is {n}")
    b, s, d = x.shape
    if b % n:
        raise ValueError(f"batch {b} must divide the expert axis size {n}")
    local_tokens = (b // n) * s
    capacity = int(np.ceil(capacity_factor * local_tokens / n))
    placed = ep_place_params(params, mesh)
    x = jax.device_put(x, NamedSharding(mesh, P("expert")))
    return _ep_fn(mesh, n, capacity, jnp.dtype(dtype or x.dtype).name)(
        placed["gate"], placed["up"], placed["down"], x)
