"""Context-parallel execution of the transformer LM.

``cp_apply`` runs a :class:`~bluefog_tpu.models.transformer.TransformerLM`
with the sequence dimension sharded across the mesh: each device holds S/n
tokens, attention is ring attention over the ppermute ring (or Ulysses), and
every other layer (embed, RMSNorm, MLP, head) is purely token-local so it
needs no communication at all. ``cp_loss_fn`` wraps it into the
``loss_fn(params, batch)`` contract of the distributed optimizers, with the
cross-entropy mean taken over the full sequence via ``psum``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .context import ring_attention_shard, ulysses_attention_shard
from ..utils.compat import shard_map


def _cp_model(model, kind: str, axis: str):
    body = {"ring": ring_attention_shard,
            "ulysses": ulysses_attention_shard}[kind]
    return model.clone(attn_fn=functools.partial(
        body, axis_name=axis, causal=True))


def cp_apply(model, variables, tokens, mesh: Optional[Mesh] = None,
             axis: str = "rank", kind: str = "ring"):
    """Sequence-parallel forward: tokens [B, S] -> logits [B, S, V].

    Equivalent (to numerics) to ``model.apply`` on one device; the sequence
    is sharded over ``axis`` and attention runs as a ring/Ulysses program.
    """
    if mesh is None:
        from ..runtime.state import _global_state
        st = _global_state()
        st.check_initialized()
        mesh = st.mesh
    n = mesh.shape[axis]
    if tokens.shape[1] % n:
        raise ValueError(
            f"sequence length {tokens.shape[1]} must divide mesh axis {n}")
    if kind == "ulysses" and model.num_heads % n:
        raise ValueError(
            f"ulysses needs num_heads % {n} == 0; got {model.num_heads}")
    return _cp_apply_fn(model, mesh, axis, kind)(variables, tokens)


@functools.lru_cache(maxsize=32)
def _cp_apply_fn(model, mesh: Mesh, axis: str, kind: str):
    """Cached jitted CP forward — stable identity so repeat calls hit the
    jit cache instead of re-tracing (flax Modules hash by value)."""
    cp = _cp_model(model, kind, axis)

    def body(variables, toks):
        me = lax.axis_index(axis)
        sq = toks.shape[1]
        positions = me * sq + jnp.arange(sq)
        return cp.apply(variables, toks, positions=positions)

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, axis)),
        out_specs=P(None, axis),
    )
    return jax.jit(mapped)


def chunked_ce_loss(model, params, tokens, targets, chunk: int = 1024,
                    remat_backbone: bool = False):
    """Next-token cross-entropy WITHOUT materializing the [S, V] logits.

    At long S the logits tensor dominates HBM traffic: S=8192 x V=32768
    f32 is 1 GB written by the forward, read by the softmax, and touched
    twice more in the backward. This computes the backbone hidden states
    once, then projects to the vocabulary one sequence chunk at a time
    under ``jax.checkpoint`` inside a sequential ``lax.map`` — the
    backward recomputes each chunk's [chunk, V] logits instead of reading
    stored ones, so peak logits memory falls from [S, V] to [chunk, V].
    Numerics are exact (a mean over disjoint chunk sums; matmul dtype is
    the model's, softmax in f32 — identical to the full-logits path).
    """
    def backbone(p, toks):
        return model.apply({"params": p}, toks, method="hidden")

    if remat_backbone:
        backbone = jax.checkpoint(backbone)
    h = backbone(params, tokens)
    # hoist the [d, V] kernel cast out of the chunk loop: inside the map
    # body it would re-materialize per iteration (and per checkpointed
    # backward recompute) — wasted HBM traffic on exactly the
    # long-context path this function exists for
    W = params["lm_head"]["kernel"].astype(h.dtype)
    b, s, d = h.shape
    t = b * s
    if t % chunk:
        raise ValueError(
            f"CE chunk {chunk} must divide the token count {t}")
    hc = h.reshape(t // chunk, chunk, d)
    tc = targets.reshape(t // chunk, chunk)

    def chunk_nll(args):
        h_c, t_c = args
        logits = (h_c @ W).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, t_c[..., None], axis=-1))

    totals = lax.map(jax.checkpoint(chunk_nll), (hc, tc))
    return totals.sum() / t


def cp_loss_fn(model, mesh: Optional[Mesh] = None, axis: str = "rank",
               kind: str = "ring"):
    """``loss_fn(params, (tokens, targets)) -> loss`` with CP attention.

    For sequence-parallel training of ONE long-sequence model replica:
    differentiate it directly (``jax.value_and_grad``) under jit. It builds
    its own shard_map over ``axis``, so do not nest it inside the
    data-parallel distributed optimizers — context parallelism and
    decentralized DP consume different mesh axes by design.
    """
    if mesh is None:
        from ..runtime.state import _global_state
        st = _global_state()
        st.check_initialized()
        mesh = st.mesh
    cpm = _cp_model(model, kind, axis)

    def body(params, toks, tgts):
        me = lax.axis_index(axis)
        sq = toks.shape[1]
        positions = me * sq + jnp.arange(sq)
        logits = cpm.apply({"params": params}, toks, positions=positions)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgts[..., None], axis=-1)
        # mean over the FULL sequence: psum local sums over the axis
        total = lax.psum(jnp.sum(nll), axis)
        count = lax.psum(jnp.asarray(nll.size, jnp.float32), axis)
        return total / count

    mapped = shard_map(
        body, mesh=mesh,
        in_specs=(P(), P(None, axis), P(None, axis)),
        out_specs=P(),
    )

    def loss(params, batch):
        tokens, targets = batch
        return mapped(params, tokens, targets)

    return loss
