"""Tensor parallelism for the transformer LM — the GSPMD/scaling-book recipe.

Net-new vs the reference (data-parallel only, SURVEY §2.6): shard the
*model* dimension over a mesh axis. Unlike the explicitly-scheduled
collectives elsewhere in this package (shard_map + ppermute, where the
schedule IS the product), tensor parallelism on TPU is best expressed as
sharding annotations: pick a 2-D ``(data, model)`` mesh, place each weight
with a `NamedSharding`, and let XLA's SPMD partitioner insert the
all-reduces — the canonical Megatron scheme falls out of the layout.

The layout (`LM_TP_RULES`) is Megatron-style:

  * ``qkv``/``up`` kernels   column-parallel  P(None, "model")
  * ``out``/``down`` kernels row-parallel     P("model", None)
    (XLA inserts one psum over "model" after each row-parallel matmul —
    two per block, exactly Megatron's communication count)
  * ``lm_head``              column-parallel  (vocab sharded)
  * ``embed``                P(None, "model") (features sharded)
  * norms                    replicated

Composes with the rest of the stack: the batch dim rides the "data" axis
(plain DP over that axis), and sequence parallelism (``cp_apply``) consumes
a different mesh axis by design.
"""

from __future__ import annotations

import functools
import re
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# path-regex -> spec for TransformerLM params (models/transformer.py).
LM_TP_RULES: Tuple[Tuple[str, P], ...] = (
    (r".*/(qkv|up)/kernel$", P(None, "model")),
    (r".*/(out|down)/kernel$", P("model", None)),
    (r".*lm_head/kernel$", P(None, "model")),
    (r".*embed/embedding$", P(None, "model")),
)


def tp_mesh(n_data: int, n_model: int,
            devices: Optional[Sequence] = None) -> Mesh:
    """A 2-D ``(data, model)`` mesh over ``n_data * n_model`` devices."""
    if devices is None:
        devices = jax.devices()
    devices = np.asarray(devices[: n_data * n_model])
    if devices.size != n_data * n_model:
        raise ValueError(
            f"need {n_data * n_model} devices, have {devices.size}")
    return Mesh(devices.reshape(n_data, n_model), ("data", "model"))


def _path_str(path) -> str:
    return "/".join(
        str(getattr(k, "key", getattr(k, "idx", k))) for k in path)


def tp_shard_params(params, mesh: Mesh,
                    rules: Sequence[Tuple[str, P]] = LM_TP_RULES):
    """Place a param pytree on the mesh per the TP layout rules.

    Leaves matching no rule are replicated. Matching leaves whose sharded
    dimension does not divide the "model" axis size fall back to replicated
    (correctness never depends on the hint).
    """
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def place(path, x):
        s = _path_str(path)
        for pat, spec in compiled:
            if pat.match(s):
                ok = x.ndim >= len(spec) and all(
                    ax is None or x.shape[d] % mesh.shape[ax] == 0
                    for d, ax in enumerate(spec))
                if ok:
                    return jax.device_put(x, NamedSharding(mesh, spec))
                break
        return jax.device_put(x, NamedSharding(mesh, P()))

    return jax.tree_util.tree_map_with_path(place, params)


@functools.lru_cache(maxsize=16)
def _tp_forward(model, mesh: Mesh):
    data_sh = NamedSharding(mesh, P("data"))

    def fwd(params, tokens):
        logits = model.apply({"params": params}, tokens)
        return jax.lax.with_sharding_constraint(logits, data_sh)

    return jax.jit(fwd)


def tp_apply(model, params, tokens, mesh: Mesh):
    """Forward pass with TP-sharded params and batch over the "data" axis.

    ``params`` should come from :func:`tp_shard_params`; jit honors the
    committed input shardings and the SPMD partitioner propagates them
    through the matmuls, inserting the Megatron all-reduces.
    """
    tokens = jax.device_put(tokens, NamedSharding(mesh, P("data")))
    return _tp_forward(model, mesh)(params, tokens)


def tp_loss_fn(model, mesh: Mesh):
    """``loss_fn(params, (tokens, targets)) -> loss`` under the TP layout.

    Differentiate directly. For layout-stable training steps, pin the
    gradient shardings to the param shardings::

        out_sh = jax.tree.map(lambda p: p.sharding, params)
        grads = jax.jit(jax.grad(loss_fn), out_shardings=out_sh)(params, batch)

    (without the pin, XLA may choose different output layouts per compile).
    """

    data_sh = NamedSharding(mesh, P("data"))

    def loss_fn(params, batch):
        tokens, targets = batch
        # keep the batch on the data axis (an unconstrained batch is free to
        # replicate across the whole mesh under the partitioner)
        tokens = jax.lax.with_sharding_constraint(tokens, data_sh)
        targets = jax.lax.with_sharding_constraint(targets, data_sh)
        logits = model.apply({"params": params}, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return -jnp.mean(ll)

    return loss_fn
