"""Pallas flash-attention block kernel.

The MXU hot path for attention: one fused kernel computes, per query tile,
the unnormalized attention partials

    o = exp(s - m) @ V,   m = rowmax(s),   l = rowsum(exp(s - m))

against one K/V block held in VMEM — scores never touch HBM, which is the
whole point of flash attention (XLA would materialize the [Sq, Sk] score
tensor for long sequences). Returning (o, m, l) instead of normalized output
makes the kernel the *inner step* of ring attention: the XLA-level ring loop
(context.py) merges the per-block statistics exactly as it does for its
einsum fallback.

Global-position offsets are scalar-prefetch operands so the SAME compiled
kernel serves every ring step (block positions are runtime values, not
trace constants). Off-TPU the kernel runs in interpret mode, keeping the
CPU-mesh test suite meaningful.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from ..utils.compat import typeof as _typeof

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

_NEG = -1e30


def _tile(s: int, candidates) -> int:
    """Largest candidate tile evenly dividing s (1 is always a candidate)."""
    return next(t for t in candidates if s % t == 0)


def _env_tile(name: str, s: int, default: int) -> int:
    """Tile override knob (perf sweeps): honored only when it divides s."""
    v = int(os.environ.get(name, "0"))
    return v if v > 0 and s % v == 0 else default


def _q_tile(sq: int) -> int:
    # 512/2048 defaults from the r5 on-chip sweep (S=8192, D=128): +5 %
    # step time over the r4 256/1024 defaults; 1024/4096 fail to fit VMEM.
    return _env_tile("BLUEFOG_FLASH_TQ", sq,
                     _tile(sq, (512, 256, 128, 64, 32, 16, 8, 4, 2, 1)))


def _k_tile(sk: int) -> int:
    # bound the [TQ, TK] f32 score tile (+ K/V tiles) well inside VMEM:
    # holding the whole K/V block per kernel invocation overflows the 16 MB
    # scoped limit past S~4k
    return _env_tile("BLUEFOG_FLASH_TK", sk,
                     _tile(sk, (2048, 1024, 512, 256, 128, 64, 32, 16, 8,
                                4, 2, 1)))



def _dot_prec(dtype):
    """Kernel matmul precision: DEFAULT for sub-f32 operands (bf16 x bf16
    runs the MXU at 4x its f32 rate and the products are exact for bf16
    operands), HIGHEST for f32 (DEFAULT decomposes f32 dots into bf16
    passes on some backends — measured 0.1-level error — which would break
    the f32 oracle contract interpret-mode tests pin)."""
    return (jax.lax.Precision.HIGHEST if jnp.dtype(dtype) == jnp.float32
            else jax.lax.Precision.DEFAULT)

def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
            causal: bool, scale: float):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    tq = q_ref.shape[1]
    tk = k_ref.shape[1]

    # the K dimension iterates innermost over the same output block, so the
    # out refs double as the online-softmax running state
    @pl.when(kj == 0)
    def _init():
        o_ref[0] = jnp.zeros_like(o_ref[0])
        m_ref[0] = jnp.full_like(m_ref[0], _NEG)
        l_ref[0] = jnp.zeros_like(l_ref[0])

    def body(masked: bool):
        # Dots keep the inputs' NATIVE dtype (bf16) with f32 accumulation:
        # the MXU runs bf16x bf16 at 4x its f32 rate, and the operands are
        # already bf16 so the products are bit-identical; only the scale
        # (applied post-dot, in f32) and the p cast below round differently
        # — the standard flash-attention-2 precision recipe.
        q = q_ref[0]                                  # [TQ, D] native dtype
        k = k_ref[0]                                  # [TK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_prec(q_ref.dtype)) * scale
        if masked:
            q_pos = offs_ref[0] + qi * tq + jax.lax.broadcasted_iota(
                jnp.int32, (tq, tk), 0)
            k_pos = offs_ref[1] + kj * tk + jax.lax.broadcasted_iota(
                jnp.int32, (tq, tk), 1)
            allowed = q_pos >= k_pos
            s = jnp.where(allowed, s, _NEG)
        m_prev = m_ref[0][:, 0]                       # [TQ]
        l_prev = l_ref[0][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)               # 0 on the first block
        p = jnp.exp(s - m_new[:, None])
        if masked:
            p = jnp.where(allowed, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        o_ref[0] = alpha[:, None] * o_ref[0] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_prec(q_ref.dtype))
        # m/l carry a size-8 lane dim purely for TPU tiling (sublane x lane
        # constraints); consumers read lane 0.
        m_ref[0] = jnp.broadcast_to(m_new[:, None], (tq, 8))
        l_ref[0] = jnp.broadcast_to(l_new[:, None], (tq, 8))

    if causal:
        # Three tile classes (VPU saver — masking builds two [TQ, TK]
        # iotas + compares + selects per tile, and only DIAGONAL tiles
        # need it): dead tiles (K entirely in the future) are skipped;
        # interior tiles (K entirely in the past) run unmasked; diagonal
        # tiles pay the mask. At S >> TQ the diagonal is a vanishing
        # fraction of live tiles.
        live = (offs_ref[1] + kj * tk
                <= offs_ref[0] + qi * tq + tq - 1)
        interior = (offs_ref[1] + kj * tk + tk - 1
                    <= offs_ref[0] + qi * tq)
        pl.when(interior)(lambda: body(False))
        pl.when(live & ~interior)(lambda: body(True))
    else:
        body(False)


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def flash_block(q, k, v, q_off, k_off, *, causal: bool = True,
                interpret: bool = False):
    """Attention partials of q against one K/V block.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; q_off/k_off: scalar global
    positions of element 0 (for causal masking across ring steps).
    Returns (o, m, l): [B, Sq, H, D] f32 unnormalized output and [B, Sq, H]
    f32 row max / row sum. Final output = o / l after merging blocks.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    tq = _q_tile(Sq)

    def bhsd(x):  # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    tk = _k_tile(Sk)
    offs = jnp.asarray([q_off, k_off], jnp.int32)
    grid = (B * H, Sq // tq, Sk // tk)
    kernel = functools.partial(_kernel, causal=causal, scale=scale)
    # Inside shard_map the inputs carry varying-mesh-axes (vma) metadata and
    # pallas_call requires out_shape to declare the same — without it the
    # kernel compiles under interpret mode but fails to lower on real TPU.
    # Union over q/k/v: any varying operand makes the outputs varying (k/v
    # can be rank-varying while q is replicated, e.g. broadcast-query).
    vmas = [getattr(_typeof(t), "vma", None) for t in (q, k, v)]
    kw = {} if all(m is None for m in vmas) else {
        "vma": frozenset().union(*(m for m in vmas if m is not None))}
    out_shape = (
        jax.ShapeDtypeStruct((B * H, Sq, D), jnp.float32, **kw),
        jax.ShapeDtypeStruct((B * H, Sq, 8), jnp.float32, **kw),
        jax.ShapeDtypeStruct((B * H, Sq, 8), jnp.float32, **kw),
    )
    if _HAVE_PLTPU:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq, D), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tk, D), lambda bh, qi, kj, offs: (bh, kj, 0)),
                pl.BlockSpec((1, tk, D), lambda bh, qi, kj, offs: (bh, kj, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tq, D), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, offs: (bh, qi, 0)),
            ],
        )
        # bh/qi grid dims are independent (parallel); kj is the sequential
        # online-softmax accumulation and must stay "arbitrary"
        params = {} if interpret else {
            "compiler_params": pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))}
        o, m, l = pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=interpret, **params,
        )(offs, bhsd(q), bhsd(k), bhsd(v))
    else:  # pragma: no cover - pltpu always importable in this image
        raise RuntimeError("pallas TPU backend unavailable")

    def sbhd(x):  # [B*H, Sq, C] -> [B, Sq, H, C]
        return x.reshape((B, H) + x.shape[1:]).transpose(0, 2, 1, 3)

    return sbhd(o), sbhd(m)[..., 0], sbhd(l)[..., 0]


def _bwd_tiles(offs_ref, qi, kj, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref,
               d_ref, masked: bool, scale: float):
    """Shared backward-tile recompute -> (q, k, g*inv_l, P_unnorm, dS).

    The probability tile is rebuilt in VMEM from the saved GLOBAL (m, l)
    row statistics with the same offset-based causal mask as the forward
    kernel; the row normalizer rides the RETURNED g (see the inline note)
    so the [TQ, TK] tile is touched once less, and dS = P * (dP - D) is
    the softmax-jacobian product both backward passes consume. One
    definition keeps the dq and dk/dv kernels (and their masking) from
    drifting apart. q is returned UNSCALED — the dk pass applies the
    score scale itself."""
    tq = q_ref.shape[1]
    tk = k_ref.shape[1]
    # native-dtype (bf16) dot operands, f32 accumulation — see _kernel; the
    # scale moves AFTER the qk dot (q stays unscaled, so the dk pass
    # applies it explicitly)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    g = g_ref[0]
    m = m_ref[0][:, 0]
    inv_l = 1.0 / l_ref[0][:, 0]
    d = d_ref[0][:, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32,
                            precision=_dot_prec(q_ref.dtype)) * scale
    if masked:
        q_pos = offs_ref[0] + qi * tq + jax.lax.broadcasted_iota(
            jnp.int32, (tq, tk), 0)
        k_pos = offs_ref[1] + kj * tk + jax.lax.broadcasted_iota(
            jnp.int32, (tq, tk), 1)
        allowed = q_pos >= k_pos
        s = jnp.where(allowed, s, _NEG)
    # VPU saver: the softmax row normalizer inv_l is folded into the
    # per-ROW quantities instead of the [TQ, TK] tile — p stays
    # UNNORMALIZED (exp(s - m), in [0, 1] since m is the global row max)
    # and the returned g is pre-scaled g * inv_l, so
    #   dP  = g @ V^T           becomes dp' = (g inv_l) @ V^T = dP inv_l
    #   dS  = P (dP - d)        becomes ds  = p_un (dp' - d inv_l) = dS
    #   dV += P^T g             becomes      p_un^T (g inv_l)      = dV
    # — one fewer full-tile elementwise pass per (q, k) tile pair.
    p = jnp.exp(s - m[:, None])
    if masked:
        p = jnp.where(allowed, p, 0.0)
    g_scaled = (g.astype(jnp.float32)
                * inv_l[:, None]).astype(g.dtype)   # [TQ, D]: cheap
    dp = jax.lax.dot_general(g_scaled, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=_dot_prec(q_ref.dtype))
    ds = p * (dp - (d * inv_l)[:, None])
    return q, k, g_scaled, p, ds


def _bwd_live(offs_ref, qi, kj, tq, tk):
    """Causal block-skip shared by both backward passes (same predicate as
    the forward): the tile pair is dead when the whole K tile lies in the
    future of the last query row."""
    return offs_ref[1] + kj * tk <= offs_ref[0] + qi * tq + tq - 1


def _bwd_interior(offs_ref, qi, kj, tq, tk):
    """K tile entirely in the past of the whole q tile: masking is a no-op
    (see the forward's three tile classes)."""
    return offs_ref[1] + kj * tk + tk - 1 <= offs_ref[0] + qi * tq


def _dq_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref,
               dq_ref, *, causal: bool, scale: float):
    """dQ pass (flash-attention-2 backward): for each query tile, iterate
    K/V tiles innermost and accumulate dq += dS @ K * scale — scores and
    probabilities never reach HBM, same as the forward."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        dq_ref[0] = jnp.zeros_like(dq_ref[0])

    def body(masked: bool):
        _, k, _, _, ds = _bwd_tiles(offs_ref, qi, kj, q_ref, k_ref, v_ref,
                                    g_ref, m_ref, l_ref, d_ref, masked,
                                    scale)
        dq_ref[0] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_prec(q_ref.dtype)) * scale

    if causal:
        tq, tk = q_ref.shape[1], k_ref.shape[1]
        live = _bwd_live(offs_ref, qi, kj, tq, tk)
        interior = _bwd_interior(offs_ref, qi, kj, tq, tk)
        pl.when(interior)(lambda: body(False))
        pl.when(live & ~interior)(lambda: body(True))
    else:
        body(False)


def _dkv_kernel(offs_ref, q_ref, k_ref, v_ref, g_ref, m_ref, l_ref, d_ref,
                dk_ref, dv_ref, *, causal: bool, scale: float):
    """dK/dV pass: for each K/V tile, iterate query tiles innermost and
    accumulate dv += P^T @ dO and dk += dS^T @ (Q * scale)."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    def body(masked: bool):
        q, _, g, p, ds = _bwd_tiles(offs_ref, qi, kj, q_ref, k_ref, v_ref,
                                    g_ref, m_ref, l_ref, d_ref, masked,
                                    scale)
        dv_ref[0] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_prec(q_ref.dtype))
        # q is unscaled in the shared tile recompute: apply the score scale
        # here (dK = dS^T @ (scale * Q))
        dk_ref[0] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=_dot_prec(q_ref.dtype)) * scale

    if causal:
        tq, tk = q_ref.shape[1], k_ref.shape[1]
        live = _bwd_live(offs_ref, qi, kj, tq, tk)
        interior = _bwd_interior(offs_ref, qi, kj, tq, tk)
        pl.when(interior)(lambda: body(False))
        pl.when(live & ~interior)(lambda: body(True))
    else:
        body(False)


def _lane8(x):  # [B, S, H] -> [B*H, S, 8] (TPU sublane x lane tiling)
    B, S, H = x.shape
    t = x.transpose(0, 2, 1).reshape(B * H, S)
    return jnp.broadcast_to(t[:, :, None], (B * H, S, 8))


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def flash_block_bwd(q, k, v, g, d_term, m, l, q_off, k_off, *,
                    causal: bool = True, interpret: bool = False):
    """Gradients of q's attention against one K/V block (pallas kernels).

    Inputs: q [B, Sq, H, D]; k, v [B, Sk, H, D]; g = dOut [B, Sq, H, D];
    ``d_term = sum(dOut * Out, -1)`` and the saved GLOBAL softmax row stats
    ``m`` (row max) and ``l`` (row sum), all [B, Sq, H] f32 — the same
    quantities the XLA ring backward reconstructs per block
    (context._ring_backward). Returns (dq_partial, dk, dv) in f32: the
    caller sums dq partials over blocks and ships dk/dv home with the ring.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    tq = _q_tile(Sq)
    tk = _k_tile(Sk)

    def bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    offs = jnp.asarray([q_off, k_off], jnp.int32)
    vmas = [getattr(_typeof(t), "vma", None) for t in (q, k, v, g)]
    kw = {} if all(mm is None for mm in vmas) else {
        "vma": frozenset().union(*(mm for mm in vmas if mm is not None))}
    operands = (offs, bhsd(q), bhsd(k), bhsd(v), bhsd(g),
                _lane8(m), _lane8(l), _lane8(d_term))
    if not _HAVE_PLTPU:  # pragma: no cover - pltpu always importable here
        raise RuntimeError("pallas TPU backend unavailable")

    params = {} if interpret else {
        "compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))}

    # pass 1: dq (K innermost, accumulates into the q tile's output)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, Sq // tq, Sk // tk),
        in_specs=[
            pl.BlockSpec((1, tq, D), lambda bh, qi, kj, o: (bh, qi, 0)),
            pl.BlockSpec((1, tk, D), lambda bh, qi, kj, o: (bh, kj, 0)),
            pl.BlockSpec((1, tk, D), lambda bh, qi, kj, o: (bh, kj, 0)),
            pl.BlockSpec((1, tq, D), lambda bh, qi, kj, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, o: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tq, D), lambda bh, qi, kj, o: (bh, qi, 0)),
        ],
    )
    (dq,) = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=scale),
        grid_spec=dq_spec,
        out_shape=(jax.ShapeDtypeStruct((B * H, Sq, D), jnp.float32, **kw),),
        interpret=interpret, **params,
    )(*operands)

    # pass 2: dk/dv (Q innermost, accumulates into the k tile's outputs)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(B * H, Sk // tk, Sq // tq),
        in_specs=[
            pl.BlockSpec((1, tq, D), lambda bh, kj, qi, o: (bh, qi, 0)),
            pl.BlockSpec((1, tk, D), lambda bh, kj, qi, o: (bh, kj, 0)),
            pl.BlockSpec((1, tk, D), lambda bh, kj, qi, o: (bh, kj, 0)),
            pl.BlockSpec((1, tq, D), lambda bh, kj, qi, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, kj, qi, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, kj, qi, o: (bh, qi, 0)),
            pl.BlockSpec((1, tq, 8), lambda bh, kj, qi, o: (bh, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, tk, D), lambda bh, kj, qi, o: (bh, kj, 0)),
            pl.BlockSpec((1, tk, D), lambda bh, kj, qi, o: (bh, kj, 0)),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=scale),
        grid_spec=dkv_spec,
        out_shape=(
            jax.ShapeDtypeStruct((B * H, Sk, D), jnp.float32, **kw),
            jax.ShapeDtypeStruct((B * H, Sk, D), jnp.float32, **kw),
        ),
        interpret=interpret, **params,
    )(*operands)

    def sbhd(x, s):
        return x.reshape((B, H, s, D)).transpose(0, 2, 1, 3)

    return sbhd(dq, Sq), sbhd(dk, Sk), sbhd(dv, Sk)


def _blockwise_attention(q, k, v, causal: bool, tk: int):
    """Pure-XLA blockwise attention: lax.scan over K blocks with online
    softmax, each step under jax.checkpoint. Numerically the same function
    as the pallas kernel, O(S*tk) live memory — kept as the independent
    test oracle for the kernel's values (tests/test_flash.py); the
    production backward is the pallas kernel pair (flash_block_bwd)."""
    B, S, H, D = q.shape
    Sk = k.shape[1]
    nk = Sk // tk
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale
    # keep K/V in their input dtype; each block upcasts inside the
    # checkpointed step, so only one block's f32 copy is ever live
    kb = k.reshape(B, nk, tk, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nk, tk, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    @jax.checkpoint
    def step(carry, inp):
        o, m, l = carry
        kj, kblk, vblk = inp
        kblk = kblk.astype(jnp.float32)
        vblk = vblk.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, kblk,
                       preferred_element_type=jnp.float32)
        if causal:
            k_pos = kj * tk + jnp.arange(tk)
            allowed = (q_pos[None, :, None, None] >= k_pos[None, None, None, :])
            s = jnp.where(allowed, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(allowed, p, 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = alpha[..., None] * o + jnp.einsum(
            "bqhk,bkhd->bqhd", p, vblk, preferred_element_type=jnp.float32)
        return (o_new, m_new, l_new), None

    init = (jnp.zeros((B, S, H, D), jnp.float32),
            jnp.full((B, S, H), _NEG, jnp.float32),
            jnp.zeros((B, S, H), jnp.float32))
    (o, m, l), _ = jax.lax.scan(step, init, (jnp.arange(nk), kb, vb))
    return (o / l[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    o, m, l = flash_block(q, k, v, 0, 0, causal=causal, interpret=interpret)
    return (o / l[..., None]).astype(q.dtype)


def _flash_fwd(q, k, v, causal, interpret):
    o, m, l = flash_block(q, k, v, 0, 0, causal=causal, interpret=interpret)
    out = (o / l[..., None]).astype(q.dtype)
    return out, (q, k, v, out, m, l)


def _flash_bwd(causal, interpret, res, g):
    # flash-attention-2 style kernel backward: dq pass + dk/dv pass, both
    # recomputing probability tiles in VMEM from the saved (m, l) stats —
    # no autodiff-through-recompute, no [S, S] tensor in either direction
    q, k, v, out, m, l = res
    gf = g.astype(jnp.float32)
    d_term = jnp.sum(gf * out.astype(jnp.float32), axis=-1)
    dq, dk, dv = flash_block_bwd(q, k, v, gf, d_term, m, l, 0, 0,
                                 causal=causal, interpret=interpret)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    interpret: bool = False):
    """Single-device flash attention over [B, S, H, D] (normalized output).

    Differentiable: the forward runs the pallas VMEM kernel and the
    backward runs the pallas flash-attention-2 kernel pair
    (:func:`flash_block_bwd` — a dq pass and a dk/dv pass that rebuild
    probability tiles in VMEM from the saved (m, l) stats), so neither
    direction materializes the [S, S] score tensor — long-context training
    works on a single chip at sequence lengths where dense attention is
    OOM-bound.
    """
    return _flash(q, k, v, causal, interpret)
