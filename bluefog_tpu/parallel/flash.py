"""Pallas flash-attention block kernel.

The MXU hot path for attention: one fused kernel computes, per query tile,
the unnormalized attention partials

    o = exp(s - m) @ V,   m = rowmax(s),   l = rowsum(exp(s - m))

against one K/V block held in VMEM — scores never touch HBM, which is the
whole point of flash attention (XLA would materialize the [Sq, Sk] score
tensor for long sequences). Returning (o, m, l) instead of normalized output
makes the kernel the *inner step* of ring attention: the XLA-level ring loop
(context.py) merges the per-block statistics exactly as it does for its
einsum fallback.

Global-position offsets are scalar-prefetch operands so the SAME compiled
kernel serves every ring step (block positions are runtime values, not
trace constants). Off-TPU the kernel runs in interpret mode, keeping the
CPU-mesh test suite meaningful.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is absent on some CPU-only builds
    from jax.experimental.pallas import tpu as pltpu
    _HAVE_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAVE_PLTPU = False

_NEG = -1e30


def _tile(s: int, candidates) -> int:
    """Largest candidate tile evenly dividing s (1 is always a candidate)."""
    return next(t for t in candidates if s % t == 0)


def _q_tile(sq: int) -> int:
    return _tile(sq, (256, 128, 64, 32, 16, 8, 4, 2, 1))


def _k_tile(sk: int) -> int:
    # bound the [TQ, TK] f32 score tile (+ K/V tiles) well inside VMEM:
    # holding the whole K/V block per kernel invocation overflows the 16 MB
    # scoped limit past S~4k
    return _tile(sk, (1024, 512, 256, 128, 64, 32, 16, 8, 4, 2, 1))


def _kernel(offs_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, *,
            causal: bool, scale: float):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    tq = q_ref.shape[1]
    tk = k_ref.shape[1]

    # the K dimension iterates innermost over the same output block, so the
    # out refs double as the online-softmax running state
    @pl.when(kj == 0)
    def _init():
        o_ref[0] = jnp.zeros_like(o_ref[0])
        m_ref[0] = jnp.full_like(m_ref[0], _NEG)
        l_ref[0] = jnp.zeros_like(l_ref[0])

    def body():
        q = q_ref[0].astype(jnp.float32) * scale      # [TQ, D]
        k = k_ref[0].astype(jnp.float32)              # [TK, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            q_pos = offs_ref[0] + qi * tq + jax.lax.broadcasted_iota(
                jnp.int32, (tq, tk), 0)
            k_pos = offs_ref[1] + kj * tk + jax.lax.broadcasted_iota(
                jnp.int32, (tq, tk), 1)
            allowed = q_pos >= k_pos
            s = jnp.where(allowed, s, _NEG)
        m_prev = m_ref[0][:, 0]                       # [TQ]
        l_prev = l_ref[0][:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_new)               # 0 on the first block
        p = jnp.exp(s - m_new[:, None])
        if causal:
            p = jnp.where(allowed, p, 0.0)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1)
        o_ref[0] = alpha[:, None] * o_ref[0] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # m/l carry a size-8 lane dim purely for TPU tiling (sublane x lane
        # constraints); consumers read lane 0.
        m_ref[0] = jnp.broadcast_to(m_new[:, None], (tq, 8))
        l_ref[0] = jnp.broadcast_to(l_new[:, None], (tq, 8))

    if causal:
        # skip k-blocks that lie entirely in the future of this q tile
        # (~half the grid for single-device causal attention)
        live = (offs_ref[1] + kj * tk
                <= offs_ref[0] + qi * tq + tq - 1)
        pl.when(live)(body)
    else:
        body()


@functools.partial(jax.jit, static_argnames=("causal", "interpret"))
def flash_block(q, k, v, q_off, k_off, *, causal: bool = True,
                interpret: bool = False):
    """Attention partials of q against one K/V block.

    q: [B, Sq, H, D]; k, v: [B, Sk, H, D]; q_off/k_off: scalar global
    positions of element 0 (for causal masking across ring steps).
    Returns (o, m, l): [B, Sq, H, D] f32 unnormalized output and [B, Sq, H]
    f32 row max / row sum. Final output = o / l after merging blocks.
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    tq = _q_tile(Sq)

    def bhsd(x):  # [B, S, H, D] -> [B*H, S, D]
        return x.transpose(0, 2, 1, 3).reshape(B * H, x.shape[1], D)

    tk = _k_tile(Sk)
    offs = jnp.asarray([q_off, k_off], jnp.int32)
    grid = (B * H, Sq // tq, Sk // tk)
    kernel = functools.partial(_kernel, causal=causal, scale=scale)
    # Inside shard_map the inputs carry varying-mesh-axes (vma) metadata and
    # pallas_call requires out_shape to declare the same — without it the
    # kernel compiles under interpret mode but fails to lower on real TPU.
    # Union over q/k/v: any varying operand makes the outputs varying (k/v
    # can be rank-varying while q is replicated, e.g. broadcast-query).
    vmas = [getattr(jax.typeof(t), "vma", None) for t in (q, k, v)]
    kw = {} if all(m is None for m in vmas) else {
        "vma": frozenset().union(*(m for m in vmas if m is not None))}
    out_shape = (
        jax.ShapeDtypeStruct((B * H, Sq, D), jnp.float32, **kw),
        jax.ShapeDtypeStruct((B * H, Sq, 8), jnp.float32, **kw),
        jax.ShapeDtypeStruct((B * H, Sq, 8), jnp.float32, **kw),
    )
    if _HAVE_PLTPU:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, tq, D), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tk, D), lambda bh, qi, kj, offs: (bh, kj, 0)),
                pl.BlockSpec((1, tk, D), lambda bh, qi, kj, offs: (bh, kj, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tq, D), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, offs: (bh, qi, 0)),
                pl.BlockSpec((1, tq, 8), lambda bh, qi, kj, offs: (bh, qi, 0)),
            ],
        )
        # bh/qi grid dims are independent (parallel); kj is the sequential
        # online-softmax accumulation and must stay "arbitrary"
        params = {} if interpret else {
            "compiler_params": pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary"))}
        o, m, l = pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape,
            interpret=interpret, **params,
        )(offs, bhsd(q), bhsd(k), bhsd(v))
    else:  # pragma: no cover - pltpu always importable in this image
        raise RuntimeError("pallas TPU backend unavailable")

    def sbhd(x):  # [B*H, Sq, C] -> [B, Sq, H, C]
        return x.reshape((B, H) + x.shape[1:]).transpose(0, 2, 1, 3)

    return sbhd(o), sbhd(m)[..., 0], sbhd(l)[..., 0]


def _blockwise_attention(q, k, v, causal: bool, tk: int):
    """Pure-XLA blockwise attention: lax.scan over K blocks with online
    softmax, each step under jax.checkpoint. Numerically the same function
    as the pallas kernel, O(S*tk) live memory — the autodiff twin used for
    flash_attention's backward (its VJP recomputes per-block instead of
    materializing the [S, S] score tensor)."""
    B, S, H, D = q.shape
    Sk = k.shape[1]
    nk = Sk // tk
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32) * scale
    # keep K/V in their input dtype; each block upcasts inside the
    # checkpointed step, so only one block's f32 copy is ever live
    kb = k.reshape(B, nk, tk, H, D).transpose(1, 0, 2, 3, 4)
    vb = v.reshape(B, nk, tk, H, D).transpose(1, 0, 2, 3, 4)
    q_pos = jnp.arange(S)

    @jax.checkpoint
    def step(carry, inp):
        o, m, l = carry
        kj, kblk, vblk = inp
        kblk = kblk.astype(jnp.float32)
        vblk = vblk.astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bqhk", qf, kblk,
                       preferred_element_type=jnp.float32)
        if causal:
            k_pos = kj * tk + jnp.arange(tk)
            allowed = (q_pos[None, :, None, None] >= k_pos[None, None, None, :])
            s = jnp.where(allowed, s, _NEG)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(allowed, p, 0.0)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        o_new = alpha[..., None] * o + jnp.einsum(
            "bqhk,bkhd->bqhd", p, vblk, preferred_element_type=jnp.float32)
        return (o_new, m_new, l_new), None

    init = (jnp.zeros((B, S, H, D), jnp.float32),
            jnp.full((B, S, H), _NEG, jnp.float32),
            jnp.zeros((B, S, H), jnp.float32))
    (o, m, l), _ = jax.lax.scan(step, init, (jnp.arange(nk), kb, vb))
    return (o / l[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, interpret):
    o, m, l = flash_block(q, k, v, 0, 0, causal=causal, interpret=interpret)
    return (o / l[..., None]).astype(q.dtype)


def _flash_fwd(q, k, v, causal, interpret):
    return _flash(q, k, v, causal, interpret), (q, k, v)


def _flash_bwd(causal, interpret, res, g):
    q, k, v = res
    # small backward tile (same ladder as _q_tile): the recomputed
    # [B, S, H, TK] probability tile is the live-memory high-water mark
    tk = _q_tile(k.shape[1])
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _blockwise_attention(q_, k_, v_, causal, tk),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    interpret: bool = False):
    """Single-device flash attention over [B, S, H, D] (normalized output).

    Differentiable: the forward runs the pallas VMEM kernel; the backward is
    the VJP of a checkpointed blockwise-scan twin (`_blockwise_attention`),
    so neither direction materializes the [S, S] score tensor — long-context
    training works on a single chip at sequence lengths where dense
    attention is OOM-bound.
    """
    return _flash(q, k, v, causal, interpret)
