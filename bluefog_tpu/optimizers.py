"""Distributed optimizer wrappers — the training-loop layer.

TPU-native rebuild of BlueFog's optimizer family (reference:
torch/optimizers.py, 1073 LoC). The reference wraps a torch optimizer and
hooks module forward/backward passes to launch nonblocking communication,
synchronizing in ``step()``. In JAX the idiomatic equivalent is *fusion*: each
wrapper here compiles ONE SPMD program per step that performs

    per-rank grad  ->  optax update  ->  communication (pmean / weighted
                                          neighbor combine / nothing)

so XLA overlaps the backward matmuls with the ICI collective traffic — the
same overlap BlueFog gets from its background thread, but scheduled by the
compiler instead of a negotiation protocol.

Seven strategies mirror the reference surface (optimizers.py:776-1073), plus
one net-new TPU-native strategy with no reference analog:

  * ``DistributedGradientAllreduceOptimizer``  — allreduce gradients
    (Horovod style; reference optimizers.py:1026).
  * ``DistributedAllreduceOptimizer``          — allreduce parameters after
    the local update (reference optimizers.py:895).
  * ``DistributedNeighborAllreduceOptimizer``  — weighted neighbor averaging
    of parameters over the virtual topology; per-iteration dynamic knobs
    ``self_weight / neighbor_weights / send_neighbors / enable_topo_check``
    (reference optimizers.py:943 & 298-304).
  * ``DistributedHierarchicalNeighborAllreduceOptimizer`` — intra-machine
    allreduce + machine-graph neighbor averaging (reference
    optimizers.py:971); knobs ``neighbor_machine_weights /
    send_neighbor_machines``.
  * ``DistributedWinPutOptimizer``             — push-style asynchronous
    gossip over windows (reference optimizers.py:867).
  * ``DistributedPullGetOptimizer``            — pull-style (reference
    optimizers.py:821).
  * ``DistributedPushSumOptimizer``            — push-sum with associated
    weight scalar (reference optimizers.py:776 & 624-773).
  * ``DistributedShardedAllreduceOptimizer``   — ZeRO-1 sharded data
    parallelism: reduce_scatter grads, 1/n optimizer state per rank,
    all_gather params (net-new; SURVEY §2.6 marks FSDP/ZeRO absent).

All support ``num_steps_per_communication`` (local-SGD delayed communication,
reference optimizers.py:152-155).

Canonical usage::

    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.01, momentum=0.9), loss_fn=loss_fn)
    state = opt.init(params)                 # replicates across the mesh
    state, metrics = opt.step(state, batch)  # batch is rank-stacked [n, b, ...]

``loss_fn(params, batch) -> loss`` (or ``(loss, aux)`` with ``has_aux=True``;
or ``loss_fn(params, model_state, batch) -> (loss, (model_state, aux))`` with
``with_model_state=True`` for batch-norm models).
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.flatten_util
import jax.numpy as jnp
import optax
from flax import struct
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import topology as topology_util
from .ops import fusion as _fusion
from .ops import windows as _windows
from .ops.neighbors import _dynamic_weight_matrix, _static_weight_matrix
from .ops.plan import CombinePlan, spmd_combine
from .runtime import control_plane as _cp
from .runtime import flight as _flight
from .runtime import heartbeat as _hb
from .runtime import metrics as _metrics
from .runtime import timeseries as _timeseries
from .runtime import tuner as _tuner
from .runtime.config import knob_env
from .runtime.logging import logger
from .runtime.native import PeerLostError
from .runtime.state import _global_state
from .runtime.timeline import timeline_context
from .utils.compat import shard_map


# Consensus-gauge cadence (seconds): matches the time-series sampler's
# ~1 Hz gate — the gauge is only consumed once per sample tick.
_CONSENSUS_MIN_GAP = 0.9


def _perf_gate_delay() -> None:
    """Testing-only seeded slowdown (`BLUEFOG_PERF_GATE_DELAY_MS`): every
    optimizer step eats an artificial delay so `make perf-gate`'s red path
    is deterministically exercisable (scripts/perf_gate.py). Off (0) on
    every real job — the knob's doc says so and the gate's self-check is
    the only sanctioned user."""
    ms = knob_env("BLUEFOG_PERF_GATE_DELAY_MS")
    if ms:
        time.sleep(float(ms) / 1e3)


@struct.dataclass
class TrainState:
    """Rank-stacked training state: leaf ``x[r]`` lives on device r."""

    params: Any
    opt_state: Any
    model_state: Any = None


def replicate(tree, mesh=None, axis: str = "rank"):
    """Broadcast a single-rank pytree to a rank-stacked, mesh-sharded one.

    The analog of ``bf.broadcast_parameters(..., root_rank=0)`` at t=0
    (reference: torch/utility.py:22-56): every rank starts from identical
    values.
    """
    st = _global_state()
    st.check_initialized()
    mesh = mesh or st.mesh
    n = mesh.devices.size
    sh = NamedSharding(mesh, P(mesh.axis_names))

    def rep(x):
        x = jnp.asarray(x)
        return jax.device_put(jnp.broadcast_to(x[None], (n,) + x.shape), sh)

    return jax.tree_util.tree_map(rep, tree)


def unreplicate(tree, rank: int = 0):
    """Slice one rank's copy out of a rank-stacked pytree."""
    return jax.tree_util.tree_map(lambda x: x[rank], tree)


def _canon_loss(loss_fn, has_aux: bool, with_model_state: bool):
    """Normalize to (params, model_state, batch) -> (loss, (model_state, aux))."""
    if with_model_state:
        return loss_fn
    if has_aux:
        def f(p, ms, b):
            loss, aux = loss_fn(p, b)
            return loss, (ms, aux)
        return f

    def g(p, ms, b):
        return loss_fn(p, b), (ms, {})
    return g


_unstack = lambda t: jax.tree_util.tree_map(lambda x: x[0], t)
_restack = lambda t: jax.tree_util.tree_map(lambda x: jnp.asarray(x)[None], t)


def build_fused_step(mesh, kind: str, loss, opt, plan: Optional[CombinePlan]):
    """Construct the fused per-step SPMD program for one comm strategy.

    Module-level so it works over ANY mesh — the live rank mesh inside
    :class:`_FusedOptimizer`, or a ``jax.sharding.AbstractMesh`` for AOT
    lowering (the compile-time scaling evidence in ``bluefog_tpu.scaling``
    asserts collective counts on exactly the program built here).

    ``kind``: gradient_allreduce | allreduce | neighbor_allreduce |
    hierarchical | none. Hierarchical expects a ("machine", "local") mesh.
    Returns a jitted ``fn(w, params, opt_state, model_state, batch)`` over
    rank-stacked trees with donated state.
    """
    shifts = plan.shifts if plan is not None else ()
    use_gather = plan.use_gather if plan is not None else False
    pn = plan.n if plan is not None else 0
    axis = "machine" if kind == "hierarchical" else "rank"

    def per_rank(w, params, opt_state, model_state, batch):
        p = _unstack(params)
        os_ = _unstack(opt_state)
        ms = _unstack(model_state)
        b = _unstack(batch)

        (l, (new_ms, aux)), grads = jax.value_and_grad(
            lambda p_: loss(p_, ms, b), has_aux=True)(p)
        if kind == "gradient_allreduce":
            grads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, mesh.axis_names), grads)
        updates, new_os = opt.update(grads, os_, p)
        p = optax.apply_updates(p, updates)
        if kind == "allreduce":
            p = jax.tree_util.tree_map(
                lambda x: lax.pmean(x, mesh.axis_names), p)
        elif kind == "neighbor_allreduce":
            p = spmd_combine(w, p, axis=axis, n=pn, shifts=shifts,
                             use_gather=use_gather, stacked=False)
        elif kind == "hierarchical":
            p = jax.tree_util.tree_map(lambda x: lax.pmean(x, "local"), p)
            p = spmd_combine(w, p, axis="machine", n=pn, shifts=shifts,
                             use_gather=use_gather, stacked=False)
        metrics = {"loss": l, "aux": aux}
        return (_restack(p), _restack(new_os), _restack(new_ms),
                _restack(metrics))

    spec = P(mesh.axis_names)
    mapped = shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )
    # Donate params/opt_state/model_state: the caller always replaces
    # them with the step outputs, and donation lets XLA update in place
    # instead of double-buffering the model in HBM.
    return jax.jit(mapped, donate_argnums=(1, 2, 3))


def _flat_shard(flat, n: int, me):
    """(my [ceil(size/n)] shard of a padded flat buffer, shard length).

    The single source of truth for ZeRO-1 shard sizing — used by both the
    step program and the optimizer-state init so they cannot diverge."""
    size = -(-flat.size // n)
    padded = jnp.pad(flat, (0, size * n - flat.size))
    return lax.dynamic_slice(padded, (me * size,), (size,)), size


def build_sharded_step(mesh, loss, opt):
    """ZeRO-1 step over an arbitrary mesh (see :func:`build_fused_step`):
    psum_scatter grads, update the local 1/n flat shard, all_gather params."""
    n = mesh.size  # Mesh and AbstractMesh both implement it
    axis = mesh.axis_names

    def per_rank(w, params, opt_state, model_state, batch):
        p = _unstack(params)
        os_ = _unstack(opt_state)
        ms = _unstack(model_state)
        b = _unstack(batch)

        (l, (new_ms, aux)), grads = jax.value_and_grad(
            lambda p_: loss(p_, ms, b), has_aux=True)(p)
        flat_g, _ = jax.flatten_util.ravel_pytree(grads)
        flat_p, unravel = jax.flatten_util.ravel_pytree(p)
        total = flat_p.size
        size = -(-total // n)
        me = lax.axis_index(axis)
        g_shard = lax.psum_scatter(
            jnp.pad(flat_g, (0, size * n - total)), axis,
            scatter_dimension=0, tiled=True) / n
        p_shard, _ = _flat_shard(flat_p, n, me)
        updates, new_os = opt.update(g_shard, os_, p_shard)
        new_flat = lax.all_gather(
            optax.apply_updates(p_shard, updates), axis, tiled=True)
        p_new = unravel(new_flat[:total])
        metrics = {"loss": l, "aux": aux}
        return (_restack(p_new), _restack(new_os), _restack(new_ms),
                _restack(metrics))

    spec = P(mesh.axis_names)
    mapped = shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(P(), spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec),
    )
    return jax.jit(mapped, donate_argnums=(1, 2, 3))


class _FusedOptimizer:
    """Shared machinery: fused per-step SPMD program with cached jits."""

    _comm_kind = "none"  # overridden: gradient_allreduce | allreduce |
    #                       neighbor_allreduce | hierarchical | none

    def __init__(
        self,
        optimizer: optax.GradientTransformation,
        loss_fn: Callable,
        *,
        has_aux: bool = False,
        with_model_state: bool = False,
        num_steps_per_communication: int = 1,
        name: Optional[str] = None,
    ) -> None:
        st = _global_state()
        st.check_initialized()
        self.base = optimizer
        self._loss = _canon_loss(loss_fn, has_aux, with_model_state)
        self.num_steps_per_communication = int(num_steps_per_communication)
        self._counter = 0
        self._step_cache: Dict[Any, Any] = {}
        self.name = name or type(self).__name__

    # -- state ------------------------------------------------------------

    def init(self, params, model_state=None) -> TrainState:
        """Replicate single-rank params (+ model state) and init optax state."""
        opt_state = self.base.init(params)
        return TrainState(
            params=replicate(params),
            opt_state=replicate(opt_state),
            model_state=None if model_state is None else replicate(model_state),
        )

    # -- plan hooks (overridden per strategy) -----------------------------

    def _plan(self) -> Optional[CombinePlan]:
        return None

    def _mesh_axes(self) -> Tuple[Any, Any]:
        st = _global_state()
        return st.mesh, "rank"

    # -- the fused step ---------------------------------------------------

    def _build(self, key, plan: Optional[CombinePlan], do_comm: bool):
        mesh, _ = self._mesh_axes()
        kind = self._comm_kind if do_comm else "none"
        return build_fused_step(mesh, kind, self._loss, self.base, plan)

    def _weights_and_key(self):
        plan = self._plan()
        if plan is None:
            # numpy host constants: jit places them on the mesh directly
            # instead of hopping through the default device every step.
            return None, np.zeros((1, 1), np.float32), ("none",)
        return plan, plan.weight_array(), (plan.shifts, plan.use_gather)

    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        """One training iteration over the whole mesh."""
        k = self.num_steps_per_communication
        self._counter += 1
        do_comm = (self._counter % k) == 0
        plan, w, wkey = self._weights_and_key() if do_comm else (None, np.zeros((1, 1), np.float32), ("skip",))
        key = (do_comm,) + wkey
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._build(key, plan, do_comm)
            self._step_cache[key] = fn
        _perf_gate_delay()
        try:
            with timeline_context(self.name, "STEP"), \
                    _metrics.timed("opt.step_sec"), \
                    _flight.recorder().span("opt.step", b=self._counter):
                params, opt_state, model_state, metrics = fn(
                    w, state.params, state.opt_state, state.model_state,
                    batch)
        except Exception as exc:
            # black-box dump before the stack unwinds: the ring's tail IS
            # the postmortem evidence (rate-limited; never raises)
            _flight.fatal("opt.step", exc)
            raise
        _metrics.gauge("opt.step").set(self._counter)
        return TrainState(params, opt_state, model_state), metrics


class DistributedGradientAllreduceOptimizer(_FusedOptimizer):
    """Global gradient averaging before the update (Horovod-style).

    Reference: optimizers.py:1026 / the backward accumulator hooks at
    optimizers.py:161-186. ``lax.pmean`` over the mesh is the whole transport.
    """

    _comm_kind = "gradient_allreduce"


class DistributedAllreduceOptimizer(_FusedOptimizer):
    """Global parameter averaging after the local update.

    Reference: optimizers.py:895 (_DistributedReduceOptimizer, forward hook).
    """

    _comm_kind = "allreduce"


class DistributedNeighborAllreduceOptimizer(_FusedOptimizer):
    """Parameter averaging with in-neighbors over the virtual topology (CTA).

    The flagship decentralized strategy (reference: optimizers.py:943).
    Mutate ``self_weight`` / ``neighbor_weights`` / ``send_neighbors`` between
    steps for dynamic topologies (reference: optimizers.py:298-304); each
    distinct edge-shift set compiles once and is cached — Expo-2's one-peer
    schedule has ceil(log2 n) distinct sets.
    """

    _comm_kind = "neighbor_allreduce"

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self.self_weight: Optional[float] = None
        self.neighbor_weights: Optional[Dict] = None
        self.send_neighbors = None
        self.enable_topo_check: bool = True

    def _plan(self) -> CombinePlan:
        st = _global_state()
        if self.send_neighbors is None:
            W = _static_weight_matrix(self.self_weight, self.neighbor_weights)
        else:
            W = _dynamic_weight_matrix(
                st.size, self.send_neighbors, self.self_weight,
                self.neighbor_weights, self.enable_topo_check)
        return CombinePlan(W)


class DistributedHierarchicalNeighborAllreduceOptimizer(_FusedOptimizer):
    """Intra-machine allreduce + machine-level neighbor averaging.

    Reference: optimizers.py:971 / mpi_controller.cc:455-515's 3-phase scheme,
    which collapses on TPU to ``pmean(local)`` + weighted ppermute over the
    machine mesh axis (the broadcast phase is free — all local devices compute
    identical combines).
    """

    _comm_kind = "hierarchical"

    def __init__(self, *args, **kw) -> None:
        st = _global_state()
        if st.machine_mesh is None:
            raise RuntimeError(
                "hierarchical optimizer requires a homogeneous machine layout")
        super().__init__(*args, **kw)
        self.self_weight: Optional[float] = None
        self.neighbor_machine_weights: Optional[Dict] = None
        self.send_neighbor_machines = None
        self.enable_topo_check: bool = False

    def _mesh_axes(self):
        st = _global_state()
        return st.machine_mesh, "machine"

    def _plan(self) -> CombinePlan:
        st = _global_state()
        m = st.size // st.local_size
        if self.send_neighbor_machines is None:
            if self.neighbor_machine_weights is None:
                mtopo = topology_util.ExponentialTwoGraph(m) if m > 1 else \
                    topology_util.FullyConnectedGraph(1)
                W = np.zeros((m, m))
                for r in range(m):
                    nbrs = topology_util.in_neighbor_ranks(mtopo, r)
                    u = 1.0 / (len(nbrs) + 1)
                    W[r, r] = u
                    for src in nbrs:
                        W[src, r] = u
            else:
                raise ValueError(
                    "neighbor_machine_weights requires send_neighbor_machines")
        else:
            W = _dynamic_weight_matrix(
                m, self.send_neighbor_machines, self.self_weight,
                self.neighbor_machine_weights, self.enable_topo_check)
        return CombinePlan(W)

    def init(self, params, model_state=None) -> TrainState:
        st = _global_state()
        opt_state = self.base.init(params)
        mesh = st.machine_mesh
        return TrainState(
            params=replicate(params, mesh),
            opt_state=replicate(opt_state, mesh),
            model_state=None if model_state is None else replicate(model_state, mesh),
        )


class DistributedShardedAllreduceOptimizer(_FusedOptimizer):
    """ZeRO-1 sharded data parallelism: reduce_scatter grads, shard the
    optimizer state, all_gather updated params.

    Net-new TPU-native capability — the reference has no FSDP/ZeRO analog
    (SURVEY §2.6 marks sharding absent). Numerically it matches
    :class:`DistributedGradientAllreduceOptimizer` (same mean gradient, same
    update) whenever the base transform is elementwise (sgd/momentum/adam/
    adamw/rmsprop...), while each rank stores only ``1/n`` of the optimizer
    state: the step flattens the gradient pytree to one buffer, moves it with
    a single ``psum_scatter`` (half the wire bytes of an all-reduce), updates
    the local flat shard, and reassembles params with one tiled
    ``all_gather`` — the ICI-native ZeRO-1 schedule.

    Two equivalence caveats. Transforms that couple elements *across* the
    tree (e.g. global-norm clipping) see per-shard statistics instead of
    global ones; compose those ahead of the wrapper on the unsharded
    gradients if exactness matters. And ``ravel_pytree`` promotes mixed-dtype
    param trees to one flat dtype, so a bf16-backbone + f32-head model keeps
    its optimizer moments in the promoted dtype (usually f32) rather than
    per-leaf dtypes — higher precision than the per-leaf reference, but not
    bit-identical to it.
    """

    _comm_kind = "sharded_allreduce"

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        if self.num_steps_per_communication != 1:
            raise ValueError(
                "DistributedShardedAllreduceOptimizer requires "
                "num_steps_per_communication=1: a local step cannot update "
                "replicated params from sharded optimizer state")

    _shard_of = staticmethod(_flat_shard)

    def init(self, params, model_state=None) -> TrainState:
        st = _global_state()
        mesh = st.mesh
        n = mesh.devices.size
        opt = self.base
        params_r = replicate(params)

        def per_rank(params):
            p = _unstack(params)
            flat, _ = jax.flatten_util.ravel_pytree(p)
            shard, _ = self._shard_of(flat, n, lax.axis_index(mesh.axis_names))
            return _restack(opt.init(shard))

        spec = P(mesh.axis_names)
        opt_state = jax.jit(shard_map(
            per_rank, mesh=mesh, in_specs=(spec,), out_specs=spec))(params_r)
        return TrainState(
            params=params_r,
            opt_state=opt_state,
            model_state=None if model_state is None else replicate(model_state),
        )

    def _build(self, key, plan, do_comm):
        mesh, _ = self._mesh_axes()
        return build_sharded_step(mesh, self._loss, self.base)


# ---------------------------------------------------------------------------
# Window (asynchronous gossip) optimizers
# ---------------------------------------------------------------------------

def _live_neighbor_sets(win, dead, demoted=frozenset()):
    """(live_out, live_in) neighbor maps with dead ranks — and tuner-
    demoted directed edges (ISSUE r16) — excluded."""
    n = win.size
    return ({r: [d for d in win.out_neighbors[r] if d not in dead
                 and (r, d) not in demoted]
             for r in range(n)},
            {r: [s for s in win.in_neighbors[r] if s not in dead
                 and (s, r) not in demoted]
             for r in range(n)})


def _healed_recv_weights(win, dead, self_weight, neighbor_weights,
                         demoted=frozenset()):
    """Combine weights over the LIVE in-neighbor sets (self-healing gossip).

    Defaults (both None) recompute the uniform ``1/(live_indegree + 1)``
    average, so each survivor still forms a convex combination — the
    shrunken-graph analog of win_update's own default. User-supplied
    weights keep their shape: dead sources drop out and the remaining
    entries (self included) rescale by one factor so each rank's total
    weight is preserved (column renormalization, the same rule as
    ``topology_util.prune_dead_ranks``). ``demoted`` directed edges
    (the self-tuning controller's in-degree lever,
    ``topology_util.demote_in_edges``) drop out of the receiving rank's
    column by the same rule — for that column only, the demoted source
    is indistinguishable from a dead one."""
    from .ops.neighbors import _per_rank

    n = win.size
    _, live_in = _live_neighbor_sets(win, dead, demoted)
    if self_weight is None and neighbor_weights is None:
        u = {r: 1.0 / (len(live_in[r]) + 1) for r in range(n)}
        return u, {r: {s: u[r] for s in live_in[r]} for r in range(n)}
    sw = _per_rank(self_weight, n, "self_weight")
    nw_table = _windows._edge_weights(neighbor_weights, win.in_neighbors,
                                      1.0, "neighbor_weights", n)
    out_sw, out_nw = {}, {}
    for r in range(n):
        total = float(sw[r]) + sum(nw_table[r].values())
        live = {s: w for s, w in nw_table[r].items()
                if s not in dead and (s, r) not in demoted}
        live_total = float(sw[r]) + sum(live.values())
        scale = total / live_total if live_total > 0 else 1.0
        out_sw[r] = float(sw[r]) * scale
        out_nw[r] = {s: w * scale for s, w in live.items()}
    return out_sw, out_nw


def _healed_send_table(win, dead, dst_weights, demoted=frozenset()):
    """Send weights with dead destinations — and tuner-demoted edges —
    dropped (no rescale: put-style send weights are per-edge multipliers,
    not a distributed mass). Skipping the send is where a demotion
    actually saves wire bytes; the receive-side renormalization keeps the
    combine convex."""
    n = win.size
    live_out, _ = _live_neighbor_sets(win, dead, demoted)
    if dst_weights is None:
        return {r: {d: 1.0 for d in live_out[r]} for r in range(n)}
    table = _windows._edge_weights(dst_weights, win.out_neighbors, 1.0,
                                   "dst_weights", n)
    return {r: {d: w for d, w in table[r].items()
                if d not in dead and (r, d) not in demoted}
            for r in range(n)}

class _WindowOptimizer(_FusedOptimizer):
    """Local fused update + host-scheduled window gossip.

    Where the fused strategies compile communication into the step, the
    window strategies keep the reference's asynchronous shape: the update is
    a compiled local step ("none" comm kind), and parameter mixing happens
    through the mailbox window subsystem (reference: _DistributedWinOptimizer,
    optimizers.py:465-621).

    **One-program gossip** (whenever ``BLUEFOG_FUSION_THRESHOLD`` > 0): the
    WHOLE parameter tree packs into a single flat ``[n, total]`` window, so
    a gossip step dispatches exactly ONE win_put/win_accumulate + ONE
    win_update program pair — where r5 dispatched one pair per 8 MB fusion
    group (a ResNet-50 gossiped in ~13 pairs; measured 10.6x dispatch-bound
    over a high-latency link, PERF.md r5). The per-rank window mutexes are
    acquired ONCE around the put+update pair instead of once per op — the
    inner ops' acquires are local depth bumps, so the hosted plane pays one
    server lock round per step. Host version bookkeeping is already one
    pipelined round-trip per op. Mixed-dtype parameter trees promote to the
    widest leaf dtype inside the packed window (the gossip average is
    computed in that dtype and cast back per leaf on unpack); set the
    threshold to 0 to recover the r5 per-leaf windows and per-leaf
    dtype-true wire.

    **Compressed gossip wire** (``BLUEFOG_WIN_CODEC``, docs/compression.md):
    hosted deposits of the fused flat window optionally ride an int8/fp8
    quantized or top-k sparsified payload. Top-k keeps an error-feedback
    residual per owned rank NEXT TO the fused flat window (the window
    object holds it in the fold/acc dtype; :meth:`ef_residual_norm`
    surfaces its magnitude, mirrored by the ``win.codec.residual_norm``
    gauge) so dropped coordinates are delayed to later gossip steps, never
    lost — the EF-SGD/CHOCO-SGD convergence argument the parity oracle in
    tests/test_codec.py pins. Push-sum's associated-p channel always ships
    exact, so mass-conservation gauges stay green under any codec.
    """

    _comm_kind = "none"
    _zero_init = False  # push-sum mailboxes must start empty (no stale mass)
    # Convergence gauge (docs/observability.md): put/get gossip records
    # the neighborhood consensus distance each comm step; push-sum opts
    # out (its numerator is biased by p — debias_drift is its signal).
    _consensus_gauge = True

    _instance_counter = [0]  # id() can recycle after GC; a counter cannot

    def __init__(self, *args, window_prefix: Optional[str] = None, **kw) -> None:
        super().__init__(*args, **kw)
        _WindowOptimizer._instance_counter[0] += 1
        self._prefix = window_prefix or \
            f"{self.name}.{_WindowOptimizer._instance_counter[0]}"
        self._win_names: list = []
        self._treedef = None
        self.require_mutex = True
        # Elastic-membership bookkeeping (r9): healed edge tables are
        # rebuilt only when the dead set actually CHANGES — the membership
        # epoch (a local mirror, no server round-trip) gates both the
        # rebuild and the donor-side rejoin-request scan.
        self._healed_cache: Dict[frozenset, tuple] = {}
        self._serve_epoch: Optional[int] = None
        # Hybrid per-edge gossip plane (ISSUE r13): the planner's compiled
        # partition runs as one fused local-mesh program; the hosted
        # residual keeps mailbox semantics. BLUEFOG_WIN_OVERLAP=1
        # double-buffers the residual: its deposit/drain for step t runs on
        # a worker thread and folds into step t+1 (one-step-stale neighbor
        # contributions — the asynchrony window algorithms tolerate by
        # design; docs/window_planes.md).
        self._overlap_on = bool(knob_env("BLUEFOG_WIN_OVERLAP"))
        self._overlap_pending = None
        self._cur_epoch = 0
        self._rows_epoch: Optional[int] = None
        self._rows_sync_count = 0
        self._last_row_value = None
        # Sharded rotation state (ISSUE r17): factor resolved in init()
        # (needs _fused_pack); _comm_rounds drives the active shard —
        # every controller advances it on the same comm cadence, so the
        # rotation stays aligned as long as step counters do (drift is
        # caught by the wire's shard guard + straggler detection).
        self._shard_factor = 1
        self._comm_rounds = 0
        self._rejoin_shards: Dict[Tuple[str, int], Dict[int, Any]] = {}
        self._consensus_fn = None  # cached jit for the consensus gauge
        self._consensus_t = 0.0    # last gauge computation (monotonic)
        # Serving plane (docs/serving.md): controller 0 publishes the
        # post-gossip model as a versioned immutable snapshot every
        # BLUEFOG_SERVE_PUBLISH_EVERY communicating steps. Lazy — no
        # publisher object, no KV traffic, unless the knob is set.
        self._serve_publisher = None
        self._serve_pub_dead = False

    def _resolve_shard_factor(self) -> int:
        S = int(knob_env("BLUEFOG_WIN_SHARD") or 1)
        if S <= 1:
            return 1
        if not self._fused_pack:
            logger.warning(
                "BLUEFOG_WIN_SHARD=%d needs the fused window "
                "(BLUEFOG_FUSION_THRESHOLD > 0 packs the tree into one "
                "flat row the partition can cut); running unsharded", S)
            return 1
        return S

    def _active_shard(self) -> int:
        return self._comm_rounds % self._shard_factor

    def init(self, params, model_state=None) -> TrainState:
        state = super().init(params, model_state)
        leaves, self._treedef = jax.tree_util.tree_flatten(state.params)
        thr = _global_state().config.fusion_threshold_bytes
        # threshold > 0: ONE window over the whole tree (one put+update
        # program pair per gossip step); <= 0: per-leaf windows (the r5
        # escape hatch — per-leaf dtype-true wire, one pair per leaf)
        if thr > 0:
            self._groups = [list(range(len(leaves)))]
        else:
            self._groups = [[i] for i in range(len(leaves))]
        self._fused_pack = len(self._groups) == 1
        # Sharded window rows (ISSUE r17, docs/sharded_windows.md):
        # BLUEFOG_WIN_SHARD=S rotates the gossip wire over S shards of
        # the param tree — the window's row, mailbox slots, deposits and
        # published copies are all shard-sized (≈1/S of the tree), and
        # each gossip step ships only the active shard. Partition rules
        # (BLUEFOG_WIN_SHARD_RULES, ops/partition.py) pick each leaf's
        # shard axis; resolved ONCE here into the PackSpec every pack,
        # wire payload, and rejoin reassembly derives from.
        self._shard_factor = self._resolve_shard_factor()
        self._comm_rounds = 0
        shard_part = None
        if self._shard_factor > 1:
            from .ops import partition as _partition

            floor_kb = knob_env("BLUEFOG_WIN_SHARD_FLOOR_KB") or 0.0
            shard_part = _partition.spec_for_tree(
                state.params, self._shard_factor,
                rules_spec=knob_env("BLUEFOG_WIN_SHARD_RULES"),
                floor_bytes=int(float(floor_kb) * 1024))
        self._specs = [
            _fusion.make_spec([leaves[i] for i in idxs], shard=shard_part)
            for idxs in self._groups
        ]
        self._win_names = [
            f"{self._prefix}.{gi}" for gi in range(len(self._groups))]
        for nm, idxs, spec in zip(self._win_names, self._groups, self._specs):
            if self._shard_factor > 1:
                packed = _fusion.pack_shard_jit(
                    [leaves[i] for i in idxs], spec, 0)
            else:
                packed = _fusion.pack_jit([leaves[i] for i in idxs], spec)
            if not _windows.win_create(packed, nm, zero_init=self._zero_init):
                raise RuntimeError(f"window {nm} already exists")
            if self._shard_factor > 1:
                _windows._get_window(nm).bind_shard(self._shard_factor)
        from .runtime import heartbeat as _hb

        if _hb.quarantine_pending():
            win0 = _windows._get_window(self._win_names[0])
            if win0.hosted:
                # Quarantined rejoin: adopt current state from a live
                # in-neighbor (striped win_get transport) — or the newest
                # local checkpoint — BEFORE the first step, then publish
                # quarantine completion so survivors re-admit this rank.
                state = self._rejoin_state_transfer(state)
            else:
                logger.warning(
                    "rejoin: collective-plane windows cannot transfer "
                    "state one-sidedly (every controller dispatches every "
                    "program); completing quarantine with fresh state")
            _hb.complete_quarantine()
        return state

    def ef_residual_norm(self) -> float:
        """L2 norm of the wire codec's error-feedback residuals held
        alongside this optimizer's fused flat window(s) (0.0 when no
        error-feedback codec is configured or nothing was compressed
        yet). A norm that grows without bound means the chosen top-k
        fraction cannot keep up with the gradient scale — raise it."""
        total = 0.0
        for nm in self._win_names:
            total += _windows._get_window(nm).ef_residual_norm() ** 2
        return float(np.sqrt(total))

    def free(self) -> None:
        if self._overlap_pending is not None:
            # drain the in-flight residual leg: win_free under it would
            # race the drain against the mailbox clear
            try:
                self._overlap_pending.result()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass
            self._overlap_pending = None
        for nm in self._win_names:
            _windows.win_free(nm)
        self._win_names = []
        self._restore_flags()

    # -- hybrid per-edge plane plumbing (ISSUE r13) ------------------------

    def _hybrid_part(self, dead):
        """``(window, partition)`` when this step takes the hybrid path:
        one fused window on the hosted plane whose planner found at least
        one compiled edge. None falls back to the pure hosted flow."""
        if not self._fused_pack:
            return None
        win = _windows._get_window(self._win_names[0])
        if not win.hosted or win._planner is None:
            return None
        self._cur_epoch = _hb.membership_epoch()
        part = win.plane_partition(dead, epoch=self._cur_epoch)
        if part is None or not part.compiled:
            return None
        return win, part

    def _harvest_overlap(self):
        """Collect the previous step's deferred hosted-residual leg (the
        one-step-stale contributions). Cleared BEFORE the result is
        examined, so a PeerLostError propagating out of here leaves no
        wedged pending for the healed-topology retry to trip over."""
        pend, self._overlap_pending = self._overlap_pending, None
        if pend is None:
            return None
        return pend.result()

    def _start_overlap(self, fn) -> None:
        self._overlap_pending = _windows._Prefetch(fn)

    def _flush_rows(self) -> None:
        """Install + publish the window's host rows from the last hybrid
        step's combined value. The all-compiled fast path has no hosted
        put leg to publish rows every step, so donors' one-sided reads
        (rejoin state transfer, win_get) see a bounded-stale copy
        refreshed here on the sync cadence and on membership-epoch change
        (a rejoin bumps the epoch before anyone reads)."""
        if self._last_row_value is None or not self._win_names:
            return
        win = _windows._get_window(self._win_names[0])
        rows = _windows._owned_rows(self._last_row_value, win.owned)
        with win.state_mu:
            for r in win.owned:
                win._rows[r] = np.asarray(rows[r]).astype(
                    win.dtype, copy=False).copy()
            win._publish_selves(win.owned)

    _ROWS_SYNC_EVERY = 16  # fast-path publish cadence (steps)

    def _sync_rows_cadence(self, value) -> None:
        self._last_row_value = value
        self._rows_sync_count += 1
        if self._cur_epoch == self._rows_epoch and \
                self._rows_sync_count % self._ROWS_SYNC_EVERY:
            return
        self._rows_epoch = self._cur_epoch
        self._flush_rows()

    def _restore_flags(self) -> None:
        pass  # push-sum restores the global associated-p toggle

    # -- convergence gauge (live telemetry plane, docs/observability.md) ---
    # (gap shared with the sampler's cadence; tests zero _consensus_t to
    # force a per-step reading against the numpy oracle)
    #
    # For combine weights that sum to 1 (the default and every healed
    # table), mixed_r - x_r = (1 - sw_r) * (x̄_nbr - x_r) where x̄_nbr is
    # the combine-weighted neighbor mean — so the neighborhood consensus
    # distance ||x̄_nbr - x_r|| falls out of ONE elementwise pass over the
    # already-available pre/post-gossip leaves, no extra combine. With
    # custom non-normalized weights the gauge is the same ratio and stays
    # a faithful decay signal (the oracle tests pin the normalized case).

    def _consensus_self_weights(self, dead) -> Dict[int, float]:
        """Effective self-weight per owned rank (the user's scalar when
        set, else the live-in-degree default the healed tables use)."""
        win = _windows._get_window(self._win_names[0])
        sw = getattr(self, "self_weight", None)
        out: Dict[int, float] = {}
        for r in win.owned:
            live_in = [s for s in win.in_neighbors[r] if s not in dead]
            if not live_in:
                continue
            out[r] = float(sw) if sw is not None \
                else 1.0 / (len(live_in) + 1)
        return out

    def _record_consensus(self, old_leaves, new_leaves) -> None:
        """Set ``opt.consensus_dist`` from the pre/post-gossip leaves
        (RMS over owned ranks). Time-gated to the telemetry sampler's
        ~1 Hz cadence: the pass is one elementwise program over the
        model plus a device sync, which at compiled-plane step rates
        would cost real throughput if it ran every comm step — and the
        series only consumes one value per second anyway. Never raises —
        a telemetry gauge must not take a training step down."""
        if not self._consensus_gauge or not self._win_names:
            return
        now = time.monotonic()
        if now - self._consensus_t < _CONSENSUS_MIN_GAP:
            return
        self._consensus_t = now
        try:
            fn = self._consensus_fn
            if fn is None:
                def _sq(olds, news):
                    acc = None
                    for a, b in zip(olds, news):
                        d = b.astype(jnp.float32) - a.astype(jnp.float32)
                        s = jnp.sum(jnp.square(d).reshape(d.shape[0], -1),
                                    axis=1)
                        acc = s if acc is None else acc + s
                    return acc
                fn = self._consensus_fn = jax.jit(_sq)
            sq = np.asarray(fn(old_leaves, new_leaves))
            sw = self._consensus_self_weights(self._dead_ranks())
            total = 0.0
            cnt = 0
            for r, w in sw.items():
                denom = 1.0 - w
                if denom <= 1e-9 or r >= len(sq):
                    continue
                total += float(sq[r]) / (denom * denom)
                cnt += 1
            if cnt:
                _metrics.gauge("opt.consensus_dist").set(
                    float(np.sqrt(total / cnt)))
        except Exception as exc:  # noqa: BLE001 — gauge only
            logger.debug("consensus gauge skipped (%s)", exc)

    def _local_step(self, state, batch):
        key = (False, "none")
        fn = self._step_cache.get(key)
        if fn is None:
            fn = self._build(key, None, False)
            self._step_cache[key] = fn
        params, opt_state, model_state, metrics = fn(
            np.zeros((1, 1), np.float32),
            state.params, state.opt_state, state.model_state, batch)
        return TrainState(params, opt_state, model_state), metrics

    def _gossip(self, buffers):  # packed [n, total] buffers -> mixed buffers
        raise NotImplementedError

    # -- elastic rejoin: quarantined state transfer (ISSUE r9) -------------
    #
    # A respawned rank attaches with a bumped incarnation (its zombie is
    # fenced server-side) and lands here from init(): QUARANTINED — visible
    # in membership, excluded from averaging — until it adopts current
    # state. The transfer is a striped read of the donor's published packed
    # window row (the r7 win_get transport, reused as-is) plus the donor
    # controller's step counter; push-sum overrides `_transfer_rank` with a
    # cooperative MASS SPLIT so total mass is exactly conserved. Fallback:
    # the newest local orbax checkpoint (BLUEFOG_CHECKPOINT_DIR); last
    # resort: fresh parameters with an ERROR log.

    def _step_counter_key(self, pid: int) -> str:
        return f"bf.opt.{self._prefix}.step.{pid}"

    def _publish_step_counter(self) -> None:
        """One cheap KV put per gossip step: a future rejoiner adopts the
        donor controller's counter so local-SGD communication cadence
        (num_steps_per_communication) stays aligned after the transfer."""
        try:
            _cp.client().put(
                self._step_counter_key(_global_state().process_index),
                self._counter)
        except (OSError, RuntimeError):
            pass

    def _maybe_publish_snapshot(self, leaves) -> None:
        """Serving-plane publisher hook (docs/serving.md).

        On controller 0, every ``BLUEFOG_SERVE_PUBLISH_EVERY``-th
        COMMUNICATING step, the post-gossip leaves are written to the
        control plane as one versioned immutable snapshot (version = the
        step counter, codec = the trainer's wire codec through
        ``state_codec_for``). Publish failures degrade the serving plane,
        never the training step — this method must not raise.
        """
        if self._serve_pub_dead:
            return
        try:
            every = int(knob_env("BLUEFOG_SERVE_PUBLISH_EVERY") or 0)
            if every <= 0:
                return
            if _global_state().process_index != 0 or not _cp.active():
                return
            if (self._counter // self.num_steps_per_communication) \
                    % every != 0:
                return
            if self._serve_publisher is None:
                from .serving.snapshot import (SnapshotPublisher,
                                               resolve_serve_codec)
                win = _windows._get_window(self._win_names[0])
                self._serve_publisher = SnapshotPublisher(
                    _cp.client(),
                    codec=resolve_serve_codec(getattr(win, "codec", None)))
            stats = self._serve_publisher.publish(
                [np.asarray(v) for v in leaves], self._counter,
                step=self._counter)
            _metrics.counter("serve.publishes").inc()
            _metrics.counter("serve.publish_wire_bytes").inc(
                int(stats["wire_bytes"]))
            _metrics.gauge("serve.version").set(int(stats["version"]))
            _metrics.gauge("serve.publish_sec").set(stats["seconds"])
        except (OSError, RuntimeError) as exc:
            # transient wire trouble: skip this version, keep training
            logger.warning("serving-plane snapshot publish failed (%s); "
                           "version %d skipped", exc, self._counter)
        except Exception as exc:  # noqa: BLE001 — structural: disable
            self._serve_pub_dead = True
            logger.warning(
                "serving-plane publisher disabled for this run (%s)", exc)

    def _serve_rejoin_requests(self) -> None:
        """Donor-side hook, run once per membership-epoch change (base
        strategies transfer one-sidedly — only push-sum needs donor
        cooperation, see its override)."""

    def _donor_candidates(self, win, rank):
        """Live-donor candidates for `rank`'s state: its in-neighbors on
        other controllers, in sorted order (a donor must be remote — this
        controller's own rows died with the previous incarnation)."""
        owned = set(win.owned)
        return [s for s in win.in_neighbors[rank] if s not in owned]

    def _transfer_rank(self, rank: int, donor: int, deadline: float) -> bool:
        """Adopt `donor`'s published window rows as `rank`'s state —
        one-sided, under the donor's window mutexes so a concurrent
        win_update publish cannot tear the read."""
        from .runtime.native import PeerLostError

        if self._shard_factor > 1:
            return self._transfer_rank_sharded(rank, donor, deadline)
        rows = []
        for nm in self._win_names:
            win = _windows._get_window(nm)
            try:
                with _windows.win_mutex(nm, ranks=[donor]):
                    row = win.read_published_row(donor)
            except (PeerLostError, OSError):
                return False
            if row is None:
                return False
            rows.append(row)
        for nm, row in zip(self._win_names, rows):
            _windows._get_window(nm).install_row(rank, row)
        return True

    def _transfer_rank_sharded(self, rank: int, donor: int,
                               deadline: float) -> bool:
        """Sharded rejoin reassembly (ISSUE r17): the donor's published
        row carries only its CURRENT shard, and its rotation advances one
        shard per gossip step — so the rejoiner polls the donor across
        its steps, collecting each shard index exactly once, until all S
        shards of the tree are in hand (``fusion.assemble_rows`` rebuilds
        the full leaves in ``_adopt_window_rows``). A stalled donor
        (never stepping, so never rotating) times out into the next
        candidate / the checkpoint fallback like any other failed
        transfer."""
        from .runtime.native import PeerLostError

        ok = True
        for nm in self._win_names:
            win = _windows._get_window(nm)
            # fresh accumulator PER DONOR ATTEMPT: assemble_rows must
            # stitch a rank's tree from a single donor's rotation — a
            # partial collection left by a failed previous donor must not
            # be topped up with another donor's shards
            got = {}
            self._rejoin_shards[(nm, rank)] = got
            while len(got) < self._shard_factor and \
                    time.monotonic() < deadline:
                try:
                    with _windows.win_mutex(nm, ranks=[donor]):
                        row, sidx = win.read_published_shard(donor)
                except (PeerLostError, OSError):
                    return False
                if row is not None and sidx is not None and sidx not in got:
                    got[int(sidx)] = np.array(row)
                    continue  # a new shard may already be up — re-read now
                time.sleep(0.05)
            if len(got) < self._shard_factor:
                ok = False
                break
        if ok:
            # keep the window's published copy fresh for the shard it is
            # currently rotated to (the first put re-publishes anyway)
            for nm in self._win_names:
                win = _windows._get_window(nm)
                cur = self._rejoin_shards[(nm, rank)].get(
                    max(win.active_shard, 0))
                if cur is not None and rank in win.owned:
                    win.install_row(rank, cur)
        return ok

    def _realign_rotation(self) -> None:
        """Re-derive the shard-rotation counter from the (just adopted)
        step counter. ``_comm_rounds == _counter // k`` is the
        steady-state invariant on every controller (a comm round fires
        exactly when the counter crosses a multiple of k), so deriving it
        after a rejoin realigns this controller's active shard with its
        peers. Leaving it at the init-time 0 would phase-shift the
        rotation permanently — the wire's shard guard would then discard
        every deposit to/from this rank forever."""
        self._comm_rounds = self._counter // self.num_steps_per_communication

    def _rejoin_state_transfer(self, state: TrainState) -> TrainState:
        st = _global_state()
        win0 = _windows._get_window(self._win_names[0])
        owned = sorted(win0.owned)
        timeout = float(os.environ.get("BLUEFOG_CP_QUARANTINE_TIMEOUT",
                                       "120"))
        deadline = time.monotonic() + timeout
        donors: Dict[int, int] = {}
        for r in owned:
            for d in self._donor_candidates(win0, r):
                if self._transfer_rank(r, d, deadline):
                    donors[r] = d
                    break
            if r not in donors:
                break
        if len(donors) == len(owned):
            # adopt the (max) donor-controller step counter so the
            # communication cadence realigns
            try:
                cl = _cp.client()
                pids = {getattr(st.devices[d], "process_index", 0)
                        for d in donors.values()}
                steps = [int(cl.get(self._step_counter_key(p)))
                         for p in pids]
                if steps:
                    self._counter = max(self._counter, max(steps))
            except (OSError, RuntimeError):
                pass
            self._realign_rotation()
            logger.warning(
                "rejoin: window state transferred from live in-neighbors "
                "%s (step counter -> %d)", donors, self._counter)
            return self._adopt_window_rows(state)
        restored = self._restore_from_checkpoint(state)
        if restored is not None:
            state, step = restored
            self._counter = int(step)
            self._realign_rotation()
            logger.warning(
                "rejoin: no live in-neighbor served state transfer; "
                "restored the newest local checkpoint (step %d)", step)
            return state
        logger.error(
            "rejoin: no live donor and no checkpoint "
            "(BLUEFOG_CHECKPOINT_DIR unset/empty) — continuing from FRESH "
            "parameters; this rank re-enters averaging with "
            "initialization-time values")
        return state

    def _adopt_window_rows(self, state: TrainState) -> TrainState:
        """Rebuild state.params' owned rows from the windows' current rows
        (host-side unpack: a one-sided rejoin cannot dispatch a collective
        unpack program)."""
        st = _global_state()
        leaves = jax.tree_util.tree_flatten(state.params)[0]
        out = list(leaves)
        for nm, idxs, spec in zip(self._win_names, self._groups,
                                  self._specs):
            win = _windows._get_window(nm)
            if self._shard_factor > 1:
                # reassemble the full per-leaf arrays from the S shard
                # rows the sharded transfer collected (host-side, no
                # compiled dispatch — the one-sided rejoin contract)
                rows = {}
                for r in win.owned:
                    got = self._rejoin_shards.get((nm, r), {})
                    rows[r] = _fusion.assemble_rows(
                        [got[s] for s in range(self._shard_factor)], spec)
            else:
                rows = {r: _fusion.unpack_row(
                            self._window_row_to_params(win, r), spec)
                        for r in win.owned}
            for j, i in enumerate(idxs):
                leaf = leaves[i]
                shape = tuple(leaf.shape)
                sh = leaf.sharding
                per_rank = {r: rows[r][j] for r in rows}
                if len(per_rank) == shape[0]:
                    out[i] = jax.device_put(
                        np.stack([per_rank[r] for r in range(shape[0])]),
                        sh)
                else:
                    shards = [
                        jax.device_put(per_rank[r][None], st.devices[r])
                        for r in sorted(per_rank)
                    ]
                    out[i] = jax.make_array_from_single_device_arrays(
                        shape, sh, shards)
        params = jax.tree_util.tree_unflatten(self._treedef, out)
        return TrainState(params, state.opt_state, state.model_state)

    def _window_row_to_params(self, win, rank: int) -> np.ndarray:
        """Window row -> parameter row (identity; push-sum de-biases)."""
        return win._rows[rank]

    def _restore_from_checkpoint(self, state: TrainState):
        ckdir = os.environ.get("BLUEFOG_CHECKPOINT_DIR")
        if not ckdir or not os.path.isdir(ckdir):
            return None
        from . import checkpoint as _ckpt

        path = _ckpt.latest_path(ckdir)
        if path is None:
            return None
        try:
            new_state, step = _ckpt.restore(path, template=state)
        except Exception as exc:  # noqa: BLE001 — fall through to fresh
            logger.error("rejoin: checkpoint restore from %s failed (%s)",
                         path, exc)
            return None
        self._reseed_windows(new_state)
        return new_state, step

    def _reseed_windows(self, state: TrainState) -> None:
        """Re-publish the windows' owned rows from restored parameters
        (host-side pack — see _adopt_window_rows for why no jit)."""
        leaves = jax.tree_util.tree_flatten(state.params)[0]
        for nm, idxs, spec in zip(self._win_names, self._groups,
                                  self._specs):
            win = _windows._get_window(nm)
            per_leaf_rows = [_windows._owned_rows(leaves[i], win.owned)
                             for i in idxs]
            # sharded windows hold shard-sized rows: reseed the shard the
            # window is currently rotated to (the next put refreshes it)
            shard = max(win.active_shard, 0) if self._shard_factor > 1 \
                else None
            for r in win.owned:
                win.install_row(r, _fusion.pack_row(
                    [rows[r] for rows in per_leaf_rows], spec,
                    shard=shard))

    def _dead_ranks(self) -> set:
        """Mesh ranks hosted by dead controllers, consulted EVERY gossip
        step (self-healing topology): the window strategies drop these
        from their edge sets and renormalize, so a SIGKILLed peer shrinks
        the graph within one heartbeat timeout instead of stalling the
        survivors. Only meaningful on the hosted plane — the compiled
        collective plane needs every controller dispatching anyway."""
        win = _windows._get_window(self._win_names[0])
        if not win.hosted:
            return set()
        from .runtime.heartbeat import dead_ranks

        return dead_ranks()

    def _gossip_peers(self, win, owned, dead=frozenset()):
        """Remote ranks whose mutexes this controller's gossip ops lock
        (superset of every inner op's lock set — the hoisted acquisition
        must cover them all or the inner ops would acquire out of global
        sorted order). Put-family ops lock write destinations; dead ranks
        are excluded — the healed edge tables never touch them, and
        skipping their mutexes avoids pointless server lock rounds."""
        return {d for s in owned for d in win.out_neighbors[s]
                if d not in dead}

    def _hoisted_mutex(self, name, dead=frozenset()):
        """One mutex acquisition for the whole put+update pair.

        The inner ops still pass ``require_mutex=True``; their acquires are
        local depth bumps on the already-held locks (no server round-trip),
        so strict-mode drains keep working while the hosted plane pays ONE
        lock round per step instead of one per op."""
        if not self.require_mutex:
            return contextlib.nullcontext()
        win = _windows._get_window(name)
        if not win.hosted:
            ranks = range(win.size)
        else:
            owned = set(win.owned)
            ranks = sorted(owned | self._gossip_peers(win, owned, dead))
        return _windows.win_mutex(name, ranks=ranks)

    def step(self, state: TrainState, batch) -> Tuple[TrainState, Dict]:
        self._counter += 1
        do_comm = (self._counter % self.num_steps_per_communication) == 0
        _metrics.gauge("opt.step").set(self._counter)
        _perf_gate_delay()
        try:
            return self._step_body(state, batch, do_comm)
        except Exception as exc:
            # the always-on black box: a fatal gossip step (PeerLostError
            # included, once the healed-topology retry is exhausted) dumps
            # the ring before the exception unwinds (rate-limited)
            _flight.fatal("opt.step", exc)
            raise

    def _step_body(self, state: TrainState, batch,
                   do_comm: bool) -> Tuple[TrainState, Dict]:
        fl = _flight.recorder()
        with timeline_context(self.name, "STEP"), \
                _metrics.timed("opt.step_sec"), \
                fl.span("opt.step", b=self._counter):
            with fl.span("opt.local"):
                state, metrics = self._local_step(state, batch)
            if not do_comm:
                return state, metrics
            if _windows._get_window(self._win_names[0]).hosted:
                # donor-side rejoin protocol + step-counter publish: one
                # epoch compare (local mirror) and one KV put per gossip
                # step — the serve scan itself only runs on epoch change
                self._serve_rejoin_requests()
                self._publish_step_counter()
            leaves = jax.tree_util.tree_flatten(state.params)[0]
            # PACK/UNPACK sub-spans: fusion-buffer copy time, the analog
            # of the reference's MEMCPY_IN/OUT_FUSION_BUFFER activities
            # (common/timeline.cc usage, mpi_controller.cc:276-292) —
            # without them the host cost of fusion is invisible next to
            # the COMMUNICATE spans. (Packing inside the step program was
            # tried and measured ~45 ms SLOWER at MLP scale on the CPU
            # mesh: the in-program concat defeats the donated in-place
            # optimizer update.)
            shard = -1
            with timeline_context(self.name, "PACK"), \
                    _metrics.timed("opt.pack_sec"), fl.span("opt.pack"):
                if self._shard_factor > 1:
                    # rotate: pack ONLY the active shard's pieces — the
                    # window row, every deposit, and the published copy
                    # this step are shard-sized (1/S of the tree)
                    shard = self._active_shard()
                    _windows._get_window(
                        self._win_names[0]).set_active_shard(shard)
                    packed = [
                        _fusion.pack_shard_jit(
                            [leaves[i] for i in idxs], spec, shard)
                        for idxs, spec in zip(self._groups, self._specs)
                    ]
                else:
                    packed = [
                        _fusion.pack_jit([leaves[i] for i in idxs], spec)
                        for idxs, spec in zip(self._groups, self._specs)
                    ]
            with _metrics.timed("opt.gossip_sec"), fl.span("opt.gossip"):
                if self._fused_pack:
                    # Single window: one mutex acquisition spans the whole
                    # put+update pair (inner acquires are local depth
                    # bumps). A PeerLostError here comes from the hoisted
                    # acquire — BEFORE any data op, so retrying is
                    # side-effect-free: the dead holder's lock was
                    # force-released server-side, and _gossip recomputes
                    # its edge tables against the (now updated) dead set,
                    # continuing on the shrunken graph.
                    for attempt in (0, 1):
                        try:
                            with self._hoisted_mutex(self._win_names[0],
                                                     self._dead_ranks()):
                                mixed = self._gossip(packed)
                            break
                        except PeerLostError as exc:
                            if attempt:
                                raise
                            _metrics.counter("opt.gossip_retries").inc()
                            logger.warning(
                                "gossip step hit a dead peer (%s); "
                                "retrying once on the self-healed "
                                "topology", exc)
                else:
                    mixed = self._gossip(packed)
            with timeline_context(self.name, "UNPACK"), \
                    _metrics.timed("opt.unpack_sec"), fl.span("opt.unpack"):
                out = list(leaves)
                for idxs, spec, buf in zip(self._groups, self._specs,
                                           mixed):
                    if shard >= 0:
                        # scatter the combined shard back into the full
                        # leaves: only this shard's pieces change. The
                        # leaves are DONATED by default (in-place update,
                        # no full-model double-buffer) — a TrainState
                        # retained from before this step must not be read
                        # after it unless BLUEFOG_WIN_SHARD_DONATE=0
                        # (docs/sharded_windows.md, donation contract)
                        group = [out[i] for i in idxs]
                        for i, v in zip(idxs, _fusion.scatter_shard_jit(
                                group, buf, spec, shard)):
                            out[i] = v
                    else:
                        for i, v in zip(idxs,
                                        _fusion.unpack_jit(buf, spec)):
                            out[i] = v
                if shard >= 0:
                    self._comm_rounds += 1
            if shard < 0:
                # sharded steps donate the old leaves to the scatter (in-
                # place piece writes) — their convergence signal is the
                # shard-drift rate instead (docs/observability.md)
                self._record_consensus(leaves, out)
            params = jax.tree_util.tree_unflatten(self._treedef, out)
            state = TrainState(params, state.opt_state, state.model_state)
            # serving plane: publish the post-gossip model as a versioned
            # immutable snapshot (controller 0, every N-th comm step; a
            # no-op without BLUEFOG_SERVE_PUBLISH_EVERY)
            self._maybe_publish_snapshot(out)
        # live telemetry plane: ~1 Hz self-gated sample so single-
        # controller jobs (no heartbeat tick) still stream bf.ts.<rank>
        _timeseries.maybe_sample()
        # self-tuning controller: same self-gated funnel for single-
        # controller jobs; no-op unless BLUEFOG_TUNE=1
        _tuner.maybe_tick()
        return state, metrics


class DistributedWinPutOptimizer(_WindowOptimizer):
    """Push-style gossip: put fresh params into out-neighbors' mailboxes,
    then combine self + received values under mutex (reference:
    optimizers.py:867, pull_style=False)."""

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self.dst_weights = None
        self.self_weight = None
        self.neighbor_weights = None

    def _gossip(self, leaves):
        # consult the failure detector EVERY step (a cheap in-memory set):
        # dead neighbors drop out of the send and combine tables, weights
        # renormalize over the live sets, and the survivors keep gossiping
        # on the shrunken graph. The healed tables themselves are REBUILT
        # only when membership changes (cached per dead set — the epoch
        # bump on join/leave/re-admission is what moves it), not re-derived
        # every step.
        dead = self._dead_ranks()
        demoted = _tuner.demoted_edges()
        hyb = self._hybrid_part(dead)
        dst_weights, self_weight = self.dst_weights, self.self_weight
        neighbor_weights = self.neighbor_weights
        if dead or demoted or hyb is not None:
            # the hybrid path needs the tables materialized even with an
            # empty dead set (the fused program takes explicit weights);
            # same cache, same per-dead-set rebuild discipline
            win = _windows._get_window(self._win_names[0])
            custom = (dst_weights is not None or self_weight is not None
                      or neighbor_weights is not None)
            key = ("put", frozenset(dead), demoted)
            cached = None if custom else self._healed_cache.get(key)
            if cached is None:
                if dead:
                    _metrics.counter("opt.healed_rebuilds").inc()
                sw, nw = _healed_recv_weights(win, dead, self_weight,
                                              neighbor_weights, demoted)
                cached = (_healed_send_table(win, dead, dst_weights,
                                             demoted), sw, nw)
                if not custom:
                    if len(self._healed_cache) > 16:
                        self._healed_cache.clear()
                    self._healed_cache[key] = cached
            dst_weights, self_weight, neighbor_weights = cached
        if hyb is not None:
            return self._gossip_hybrid(hyb, leaves[0], dst_weights,
                                       self_weight, neighbor_weights)
        out = []
        for nm, leaf in zip(self._win_names, leaves):
            # donate_source: the packed fusion buffer is dead after the
            # put — the compiled exchange reuses it for the self value
            # (with the default all-ones self weight, a pure alias)
            _windows.win_put(leaf, nm, dst_weights=dst_weights,
                             require_mutex=self.require_mutex,
                             donate_source=True)
            out.append(_windows.win_update(
                nm, self_weight=self_weight,
                neighbor_weights=neighbor_weights,
                require_mutex=self.require_mutex))
        return out

    def _gossip_hybrid(self, hyb, leaf, dst_weights, self_weight,
                       neighbor_weights):
        """One hybrid gossip step: compiled partition in one fused program
        + hosted mailbox residual (deposit/drain semantics unchanged on
        its edges). With overlap on, the residual leg of step t runs on a
        worker thread and its contributions fold into step t+1."""
        win, part = hyb
        nm = self._win_names[0]
        host_dst = {s: {d: w for d, w in m.items() if (s, d) in part.hosted}
                    for s, m in dst_weights.items()}
        host_nw = {r: {s: w for s, w in m.items() if (s, r) in part.hosted}
                   for r, m in neighbor_weights.items()}
        have_out = any(host_dst.values())
        have_in = any(host_nw.values())
        ones = {r: 1.0 for r in range(win.size)}

        def hosted_leg():
            rows = None
            if have_out:
                # deposits + row publish + post-send self scaling ride the
                # unchanged hosted put
                _windows.win_put(leaf, nm, dst_weights=host_dst,
                                 require_mutex=self.require_mutex)
            if have_in:
                rows, _ = _windows._residual_update(
                    win, host_nw, reset=False,
                    require_mutex=self.require_mutex)
            return rows

        prev_rows = None
        if self._overlap_on:
            prev = self._harvest_overlap()
            prev_rows = prev if prev is not None else None
        comp, meta = _windows._run_compiled_partition(
            win, leaf, part, dst_weights, ones, self_weight,
            neighbor_weights, accumulate=False)
        if self._overlap_on:
            if have_out or have_in:
                self._start_overlap(hosted_leg)
            rows = prev_rows
        else:
            rows = hosted_leg() if (have_out or have_in) else None
        mixed = _windows._globalize(
            win, meta, _windows._combine_with_residual(win, meta, comp,
                                                       rows))
        if have_out:
            self._last_row_value = mixed  # put leg already published
        else:
            self._sync_rows_cadence(mixed)
        return [mixed]


class DistributedPullGetOptimizer(_WindowOptimizer):
    """Pull-style gossip: publish own params, pull neighbors' current values,
    combine locally (reference: optimizers.py:821, pull_style=True)."""

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        self.src_weights = None
        self.self_weight = None
        self.neighbor_weights = None

    def _gossip_peers(self, win, owned, dead=frozenset()):
        # a get locks the SOURCE ranks it reads (the in-neighbors)
        return {s for d in owned for s in win.in_neighbors[d]
                if s not in dead}

    def _gossip(self, leaves):
        st = _global_state()
        dead = self._dead_ranks()
        demoted = _tuner.demoted_edges()
        hyb = self._hybrid_part(dead)
        src_weights, self_weight = self.src_weights, self.self_weight
        neighbor_weights = self.neighbor_weights
        if dead or demoted or hyb is not None:
            win = _windows._get_window(self._win_names[0])
            custom = (src_weights is not None or self_weight is not None
                      or neighbor_weights is not None)
            key = ("get", frozenset(dead), demoted)
            cached = None if custom else self._healed_cache.get(key)
            if cached is None:
                if dead:
                    _metrics.counter("opt.healed_rebuilds").inc()
                # pull only from LIVE sources (a dead peer's published
                # tensor goes stale, and at re-publish races it could tear
                # mass) and renormalize the combine over the live in-sets
                _, live_in = _live_neighbor_sets(win, dead, demoted)
                if src_weights is None:
                    srcw = {r: {s: 1.0 for s in live_in[r]}
                            for r in range(win.size)}
                else:
                    table = _windows._edge_weights(
                        src_weights, win.in_neighbors, 1.0, "src_weights",
                        win.size)
                    srcw = {r: {s: w for s, w in table[r].items()
                                if s not in dead and (s, r) not in demoted}
                            for r in range(win.size)}
                sw, nw = _healed_recv_weights(win, dead, self_weight,
                                              neighbor_weights, demoted)
                cached = (srcw, sw, nw)
                if not custom:
                    if len(self._healed_cache) > 16:
                        self._healed_cache.clear()
                    self._healed_cache[key] = cached
            src_weights, self_weight, neighbor_weights = cached
        if hyb is not None:
            return self._gossip_hybrid(hyb, leaves[0], src_weights,
                                       self_weight, neighbor_weights)
        out = []
        for nm, leaf in zip(self._win_names, leaves):
            st.windows[nm].self_value = jnp.asarray(leaf)  # publish
            _windows.win_get(nm, src_weights=src_weights,
                             require_mutex=self.require_mutex)
            out.append(_windows.win_update(
                nm, self_weight=self_weight,
                neighbor_weights=neighbor_weights,
                require_mutex=self.require_mutex))
        return out

    def _gossip_hybrid(self, hyb, leaf, src_weights, self_weight,
                       neighbor_weights):
        """Pull-style hybrid: compiled in-edges move w*x_src in-program
        (the pull of a mesh-local source IS a ppermute); hosted residual
        sources keep publish → win_get → combine. The edge weight
        structure mirrors the put path with src_weights in the
        dst-weight position (a pull from s with weight w is the wire
        edge s→r carrying w*x_s, exactly _hosted_exchange's from_get
        table transposition)."""
        win, part = hyb
        nm = self._win_names[0]
        # src_weights is dst-keyed {r: {s: w}}; the fused program (and the
        # precheck split) want the src->dst orientation
        host_src = {r: {s: w for s, w in m.items() if (s, r) in part.hosted}
                    for r, m in src_weights.items()}
        pull_table = {s: {} for s in range(win.size)}
        for r, m in src_weights.items():
            for s, w in m.items():
                pull_table[s][r] = w
        host_nw = {r: {s: w for s, w in m.items() if (s, r) in part.hosted}
                   for r, m in neighbor_weights.items()}
        have_host = any(host_src.values()) or any(host_nw.values())
        ones = {r: 1.0 for r in range(win.size)}

        def hosted_leg():
            # publish first: hosted pulls (ours and remote peers') read the
            # published rows / owned host rows
            win.self_value = jnp.asarray(leaf)
            if any(host_src.values()):
                _windows.win_get(nm, src_weights=host_src,
                                 require_mutex=self.require_mutex)
            rows = None
            if any(host_nw.values()):
                rows, _ = _windows._residual_update(
                    win, host_nw, reset=False,
                    require_mutex=self.require_mutex)
            return rows

        prev_rows = None
        if self._overlap_on:
            prev_rows = self._harvest_overlap()
        comp, meta = _windows._run_compiled_partition(
            win, leaf, part, pull_table, ones, self_weight,
            neighbor_weights, accumulate=False)
        if self._overlap_on:
            if have_host:
                self._start_overlap(hosted_leg)
            rows = prev_rows
        else:
            rows = hosted_leg() if have_host else None
        mixed = _windows._globalize(
            win, meta, _windows._combine_with_residual(win, meta, comp,
                                                       rows))
        if have_host and not self._overlap_on:
            self._last_row_value = mixed  # publish already ran this step
        else:
            self._sync_rows_cadence(mixed)
        return [mixed]


class DistributedPushSumOptimizer(_WindowOptimizer):
    """Push-sum gossip with associated weights (column-stochastic sends).

    Reference: optimizers.py:624-773. Each rank's window holds the push-sum
    numerator; the associated-p scalar rides the same ops (the reference
    concatenates it to the flattened parameter; here it is the window
    subsystem's associated-p channel, mpi_ops.py:1339-1363). Parameters for
    the next gradient evaluation are numerator / p.
    """

    _zero_init = True  # reference creates push-sum windows with zero_init
    # the raw numerator is p-biased — pushsum.debias_drift and the mass
    # gauges are this strategy's convergence signals, not consensus_dist
    _consensus_gauge = False

    def __init__(self, *args, **kw) -> None:
        super().__init__(*args, **kw)
        st = _global_state()
        self._prior_associated_p = st.win_ops_with_associated_p
        self._reminted = False
        _windows.turn_on_win_ops_with_associated_p()

    def _restore_flags(self) -> None:
        _global_state().win_ops_with_associated_p = self._prior_associated_p

    def init(self, params, model_state=None) -> TrainState:
        # Mass-conservation accounting for the health plane: `minted` is
        # the de-bias mass this controller CREATED (p=1 per owned rank at
        # window creation, or at a checkpoint-fallback re-mint); a rejoin
        # via the donor mass split transfers mass without minting, so the
        # cluster-wide sum(mass) == sum(minted) invariant survives it
        # (bf.cluster_health's drift check; docs/metrics.md).
        was_rejoining = _hb.quarantine_pending()
        self._reminted = False
        state = super().init(params, model_state)
        minted = 0.0
        mass = 0.0
        for nm in self._win_names:
            win = _windows._get_window(nm)
            if not was_rejoining or self._reminted:
                minted += float(len(win.owned))
            p = win.host.read_p()
            mass += float(np.sum(np.asarray(p)[list(win.owned)]))
        _metrics.gauge("pushsum.minted").set(minted)
        _metrics.gauge("pushsum.mass").set(mass)
        return state

    def _gossip(self, leaves):
        st = _global_state()
        n = st.size
        # Column-stochastic weights: each rank splits mass 1/(outdeg+1)
        # between itself and every out-neighbor (optimizers.py:700-717).
        # Self-healing: dead destinations drop out and mass splits over
        # 1/(live_outdeg+1) instead — still column-stochastic over the
        # live set BY CONSTRUCTION, so push-sum's total mass (and the
        # de-biasing p mass) stays conserved on the shrunken graph. The
        # tables are cached per dead set (rebuilt only on membership
        # change, not re-derived every step).
        dead = self._dead_ranks()
        # tuner-demoted edges (ISSUE r16) drop from the SEND side here:
        # push-sum normalizes sender columns, so mass re-splits over the
        # remaining out-edges and stays conserved by construction
        demoted = _tuner.demoted_edges()
        key = (frozenset(dead), demoted)
        cached = self._healed_cache.get(key)
        if cached is None:
            if dead:  # the empty-set entry is the initial build, not a heal
                _metrics.counter("opt.healed_rebuilds").inc()
            out_nbrs = {
                r: [d for d in
                    topology_util.out_neighbor_ranks(st.topology, r)
                    if d not in dead and (r, d) not in demoted]
                for r in range(n)
            }
            sw = {r: 1.0 / (len(out_nbrs[r]) + 1) for r in range(n)}
            dw = {r: {dst: sw[r] for dst in out_nbrs[r]} for r in range(n)}
            if len(self._healed_cache) > 16:
                self._healed_cache.clear()
            self._healed_cache[key] = (sw, dw)
        else:
            sw, dw = cached
        hyb = self._hybrid_part(dead)
        if hyb is not None:
            return self._gossip_hybrid(hyb, leaves[0], sw, dw)
        out = []
        mass = 0.0
        drift = 0.0
        for nm, leaf in zip(self._win_names, leaves):
            win = st.windows[nm]
            # numerator = x * p  (x is the de-biased parameter)
            p_col = win.host.read_p()
            numer = leaf * np.asarray(p_col, leaf.dtype).reshape(
                (n,) + (1,) * (leaf.ndim - 1))
            # numer is this step's scratch product — donate it
            _windows.win_accumulate(numer, nm, self_weight=sw, dst_weights=dw,
                                    require_mutex=self.require_mutex,
                                    donate_source=True)
            collected = _windows.win_update_then_collect(
                nm, require_mutex=self.require_mutex)
            p_new = _windows.win_associated_p_all(nm)
            owned = list(win.owned)
            p_own = np.asarray(p_new)[owned]
            mass += float(np.sum(p_own))
            drift = max(drift, float(np.max(np.abs(p_own - 1.0)))
                        if len(owned) else 0.0)
            out.append(collected / np.asarray(p_new, collected.dtype).reshape(
                (n,) + (1,) * (collected.ndim - 1)))
        # health-plane gauges: this controller's share of the global
        # push-sum mass (summed across controllers by bf.cluster_health)
        # and how far the de-bias scalar has wandered from neutral
        _metrics.gauge("pushsum.mass").set(mass)
        _metrics.gauge("pushsum.debias_drift").set(drift)
        return out

    def _gossip_hybrid(self, hyb, leaf, sw, dw):
        """Hybrid push-sum: compiled edges move mass IN-PROGRAM (the fused
        accumulate-mode program sums dw*numer contributions next to the
        numer*sw self term), hosted edges via the mailbox. The p channel
        splits the same way — p*sw self down-weight plus compiled
        contributions computed host-side plus the residual collect's
        p-mailbox contraction — so ``sum(p)`` over live ranks is exactly
        the column-stochastic total either plane alone would conserve
        (the partition-boundary conservation contract, ISSUE r13).

        BLUEFOG_WIN_OVERLAP is deliberately IGNORED here: deferring the
        residual would let a later step's p*sw rescale race the deposits'
        p contributions, breaking exact conservation — push-sum keeps the
        synchronous residual (docs/window_planes.md)."""
        win, part = hyb
        nm = self._win_names[0]
        n = win.size
        p_col = np.asarray(win.host.read_p())
        numer = leaf * np.asarray(p_col, leaf.dtype).reshape(
            (n,) + (1,) * (leaf.ndim - 1))
        host_dw = {s: {d: w for d, w in m.items() if (s, d) in part.hosted}
                   for s, m in dw.items()}
        host_in = {r: {s: 1.0 for s in win.in_neighbors[r]
                       if (s, r) in part.hosted and s not in part.dead}
                   for r in range(n)}
        ones = {r: 1.0 for r in range(n)}
        collect_nw = {r: {s: 1.0 for s in win.in_neighbors[r]}
                      for r in range(n)}
        rows = p_sums = None
        if any(host_dw.values()):
            _windows.win_accumulate(numer, nm, self_weight=sw,
                                    dst_weights=host_dw,
                                    require_mutex=self.require_mutex)
        else:
            # the self down-weight normally rides the accumulate leg;
            # without one, scale p directly (rows follow on the sync
            # cadence — the numerator rows are re-derived below anyway)
            win.host.write_p_entries(
                {r: float(p_col[r] * sw[r]) for r in win.owned})
        if any(host_in.values()):
            rows, p_sums = _windows._residual_update(
                win, host_in, reset=True, require_mutex=self.require_mutex)
        comp, meta = _windows._run_compiled_partition(
            win, numer, part, dw, sw, ones, collect_nw, accumulate=True)
        collected = _windows._globalize(
            win, meta, _windows._combine_with_residual(win, meta, comp,
                                                       rows))
        # p across the partition boundary: self down-weight + compiled
        # in-contributions (host-side — p is a tiny scalar channel) +
        # the residual collect's p-mailbox contraction
        p_new = {}
        for r in win.owned:
            p_comp = sum(dw[s].get(r, 0.0) * float(p_col[s])
                         for s in range(n) if (s, r) in part.compiled)
            p_new[r] = float(p_col[r] * sw[r]) + p_comp + \
                float((p_sums or {}).get(r, 0.0))
        win.host.write_p_entries(p_new)
        p_all = np.asarray(win.host.read_p())
        owned = list(win.owned)
        p_own = p_all[owned]
        _metrics.gauge("pushsum.mass").set(float(np.sum(p_own)))
        _metrics.gauge("pushsum.debias_drift").set(
            float(np.max(np.abs(p_own - 1.0))) if owned else 0.0)
        # window rows = the collected numerator (what a donor's mass split
        # halves); cadence-published, and _serve_rejoin_requests flushes
        # them before serving so rows/p stay a consistent pair
        self._sync_rows_cadence(collected)
        return [collected / np.asarray(p_all, collected.dtype).reshape(
            (n,) + (1,) * (collected.ndim - 1))]

    # -- elastic rejoin with exact mass conservation -----------------------
    #
    # A one-sided copy cannot conserve push-sum mass: copying a donor's
    # (numerator, p) duplicates its mass, and minting fresh p=1 inflates
    # the total. The rejoiner instead REQUESTS a split: the donor's
    # controller — at its next step's serve scan, gated on the membership
    # epoch the rejoiner bumps after posting the request — halves its own
    # numerator row and p under the rank mutex (exact in IEEE arithmetic),
    # republishes, and parks the other half under transfer keys the
    # rejoiner installs. Total mass is bit-exactly unchanged, and both
    # parties' de-biased parameters x = num/p are the donor's.

    def _window_row_to_params(self, win, rank: int) -> np.ndarray:
        p = win.host.read_p()[rank]
        if p <= 0:
            return win._rows[rank]
        return (win._rows[rank].astype(np.float64) / p).astype(win.dtype)

    def _transfer_rank(self, rank: int, donor: int, deadline: float) -> bool:
        if self._shard_factor > 1:
            # A donor's mass split halves its p AND its numerator row,
            # but a sharded window row is only the ACTIVE shard's
            # numerator — splitting it would de-bias the other S-1
            # shards' implicit numerators without transferring them.
            # Sharded push-sum rejoin therefore skips the donor path and
            # falls back to the checkpoint re-mint (conservation caveat
            # logged there; docs/sharded_windows.md).
            return False
        cl = _cp.client()
        for nm in self._win_names:
            cl.put(f"w.{nm}.msreq.{rank}", donor + 1)
        # poke the donors' serve scans (they only run on epoch change)
        _cp.bump_membership_epoch()
        done_keys = [f"w.{nm}.msdone.{rank}" for nm in self._win_names]
        # bounded per-donor wait: leave budget for the remaining candidates
        wait_until = min(deadline, time.monotonic() + max(
            5.0, (deadline - time.monotonic()) / 2.0))
        served = False
        while time.monotonic() < wait_until:
            try:
                if all(cl.get(k) for k in done_keys):
                    served = True
                    break
            except OSError:
                break
            time.sleep(0.05)
        if not served:
            for nm in self._win_names:  # withdraw; try the next donor
                cl.put(f"w.{nm}.msreq.{rank}", 0)
            return False
        for nm in self._win_names:
            win = _windows._get_window(nm)
            raw = cl.get_bytes(f"w.{nm}.xfer.{rank}")
            expect = int(np.prod(win.row_shape, dtype=np.int64)) * \
                win.dtype.itemsize
            if len(raw) != expect:
                return False
            row = np.frombuffer(raw, win.dtype).reshape(win.row_shape)
            win.install_row(rank, row)
            win.host.write_p_entries(
                {rank: _cp.get_float(cl, f"w.{nm}.xferp.{rank}")})
            cl.put(f"w.{nm}.msdone.{rank}", 0)
            cl.put_bytes(f"w.{nm}.xfer.{rank}", b"")
        return True

    def _serve_rejoin_requests(self) -> None:
        ep = _hb.membership_epoch()
        if ep == self._serve_epoch:
            return
        self._serve_epoch = ep
        # Hybrid fast path: host rows are cadence-stale between publishes.
        # A mass split halves win._rows, so install the last collected
        # numerator first — rows and p must be a consistent pair or the
        # rejoiner's de-biased x would be torn (docs/window_planes.md).
        self._flush_rows()
        cl = _cp.client()
        for nm in self._win_names:
            win = _windows._get_window(nm)
            try:
                reqs = cl.get_many(
                    [f"w.{nm}.msreq.{r}" for r in range(win.size)])
            except (OSError, RuntimeError):
                return
            for r, req in enumerate(reqs):
                d = int(req) - 1
                if req <= 0 or d not in win.owned:
                    continue
                with _windows.win_mutex(nm, ranks=[d]), win.state_mu:
                    # exact split: *0.5 is an exponent decrement — the
                    # halves sum back to the original bit for bit
                    half = win._rows[d] * np.asarray(0.5, win.dtype)
                    p_half = win.host.read_p()[d] * 0.5
                    win._rows[d] = half
                    win.host.write_p_entries({d: p_half})
                    win._publish_selves([d])
                    cl.put_bytes(f"w.{nm}.xfer.{r}",
                                 np.ascontiguousarray(half).tobytes())
                    _cp.put_float(cl, f"w.{nm}.xferp.{r}", p_half)
                cl.put(f"w.{nm}.msreq.{r}", 0)
                cl.put(f"w.{nm}.msdone.{r}", 1)
                logger.warning(
                    "rejoin: split push-sum mass of owned rank %d with "
                    "rejoining rank %d (window %s, p -> %g each)",
                    d, r, nm, p_half)

    def _reseed_windows(self, state: TrainState) -> None:
        super()._reseed_windows(state)
        self._reminted = True
        # checkpoint fallback re-mints unit mass for the restored ranks:
        # exact conservation is only possible via the donor split (the old
        # incarnation's mass died with it and no donor is reachable)
        logger.warning(
            "rejoin: push-sum restored from checkpoint re-mints p=1 for "
            "its ranks — total mass is NOT conserved on this path (no "
            "live donor to split with)")
        for nm in self._win_names:
            win = _windows._get_window(nm)
            win.host.write_p_entries({r: 1.0 for r in win.owned})


__all__ = [
    "TrainState",
    "replicate",
    "unreplicate",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]
