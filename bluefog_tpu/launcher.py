"""``bfrun`` — launcher for bluefog_tpu programs.

TPU-native analog of the reference's ``bfrun`` (reference: run/run.py:198-280).
The reference assembles an ``mpirun`` command line after ssh-probing hosts and
discovering a common routed NIC (run/horovod_driver.py). None of that exists
on TPU: pods already share a control plane, and multi-host JAX bootstraps from
the coordinator address + process count (`jax.distributed.initialize`). So the
launcher's job collapses to:

  * single host: exec the script (devices = local chips), optionally
    simulating an N-device CPU mesh for development (--simulate N).
  * multi host: export the JAX distributed env (coordinator, process id,
    process count) and exec the script on this host; run the same command on
    every host (or let the TPU pod runtime fan it out).

Env parity: --timeline-filename exports BLUEFOG_TIMELINE and --verbose sets
BLUEFOG_LOG_LEVEL=debug, like run.py:143-174.
"""

from __future__ import annotations

import argparse
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bfrun",
        description="Launch a bluefog_tpu training program.",
    )
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of processes (multi-host); default: "
                        "single-process using all local devices")
    p.add_argument("--coordinator", type=str, default=None,
                   help="coordinator address host:port for jax.distributed "
                        "(required when -np > 1)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's process index (multi-host)")
    p.add_argument("--simulate", type=int, default=None, metavar="N",
                   help="simulate an N-device CPU mesh (development)")
    p.add_argument("--timeline-filename", type=str, default=None,
                   help="enable the timeline profiler, writing to this prefix")
    p.add_argument("--verbose", action="store_true",
                   help="debug logging (BLUEFOG_LOG_LEVEL=debug)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and arguments to run")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.command:
        build_parser().print_usage()
        return 1

    env = dict(os.environ)
    if args.timeline_filename:
        env["BLUEFOG_TIMELINE"] = args.timeline_filename
    if args.verbose:
        env["BLUEFOG_LOG_LEVEL"] = "debug"
    if args.simulate:
        env["JAX_PLATFORMS"] = ""
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate}"
        )
        env["BLUEFOG_SIMULATE_DEVICES"] = str(args.simulate)
    if args.num_proc and args.num_proc > 1:
        if not args.coordinator or args.process_id is None:
            print("bfrun: -np > 1 requires --coordinator and --process-id",
                  file=sys.stderr)
            return 1
        env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
        env["JAX_NUM_PROCESSES"] = str(args.num_proc)
        env["JAX_PROCESS_ID"] = str(args.process_id)

    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    os.execvpe(cmd[0], cmd, env)


if __name__ == "__main__":
    sys.exit(main())
