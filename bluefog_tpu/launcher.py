"""``bfrun`` — launcher for bluefog_tpu programs.

TPU-native analog of the reference's ``bfrun`` (reference: run/run.py:198-280).
The reference assembles an ``mpirun`` command line after ssh-probing hosts and
discovering a common routed NIC (run/horovod_driver.py). None of that exists
on TPU: pods already share a control plane, and multi-host JAX bootstraps from
the coordinator address + process count (`jax.distributed.initialize`). So the
launcher's job collapses to:

  * single host: exec the script (devices = local chips), optionally
    simulating an N-device CPU mesh for development (--simulate N).
  * multi host, one command (``-H host1:1,host2:1`` or ``--hostfile``): the
    driver fans out every process itself — local slots as subprocesses, remote
    slots over ssh — assigning ``--process-id`` and the coordinator address
    automatically, aggregating exit codes, and killing the whole job on
    Ctrl-C or first failure (the reference's one-shell launch UX,
    run/run.py:96-280 + horovod_driver.py fan-out, without the NIC-discovery
    machinery TPU pods don't need).
  * multi host, manual: export the JAX distributed env (coordinator, process
    id, process count) and exec the script on this host.

Env parity: --timeline-filename exports BLUEFOG_TIMELINE and --verbose sets
BLUEFOG_LOG_LEVEL=debug, like run.py:143-174.
"""

from __future__ import annotations

import argparse
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional, Tuple

# Remote shells print this after turning pty echo off; the launcher holds the
# job secret until it arrives (see the ssh fan-out below).
_SECRET_READY = "BF_SECRET_READY"


def _send_secret_when_ready(p: "subprocess.Popen", secret: str,
                            host: str) -> None:
    """Write the job secret to ssh stdin only after the remote's
    ``stty -echo`` has run, then pump the rest of its output through.

    The pty allocated by ``ssh -tt`` starts with ECHO on; a secret written
    at Popen time races the remote ``stty -echo`` and can be echoed into
    this process's output. The remote prints ``BF_SECRET_READY`` *after*
    echo is off, so waiting for that marker closes the race.
    """
    buf = b""
    marker = _SECRET_READY.encode()
    try:
        while marker not in buf:
            chunk = p.stdout.read(1)
            if not chunk:  # ssh died before the marker — nothing to send
                sys.stdout.buffer.write(buf)
                sys.stdout.buffer.flush()
                return
            buf += chunk
        p.stdin.write((secret + "\n").encode())
        p.stdin.flush()
        # forward everything after the marker line to our stdout; if OUR
        # stdout goes away (e.g. `bfrun ... | head`), keep DRAINING the ssh
        # pipe — stopping would fill it and wedge the remote job
        sink_broken = False

        def forward(chunk: bytes) -> None:
            nonlocal sink_broken
            if sink_broken:
                return
            try:
                sys.stdout.buffer.write(chunk)
                sys.stdout.buffer.flush()
            except (OSError, ValueError):
                sink_broken = True

        rest = buf.split(marker, 1)[1].lstrip(b"\r\n")
        if rest:
            forward(rest)
        for chunk in iter(lambda: p.stdout.read(4096), b""):
            forward(chunk)
    except (OSError, ValueError):
        pass  # ssh pipe broke at teardown — the exit-code path reports it


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bfrun",
        description="Launch a bluefog_tpu training program.",
    )
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="total number of processes (multi-host); default: "
                        "single-process using all local devices")
    p.add_argument("--coordinator", type=str, default=None,
                   help="coordinator address host:port for jax.distributed "
                        "(required when -np > 1)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this host's process index (multi-host)")
    p.add_argument("-H", "--hosts", type=str, default=None,
                   help="comma-separated host:slots list (e.g. "
                        "'host1:1,host2:1'); the driver launches every "
                        "process itself (reference run.py -H)")
    p.add_argument("--hostfile", type=str, default=None,
                   help="file with one 'host slots=N' (or 'host:N' or bare "
                        "'host') line per host (reference run.py --hostfile)")
    p.add_argument("--ssh-port", type=int, default=22,
                   help="ssh port for remote fan-out (reference --ssh-port)")
    p.add_argument("--remote-python", type=str, default="python3",
                   help="python executable to run on remote hosts")
    p.add_argument("--simulate", type=int, default=None, metavar="N",
                   help="simulate an N-device CPU mesh (development)")
    p.add_argument("--elastic", nargs="?", const=3, type=int, default=None,
                   metavar="MAX_RESTARTS",
                   help="supervise children elastically: a crashed rank is "
                        "respawned with BLUEFOG_INCARNATION bumped (the "
                        "control plane fences its zombie and the rank "
                        "rejoins through quarantined state transfer, see "
                        "docs/fault_tolerance.md), up to MAX_RESTARTS per "
                        "rank (default 3) with exponential backoff. A "
                        "terminal failure propagates only when a rank's "
                        "restart budget is exhausted or the surviving "
                        "world would drop below --min-world")
    p.add_argument("--min-world", type=int, default=1, metavar="M",
                   help="with --elastic: kill the whole job once fewer "
                        "than M ranks could keep running (default 1)")
    p.add_argument("--cp-shards", type=int, default=None, metavar="N",
                   help="shard the control plane across N server processes "
                        "(failover-capable: clients route keys with a "
                        "stable hash and fail over when a shard dies; "
                        "membership state is replicated on every shard — "
                        "docs/fault_tolerance.md). In driver (-H/--hostfile)"
                        " mode the driver launches N shard servers and "
                        "exports BLUEFOG_CP_HOSTS to every process; "
                        "otherwise exports BLUEFOG_CP_SHARDS and rank 0 "
                        "serves all N in-process")
    p.add_argument("--chaos", type=str, default=None, metavar="SPEC",
                   help="arm deterministic control-plane fault injection in "
                        "every launched process (exports BLUEFOG_CP_FAULT; "
                        "spec e.g. 'drop_after=37,delay_ms=50,trunc=1,"
                        "seed=7' — see docs/fault_tolerance.md). Testing "
                        "only: never set on a production job")
    p.add_argument("--status", action="store_true",
                   help="print the job's cluster-health view (per-rank "
                        "step counters, staleness, stragglers, push-sum "
                        "mass conservation) from the control-plane KV and "
                        "exit — works from OUTSIDE the job as long as "
                        "BLUEFOG_CP_HOST/PORT (or --cp) and, for "
                        "authenticated jobs, BLUEFOG_CP_SECRET are set. "
                        "Ranks publish snapshots on the "
                        "BLUEFOG_METRICS_INTERVAL cadence (docs/metrics.md)")
    p.add_argument("--strict", action="store_true",
                   help="with --status: exit non-zero (2) when the health "
                        "view shows findings — dead/stale ranks, "
                        "stragglers, or push-sum mass drift — so CI and "
                        "operator scripts can gate on cluster health; the "
                        "default stays exit 0 regardless of findings")
    p.add_argument("--top", action="store_true",
                   help="live cluster dashboard over the streamed "
                        "time-series plane (`bf.ts.<rank>`, "
                        "docs/observability.md): per-rank step cadence, "
                        "consensus distance + mixing rate, mass, EF "
                        "residual, shard drift, sparklines, active "
                        "alerts, and a per-edge bytes/s + transit-latency "
                        "matrix — refreshed in place every --interval "
                        "seconds from OUTSIDE the job (raw control-plane "
                        "client, no mesh join). Silent ranks (SIGKILLed/"
                        "wedged — no publication within 3 intervals) are "
                        "named")
    p.add_argument("--interval", type=float, default=2.0, metavar="SEC",
                   help="with --top: refresh cadence (default 2 s)")
    p.add_argument("--once", action="store_true",
                   help="with --top: render one frame to stdout and exit "
                        "(no screen clearing — scripts/CI friendly)")
    p.add_argument("--world", type=int, default=0, metavar="N",
                   help="with --top: expected rank count (default: the "
                        "bf.metrics.world hint, then BLUEFOG_CP_WORLD, "
                        "then a heartbeat-key scan) — ranks missing from "
                        "it are reported SILENT")
    p.add_argument("--dump", action="store_true",
                   help="trigger a cluster-wide flight-recorder dump: bump "
                        "the KV flag every rank's heartbeat/watchdog tick "
                        "polls, wait for acks, retrieve each rank's packed "
                        "ring tail over the control plane (no filesystem "
                        "access to any worker needed), and write per-rank "
                        "dumps plus a merged clock-synced chrome trace "
                        "under --out (docs/flight_recorder.md)")
    p.add_argument("--out", type=str, default="bf_flight_dump",
                   metavar="DIR",
                   help="output directory for --dump (default "
                        "bf_flight_dump/)")
    p.add_argument("--dump-timeout", type=float, default=60.0,
                   metavar="SEC",
                   help="how long --dump waits for rank acks (ranks poll "
                        "the trigger on their heartbeat cadence, default "
                        "5 s, so the default 60 covers slow ticks)")
    p.add_argument("--serve", action="store_true",
                   help="attach a read-only serving client to the job's "
                        "snapshot plane (docs/serving.md): pull the "
                        "current versioned snapshot, hot-swap on every "
                        "fence bump, and print one line per swap "
                        "(version, wire bytes, pull MB/s, publish lag) "
                        "until Ctrl-C. Works from OUTSIDE the job like "
                        "--status: raw control-plane client, no jax, no "
                        "mesh join. With --once: exit after the first "
                        "complete snapshot (0) or --serve-timeout (1)")
    p.add_argument("--serve-model", type=str, default=None,
                   metavar="MODULE:FN",
                   help="with --serve: import FN from MODULE as "
                        "model_fn(params, batch) and serve batched "
                        "inference behind the admission gate instead of "
                        "only mirroring snapshots")
    p.add_argument("--serve-timeout", type=float, default=30.0,
                   metavar="SEC",
                   help="with --serve: how long to wait for the first "
                        "complete snapshot before giving up (default 30)")
    p.add_argument("--cp", type=str, default=None,
                   metavar="HOST:PORT[,HOST:PORT...]",
                   help="control-plane address(es) for --status/--dump — "
                        "a sharded job names every shard, and the views "
                        "are merged with dead shards reported by name "
                        "(default: BLUEFOG_CP_HOSTS, then "
                        "BLUEFOG_CP_HOST/BLUEFOG_CP_PORT, falling back to "
                        "JAX_COORDINATOR_ADDRESS port + 17)")
    p.add_argument("--timeline-filename", type=str, default=None,
                   help="enable the timeline profiler, writing to this prefix")
    p.add_argument("--verbose", action="store_true",
                   help="debug logging (BLUEFOG_LOG_LEVEL=debug)")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="program and arguments to run")
    return p


def parse_hosts(hosts: str = None, hostfile: str = None) -> List[Tuple[str, int]]:
    """[(host, slots)] from -H 'h1:2,h2:2' or a hostfile.

    Hostfile lines accept the reference's 'host slots=N' (run.py:96-196),
    plus 'host:N' and bare 'host' (slots=1); '#' comments and blanks skipped.
    """
    entries: List[Tuple[str, int]] = []
    if hosts:
        for item in hosts.split(","):
            item = item.strip()
            if not item:
                continue
            host, _, slots = item.partition(":")
            entries.append((host, int(slots) if slots else 1))
    elif hostfile:
        with open(hostfile) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                parts = line.split()
                host = parts[0]
                slots = 1
                for tok in parts[1:]:
                    if tok.startswith("slots="):
                        slots = int(tok[len("slots="):])
                if ":" in host:
                    host, _, s = host.partition(":")
                    slots = int(s)
                entries.append((host, slots))
    for host, slots in entries:
        if slots < 1:
            raise ValueError(f"host {host}: slots must be >= 1, got {slots}")
    return entries


_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def _is_local(host: str) -> bool:
    return host in _LOCAL_NAMES or host in (
        socket.gethostname(), socket.getfqdn())


def _check_ssh(host: str, port: int) -> bool:
    """The reference's pre-launch ssh reachability probe (run.py:205-226)."""
    r = subprocess.run(
        ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=5",
         "-p", str(port), host, "true"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    return r.returncode == 0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# env the driver forwards to remote processes (local children inherit all)
_FORWARD_ENV_PREFIXES = ("BLUEFOG_", "JAX_", "XLA_")


def _supervise_elastic(procs, spawn, base_inc: int, budget: int,
                       min_world: int) -> List[int]:
    """Elastic child supervision (`bfrun --elastic`).

    A crashed rank is respawned in place with ``BLUEFOG_INCARNATION``
    bumped — the control plane then fences the crash's zombie connections
    and the rank rejoins through quarantined state transfer
    (docs/fault_tolerance.md, "Rejoin & fencing"). Respawns back off
    exponentially (0.5 s doubling, capped at 10 s) and are bounded by
    ``budget`` per rank. A terminal failure propagates only when a rank's
    budget is exhausted (its code lands in the returned list; the job
    keeps running for the survivors) or the surviving world would drop
    below ``min_world`` (the whole job is torn down). Returns per-rank
    terminal exit codes (None for ranks still running at a min-world
    teardown — the caller's cleanup terminates and aggregates them).
    """
    total = len(procs)
    restarts = [0] * total
    incs = [base_inc] * total
    final: List = [None] * total     # terminal exit code per rank
    respawn_at = [0.0] * total       # backoff deadline for pending respawns
    pending = set()
    while True:
        now = time.time()
        for i in range(total):
            if final[i] is not None:
                continue
            if i in pending:
                if now >= respawn_at[i]:
                    pending.discard(i)
                    incs[i] += 1
                    procs[i] = spawn(i, incs[i])
                continue
            c = procs[i].poll()
            if c is None:
                continue
            if c == 0:
                final[i] = 0
            elif restarts[i] < budget:
                restarts[i] += 1
                delay = min(0.5 * (2 ** (restarts[i] - 1)), 10.0)
                print(
                    f"bfrun: rank {i} exited with {c}; respawning as "
                    f"incarnation {incs[i] + 1} in {delay:.1f}s "
                    f"(restart {restarts[i]}/{budget})", file=sys.stderr)
                respawn_at[i] = now + delay
                pending.add(i)
            else:
                final[i] = c
                print(
                    f"bfrun: rank {i} exited with {c} and exhausted its "
                    f"restart budget ({budget}); marking it failed",
                    file=sys.stderr)
        failed = sum(1 for c in final if c not in (None, 0))
        if failed and total - failed < min_world:
            print(
                f"bfrun: surviving world {total - failed} dropped below "
                f"--min-world {min_world}; terminating the job",
                file=sys.stderr)
            return [c for c in final if c is not None]
        if all(c is not None for c in final):
            return final
        time.sleep(0.1)


def _spawn_shard_servers(n: int, total: int, advertise_host: str):
    """Launch N control-plane shard server processes on the driver host
    (``bfrun --cp-shards N``); returns (procs, BLUEFOG_CP_HOSTS value).
    Blocks until every shard prints its READY line so children can never
    race a bind; server processes inherit the freshly minted job secret
    through the environment.

    With ``BLUEFOG_CP_REPLICATION`` (default on) and N > 1 the spawn is
    two-phase: every shard reports its bound port first, the full ring is
    written back over stdin, and each shard wires WAL replication to its
    ring successor before declaring READY — an acked control-plane write
    then survives any single shard's SIGKILL."""
    from .runtime.config import knob_env

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "runtime", "shard_server.py")
    replicate = n > 1 and bool(int(knob_env("BLUEFOG_CP_REPLICATION")))
    procs = []

    def _fail(i, why):
        for q in procs:
            q.terminate()
        raise RuntimeError(f"control-plane shard {i} failed to start: {why}")

    for i in range(n):
        cmd = [sys.executable, script, "--port", "0", "--world", str(total),
               "--shard", str(i)]
        if replicate:
            cmd.append("--expect-peers")
        procs.append(subprocess.Popen(
            cmd, stdout=subprocess.PIPE,
            stdin=subprocess.PIPE if replicate else None, text=True))
    ports = []
    marker = "BF_SHARD_PORT" if replicate else "BF_SHARD_READY"
    for i, p in enumerate(procs):
        line = p.stdout.readline()
        if not line.startswith(marker):
            _fail(i, repr(line))
        ports.append(int(line.split()[1]))
    if replicate:
        ring = ",".join(f"127.0.0.1:{port}" for port in ports)
        for i, p in enumerate(procs):
            p.stdin.write(f"BF_SHARD_PEERS {ring}\n")
            p.stdin.flush()
        for i, p in enumerate(procs):
            line = p.stdout.readline()
            if not line.startswith("BF_SHARD_READY"):
                _fail(i, repr(line))
    eps = [f"{advertise_host}:{port}" for port in ports]
    return procs, ",".join(eps)


def _stop_shard_servers(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=5)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()


def _fanout(args) -> int:
    """Drive the whole job from this one shell: launch every process, stream
    its output, aggregate exit codes, kill-all on Ctrl-C or first failure."""
    entries = parse_hosts(args.hosts, args.hostfile)
    if not entries:
        print("bfrun: empty host list", file=sys.stderr)
        return 1
    total = sum(s for _, s in entries)
    if args.num_proc is not None and args.num_proc != total:
        print(f"bfrun: -np {args.num_proc} does not match the {total} slots "
              f"in the host list", file=sys.stderr)
        return 1

    remote_hosts = sorted({h for h, _ in entries if not _is_local(h)})
    if remote_hosts:
        # concurrent probes: a slow/down host costs one timeout, not one per
        # host (the reference driver also probes in parallel)
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=min(32, len(remote_hosts))) as ex:
            ok = list(ex.map(lambda h: _check_ssh(h, args.ssh_port),
                             remote_hosts))
        unreachable = [h for h, good in zip(remote_hosts, ok) if not good]
        if unreachable:
            print(f"bfrun: ssh unreachable host(s): {', '.join(unreachable)}",
                  file=sys.stderr)
            return 1

    # One shared secret per job, minted here and distributed over the
    # launcher's env channel (local children inherit os.environ; remote
    # commands forward every BLUEFOG_* var): the control-plane server then
    # rejects any connection that cannot complete the HMAC handshake —
    # without this, window tensors and mutexes are writable by anything
    # that can reach the port (reference: HMAC-signed driver/task
    # messages, run/horovodrun/common/util/network.py:69-86).
    if "BLUEFOG_CP_SECRET" not in os.environ:
        import secrets as _secrets
        os.environ["BLUEFOG_CP_SECRET"] = _secrets.token_hex(16)

    coordinator = args.coordinator
    if coordinator is None:
        first = entries[0][0]
        if _is_local(first):
            # remote children must be able to route to process 0: advertise
            # a real hostname, loopback only for all-local jobs
            chost = socket.getfqdn() if remote_hosts else "127.0.0.1"
        else:
            chost = first
        # the port is probed free on THIS machine; when process 0 runs
        # remotely that is only a likely-free ephemeral pick — pass an
        # explicit --coordinator if the bind fails there
        coordinator = f"{chost}:{_free_port()}"

    # Sharded control plane: the driver owns N real shard server processes
    # and every child (local and remote — BLUEFOG_* env is forwarded)
    # routes over them instead of rank 0 serving in-process.
    shard_procs: List[subprocess.Popen] = []
    if args.cp_shards and args.cp_shards > 1:
        shost = socket.getfqdn() if remote_hosts else "127.0.0.1"
        try:
            shard_procs, cp_hosts = _spawn_shard_servers(
                args.cp_shards, total, shost)
        except (RuntimeError, OSError, ValueError) as exc:
            print(f"bfrun: {exc}", file=sys.stderr)
            return 1
        os.environ["BLUEFOG_CP_HOSTS"] = cp_hosts
        os.environ["BLUEFOG_CP_SERVE"] = "0"
        print(f"bfrun: control plane sharded over {args.cp_shards} "
              f"server(s): {cp_hosts}", file=sys.stderr)

    def child_args(pid: int) -> List[str]:
        out = ["-m", "bluefog_tpu.launcher", "-np", str(total),
               "--coordinator", coordinator, "--process-id", str(pid)]
        if args.simulate:
            out += ["--simulate", str(args.simulate)]
        if args.timeline_filename:
            out += ["--timeline-filename", args.timeline_filename]
        if args.verbose:
            out += ["--verbose"]
        if args.chaos:
            out += ["--chaos", args.chaos]
        return out + ["--"] + args.command

    # slot index -> host (stable across respawns in elastic mode)
    slot_host = [h for h, s in entries for _ in range(s)]
    base_inc = 0
    try:
        base_inc = max(0, int(os.environ.get("BLUEFOG_INCARNATION", "0")
                              or 0))
    except ValueError:
        pass

    def spawn(pid: int, inc: int) -> subprocess.Popen:
        host = slot_host[pid]
        if _is_local(host):
            env = dict(os.environ)
            env["BLUEFOG_INCARNATION"] = str(inc)
            return subprocess.Popen([sys.executable] + child_args(pid),
                                    env=env)
        # NEVER put the job secret on the remote command line —
        # /proc/<pid>/cmdline is world-readable, so any local
        # user on a shared node could read it and pass the HMAC
        # handshake. It travels over ssh stdin instead (echo
        # off: -tt allocates a pty that would otherwise echo
        # the line into captured output).
        exports = " ".join(
            f"{k}={shlex.quote(v)}"
            for k, v in os.environ.items()
            if (k.startswith(_FORWARD_ENV_PREFIXES)
                or k == "PYTHONPATH")
            and k not in ("BLUEFOG_CP_SECRET", "BLUEFOG_INCARNATION"))
        exports += f" BLUEFOG_INCARNATION={inc}"
        secret = os.environ.get("BLUEFOG_CP_SECRET", "")
        # '&&' so a missing remote workdir fails loudly instead
        # of becoming an opaque ModuleNotFoundError later.
        # The ready marker closes a race: until the remote stty
        # runs, the pty's ECHO flag is still on, so a secret
        # written at Popen time could be echoed back into the
        # launcher's captured output. Write it only after the
        # remote confirms echo is off.
        remote = ("stty -echo 2>/dev/null; "
                  f"printf '{_SECRET_READY}\\n'; "
                  "IFS= read -r BLUEFOG_CP_SECRET; "
                  "export BLUEFOG_CP_SECRET; "
                  f"cd {shlex.quote(os.getcwd())} && "
                  f"env {exports} {args.remote_python} "
                  + shlex.join(child_args(pid)))
        # -tt: a pty ties the remote process to the connection,
        # so kill-all on the ssh client actually kills the job
        # on the host (and forwards Ctrl-C)
        p = subprocess.Popen(
            ["ssh", "-tt", "-o", "BatchMode=yes",
             "-p", str(args.ssh_port), host, remote],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE)
        threading.Thread(
            target=_send_secret_when_ready,
            args=(p, secret, host), daemon=True).start()
        return p

    procs: List[subprocess.Popen] = []
    try:
        try:
            for pid in range(total):
                procs.append(spawn(pid, base_inc))

            if args.elastic is not None:
                own_exit = _supervise_elastic(
                    procs, spawn, base_inc, max(0, args.elastic),
                    max(1, args.min_world))
            else:
                # first failure kills the job (mpirun semantics); else
                # wait all
                while True:
                    codes = [p.poll() for p in procs]
                    failed = [c for c in codes if c not in (None, 0)]
                    if failed or all(c is not None for c in codes):
                        break
                    time.sleep(0.1)
                # codes at loop exit are authoritative: processes still
                # running get terminated below, and their -SIGTERM must
                # not mask the real failure
                own_exit = [c for c in codes if c is not None]
        except KeyboardInterrupt:
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGINT)
            deadline = time.time() + 5
            for p in procs:
                try:
                    p.wait(timeout=max(0.1, deadline - time.time()))
                except subprocess.TimeoutExpired:
                    p.kill()
            return 130
        for p in procs:
            if p.poll() is None:
                p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        rc = 0
        for c in own_exit:
            if c != 0:
                rc = c if c > 0 else 128 + abs(c)  # signal deaths,
                break                              # shell-style
        return rc
    finally:
        _stop_shard_servers(shard_procs)


def _cp_address(args, what: str):
    """Resolve the control-plane endpoint list for --status/--dump: --cp
    wins (``HOST:PORT[,HOST:PORT...]`` — a sharded job names every shard),
    then BLUEFOG_CP_HOSTS, then BLUEFOG_CP_HOST/PORT, then the jax
    coordinator + 17 convention. Returns [(host, port)] or None after
    printing the error."""
    from .runtime.router import parse_endpoints

    spec = args.cp or os.environ.get("BLUEFOG_CP_HOSTS")
    if spec:
        try:
            eps = parse_endpoints(spec)
        except ValueError as exc:
            print(f"bfrun {what}: {exc}", file=sys.stderr)
            return None
        if eps:
            return eps
    host = os.environ.get("BLUEFOG_CP_HOST")
    port = int(os.environ["BLUEFOG_CP_PORT"]) \
        if os.environ.get("BLUEFOG_CP_PORT") else None
    if host is None or port is None:
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord and ":" in coord:
            chost, _, cport = coord.partition(":")
            host = host or chost
            port = port or int(cport) + 17
    if not host or not port:
        print(f"bfrun {what}: control-plane address unknown; pass "
              "--cp HOST:PORT[,HOST:PORT...] or set "
              "BLUEFOG_CP_HOST/BLUEFOG_CP_PORT (or BLUEFOG_CP_HOSTS)",
              file=sys.stderr)
        return None
    return [(host, port)]


def _raw_client(endpoints, what: str):
    """A raw read-only attachment for --status/--dump: a plain client for
    one endpoint, a LENIENT ShardRouter for several (a dead shard is
    reported by name in the output instead of failing the probe)."""
    from .runtime.native import ControlPlaneClient
    from .runtime.router import ShardRouter

    secret = os.environ.get("BLUEFOG_CP_SECRET", "")
    try:
        if len(endpoints) == 1:
            host, port = endpoints[0]
            return ControlPlaneClient(host, port, 0, secret=secret,
                                      streams=1)
        return ShardRouter(endpoints, 0, secret=secret, streams=1,
                           lenient=True)
    except (OSError, RuntimeError) as exc:
        names = ",".join(f"{h}:{p}" for h, p in endpoints)
        print(f"bfrun {what}: cannot reach the control plane at "
              f"{names} ({exc})", file=sys.stderr)
        return None


def _report_dead_shards(cl, what: str) -> list:
    """Print (never raise) the router's dead-shard view; [] for a plain
    single-endpoint client."""
    if not hasattr(cl, "dead_shard_endpoints"):
        return []
    dead = cl.dead_shard_endpoints()
    for name in dead:
        print(f"bfrun {what}: control-plane shard {name} is DEAD "
              "(its keyspace failed over; routed state there is lost)",
              file=sys.stderr)
    return dead


def _strict_findings(health: dict) -> List[str]:
    """Health findings that make ``--status --strict`` exit non-zero."""
    findings: List[str] = []
    dead = sorted(p for p, r in health["ranks"].items() if not r["alive"])
    if dead:
        findings.append(f"stale/dead rank(s): {dead}")
    if health["stragglers"]:
        findings.append(f"straggler(s): {health['stragglers']}")
    m = health.get("mass")
    if m is not None and not m["conserved"]:
        findings.append(
            f"push-sum mass drift {m['drift']:.3g} exceeds tolerance "
            f"{m['tolerance']:.3g}")
    repl = health.get("repl")
    if repl is not None and repl["under_replicated"]:
        findings.append(
            f"{repl['under_replicated']} control-plane shard(s) "
            "under-replicated (heartbeat-published cp.under_replicated "
            "gauge)")
    return findings


def _shard_drift_findings(cl, world: int) -> List[str]:
    """SUSTAINED shard-rotation drift per rank, from the streamed
    ``win.shard_stale_drops.rate`` series (a lone historical drop is not
    a finding; three consecutive positive rate samples are — a
    controller's comm-round counter desynced and every one of its
    deposits is being discarded; docs/sharded_windows.md)."""
    from .runtime import timeseries as _ts

    findings: List[str] = []
    acc = _ts.HistoryAccumulator()
    for r in range(world):
        doc = _ts.read_rank(cl, r)
        if doc:
            acc.update(r, doc)
    for r in range(world):
        vals = acc.values(r, "win.shard_stale_drops.rate", last=8)
        tail = [v for v in vals[-3:]]
        if len(tail) >= 3 and all(v > 0 for v in tail):
            findings.append(
                f"rank {r}: sustained shard-rotation drift "
                f"({tail[-1]:.2f} stale drops/s across the last "
                f"{len(tail)} samples)")
    return findings


def _slo_budget_findings(cl) -> List[str]:
    """Exhausted serving error budgets for ``--status --strict``: any
    live serve client whose published ``slo.budget.<kind>`` gauge is at
    or below zero has burned its whole window budget (docs/slo.md)."""
    from .runtime import timeseries as _ts
    from .serving import snapshot as _snap

    findings: List[str] = []
    try:
        cids = _snap.live_client_ids(cl)
    except (OSError, RuntimeError):
        return findings
    acc = _ts.HistoryAccumulator()
    for cid in cids:
        r = _ts.SERVE_TS_RANK_BASE + cid
        doc = _ts.read_rank(cl, r)
        if doc is None:
            continue
        acc.update(r, doc)
        for (rank, name) in sorted(acc.series):
            if rank != r or not name.startswith("slo.budget."):
                continue
            v = acc.latest(r, name)
            if v is not None and v <= 0.0:
                kind = name[len("slo.budget."):]
                findings.append(
                    f"serve client {cid}: {kind} SLO error budget "
                    f"exhausted ({v * 100:.1f}% remaining over the slow "
                    "burn window — docs/slo.md)")
    return findings


def _status(args) -> int:
    """``bfrun --status``: the cluster-health view from outside the job.

    Reads the packed per-rank snapshots the controllers publish under
    ``bf.metrics.<rank>`` (runtime/metrics.py) over a plain control-plane
    connection — no jax mesh, no membership registration, no job
    interference (scalar gets only). ``--strict`` turns findings into a
    non-zero exit (2) for CI/operator scripting; the default exit stays 0
    so dashboards polling a degraded job never mistake findings for a
    broken probe."""
    addr = _cp_address(args, "--status")
    if addr is None:
        return 1
    from .runtime import metrics as _metrics

    cl = _raw_client(addr, what="--status")
    if cl is None:
        return 1
    try:
        health = _metrics.read_cluster_health(cl)
        print(_metrics.format_health(health))
        if not health["ranks"]:
            print("  (no rank has published metrics — is "
                  "BLUEFOG_METRICS_INTERVAL set on the job?)")
        dead_shards = []
        under_replicated = []
        below_quorum = []
        if hasattr(cl, "server_stats_all"):
            # sharded plane: merge the per-shard server views; a dead
            # shard is a named row, never a raised probe failure
            print(f"  control-plane shards ({cl.shard_count}):")
            for name, st in cl.server_stats_all():
                if st is None:
                    print(f"    {name}: DEAD")
                    dead_shards.append(name)
                else:
                    repl = {0: "off", 1: "live", 2: "DEGRADED"}.get(
                        st.get("repl_status", 0), "?")
                    lag = st.get("wal_enqueued", 0) - st.get("wal_acked", 0)
                    # quorum replication (r20): replicas = this shard's
                    # copy count (itself + live successor streams);
                    # quorum=LOST marks a shard serving read-only behind
                    # the typed QuorumLostError gate
                    quorum = {0: "n/a", 1: "held", 2: "LOST"}.get(
                        st.get("quorum_state", 0), "?")
                    replicas = 1 + int(st.get("repl_targets_live", 0))
                    print(f"    {name}: conns={st['live_connections']} "
                          f"kv={st['kv_entries']} "
                          f"mailbox={st['mailbox_records']} recs/"
                          f"{st['mailbox_bytes']} B "
                          f"locks={st['locks_held']} "
                          f"stale_rejects={st['stale_rejects']} "
                          f"repl={repl} wal_lag={lag} "
                          f"wal_dropped={st.get('wal_dropped', 0)} "
                          f"replicas={replicas} quorum={quorum} "
                          f"quorum_acks={st.get('quorum_acks', 0)} "
                          f"replica_sources="
                          f"{st.get('replica_sources', 0)} "
                          f"partition_rejects="
                          f"{st.get('partition_rejects', 0)}")
                    if st.get("repl_status", 0) == 2:
                        # successor lagging/absent: this shard is serving
                        # acked writes that live NOWHERE else
                        under_replicated.append(name)
                    if st.get("quorum_state", 0) == 2:
                        below_quorum.append(name)
        serve_lines, serve_st = _serve_status_lines(cl)
        for line in serve_lines:
            print(line)
        if getattr(args, "strict", False):
            from .runtime.config import knob_env

            findings = _strict_findings(health)
            findings.extend(
                _shard_drift_findings(cl, health["world"]))
            findings.extend(_slo_budget_findings(cl))
            if serve_st is not None:
                lag = serve_st.get("publish_lag_s")
                stale_s = float(knob_env("BLUEFOG_SERVE_STALE_S"))
                if lag is not None and lag > stale_s:
                    findings.append(
                        f"stale serving snapshot: v{serve_st['version']} "
                        f"published {lag:.1f} s ago (threshold "
                        f"BLUEFOG_SERVE_STALE_S={stale_s:g} s — the "
                        "publisher hook stopped or the trainer is down)")
            if dead_shards:
                findings.append(
                    f"dead control-plane shard(s): {dead_shards}")
            if under_replicated:
                findings.append(
                    "under-replicated control-plane shard(s) (WAL "
                    f"degraded, successor lagging or absent): "
                    f"{under_replicated}")
            if below_quorum:
                # an UNHEALED partition shows up exactly here: every
                # shard the cut isolated from its commit quorum stays in
                # quorum=LOST until the cut heals (a healed one leaves
                # only the cp.partitions counter trail, which is history,
                # not a finding)
                findings.append(
                    "control-plane shard(s) below commit quorum — "
                    "unhealed partition or too many replica deaths "
                    "(mutating ops rejected with QuorumLostError): "
                    f"{below_quorum}")
            if findings:
                for f in findings:
                    print(f"  STRICT: {f}", file=sys.stderr)
                return 2
    finally:
        cl.close()
    return 0


def _serve_status_lines(cl) -> Tuple[List[str], Optional[dict]]:
    """The serving-plane rows for ``--status`` (empty when the job never
    published a snapshot — serving is opt-in via
    BLUEFOG_SERVE_PUBLISH_EVERY)."""
    from .serving.snapshot import read_serve_status

    try:
        st = read_serve_status(cl)
    except (OSError, RuntimeError):
        return [], None
    if not st:
        return [], None
    lag = st.get("publish_lag_s")
    lag_txt = f"published {lag:.1f} s ago" if lag is not None \
        else "publish time unknown"
    lines = [
        "  serving plane (docs/serving.md):",
        f"    snapshot v{st['version']} (step {st['pub_step']}), "
        f"{lag_txt}, {st['shards']} stripe(s), "
        f"gc floor v{st['gc_floor']}",
        f"    serve clients: {st['clients_live']}/{st['clients_total']} "
        "heartbeating",
    ]
    return lines, st


def _serve(args) -> int:
    """``bfrun --serve``: attach a read-only serving client from OUTSIDE
    the job (docs/serving.md).

    Like --status this is a raw control-plane attachment — no jax, no
    mesh join, no membership registration — so it runs on an inference
    host that shares nothing with the trainer but the control-plane
    address. The client pulls the committed snapshot, hot-swaps on every
    fence bump, and prints one line per swap; --serve-model MODULE:FN
    additionally serves batched inference behind the admission gate."""
    addr = _cp_address(args, "--serve")
    if addr is None:
        return 1
    model_fn = None
    if args.serve_model:
        import importlib

        mod_name, _, fn_name = args.serve_model.partition(":")
        fn_name = fn_name or "model_fn"
        try:
            model_fn = getattr(importlib.import_module(mod_name), fn_name)
        except (ImportError, AttributeError) as exc:
            print(f"bfrun --serve: cannot load --serve-model "
                  f"{args.serve_model!r} ({exc})", file=sys.stderr)
            return 1
    from .serving.client import ServeClient

    sc = ServeClient(addr, model_fn,
                     secret=os.environ.get("BLUEFOG_CP_SECRET", ""))
    try:
        if not sc.wait_ready(timeout=args.serve_timeout):
            st = sc.stats()
            print(f"bfrun --serve: no complete snapshot within "
                  f"{args.serve_timeout:g} s "
                  f"({st['pull_failures']} pull failure(s)) — is the "
                  "trainer publishing (BLUEFOG_SERVE_PUBLISH_EVERY)?",
                  file=sys.stderr)
            return 1
        last = 0
        while True:
            ver = sc.version()
            if ver > last:
                last = ver
                st = sc.stats()
                lag = st.get("publish_lag_s")
                lag_txt = f"{lag:.1f}" if lag is not None else "?"
                print(f"bfrun --serve: snapshot v{ver} "
                      f"({st['wire_bytes'] / 1e6:.1f} MB wire total, "
                      f"{st.get('pull_mbps', 0.0):.0f} MB/s, "
                      f"publish lag {lag_txt} s, "
                      f"{st['swaps']} swap(s))", flush=True)
                if args.once:
                    return 0
            time.sleep(0.2)
    except KeyboardInterrupt:
        return 0
    finally:
        sc.close()


def _discover_world(cl) -> int:
    """World size for the external consumers: the published hint, the
    env, then a heartbeat-key scan (the --dump convention)."""
    world = 0
    try:
        world = int(cl.get("bf.metrics.world"))
    except (OSError, RuntimeError):
        pass
    if world <= 0:
        try:
            world = int(os.environ.get("BLUEFOG_CP_WORLD") or 0)
        except ValueError:
            world = 0
    if world <= 0:
        world = 1
        for r in range(256):
            try:
                if int(cl.get(f"bf.hb.{r}")) == 0 and r > 0:
                    break
            except (OSError, RuntimeError):
                break
            world = r + 1
    return world


def _format_tune_section(cl, world: int) -> str:
    """Render the self-tuner decision trail (``bf.tune.<rank>``) for the
    ``--top`` frame: active per-edge codec levels, demoted ranks, and
    the most recent decisions across the fleet. Empty string when no
    rank has published (BLUEFOG_TUNE off — the common case)."""
    import json as _json

    from .runtime import tuner as _tuner

    levels: dict = {}
    demoted: dict = {}
    recent: list = []
    for r in range(world):
        try:
            blob = cl.get_bytes(_tuner.TUNE_KEY_FMT.format(rank=r))
        except (OSError, RuntimeError):
            continue
        if not blob:
            continue
        try:
            doc = _json.loads(bytes(blob).decode())
        except (ValueError, UnicodeDecodeError):
            continue
        levels.update(doc.get("levels") or {})
        demoted.update(doc.get("demoted") or {})
        for d in doc.get("decisions") or []:
            recent.append((d.get("t", 0.0), r, d))
    if not levels and not demoted and not recent:
        return ""
    lines = ["  SELF-TUNER (docs/self_tuning.md)"]
    if levels:
        terms = ", ".join(f"{e}={c}" for e, c in sorted(levels.items()))
        lines.append(f"    edge codecs: {terms}")
    if demoted:
        terms = ", ".join(
            f"rank {p} (-{len(v)} in-edges)"
            for p, v in sorted(demoted.items(), key=lambda kv: int(kv[0])))
        lines.append(f"    demoted: {terms}")
    for t, r, d in sorted(recent, key=lambda x: (x[0], x[1]),
                          reverse=True)[:5]:
        tgt = d.get("target")
        if isinstance(tgt, list):
            tgt = f"{tgt[0]}>{tgt[1]}"
        lines.append(
            f"    [{d.get('status', '?'):>8}] r{r} {d.get('lever')} "
            f"{d.get('action')} {tgt} {d.get('arg') or ''} "
            f"— {d.get('reason', '')}")
    return "\n".join(lines)


def _format_slo_section(acc, cids) -> str:
    """Render the serving SLO view for the ``--top`` frame: per-client
    error-budget gauges, fast/slow burn rates, and per-phase request
    latency percentiles from the serve clients' published streams
    (``bf.ts.<SERVE_TS_RANK_BASE + cid>``). Empty string when no client
    declared SLOs or enabled tracing (BLUEFOG_SLO/BLUEFOG_TRACE_SERVE
    unset — the common case)."""
    from .runtime import flight as _flight
    from .runtime import timeseries as _ts

    lines: List[str] = []
    for cid in cids:
        r = _ts.SERVE_TS_RANK_BASE + cid
        budgets = sorted(
            name for (rank, name) in acc.series
            if rank == r and name.startswith("slo.budget."))
        p50 = acc.latest(r, "slo.request_p50_us")
        p99 = acc.latest(r, "slo.request_p99_us")
        if not budgets and p99 is None:
            continue
        active = {a.get("name") for a in acc.alerts.get(r, [])
                  if str(a.get("name", "")).startswith("slo.")}
        rate = acc.latest(r, "slo.requests.rate")
        shed = acc.latest(r, "slo.shed.rate")
        head = f"    client {cid}:"
        if rate is not None:
            head += f" {rate:.1f} req/s"
            if shed:
                head += f" ({shed:.1f} shed/s)"
        if p99 is not None:
            head += (f" | req p50/p99 {p50 or 0:.0f}/{p99:.0f} us")
        stale = acc.latest(r, "slo.staleness_p99_ver")
        if stale is not None:
            head += f" | staleness p99 {stale:.0f} ver"
        lines.append(head)
        for name in budgets:
            kind = name[len("slo.budget."):]
            budget = acc.latest(r, name)
            fast = acc.latest(r, f"slo.burn.{kind}.fast") or 0.0
            slow = acc.latest(r, f"slo.burn.{kind}.slow") or 0.0
            if budget is None:
                continue
            if budget <= 0.0:
                flag = "EXHAUSTED"
            elif f"slo.{kind}" in active:
                flag = "BURNING"
            else:
                flag = "ok"
            lines.append(
                f"      {kind}: budget {budget * 100:6.1f}%  "
                f"burn {fast:.2f}x fast / {slow:.2f}x slow  [{flag}]")
        phases = []
        for p in _flight.SERVE_PHASES:
            pp50 = acc.latest(r, f"slo.phase.{p}.p50_us")
            pp99 = acc.latest(r, f"slo.phase.{p}.p99_us")
            if pp99 is not None:
                phases.append(f"{p} {pp50 or 0:.0f}/{pp99:.0f}")
        if phases:
            lines.append("      phases p50/p99 us: " + "  ".join(phases))
    if not lines:
        return ""
    return "\n".join(["  SERVING SLO (docs/slo.md)"] + lines)


def _format_quorum_section(cl, episodes: dict) -> str:
    """The ``--top`` QUORUM line (r20 durability plane): per-shard
    commit-quorum state from ``server_stats_all`` with partition-episode
    start/heal wall-clock timestamps tracked across frames in
    ``episodes`` (shard name -> mutable record). Empty string when the
    plane is unsharded or replication is off (quorum n/a everywhere)."""
    if not hasattr(cl, "server_stats_all"):
        return ""
    try:
        stats = list(cl.server_stats_all())
    except (OSError, RuntimeError):
        return ""
    now = time.time()

    def _hms(t):
        return time.strftime("%H:%M:%S", time.localtime(t))

    held = lost = 0
    terms: List[str] = []
    for name, st in stats:
        ep = episodes.setdefault(
            name, {"state": 0, "since": None, "last": None, "count": 0})
        q = 0 if st is None else int(st.get("quorum_state", 0))
        if q == 2 and ep["state"] != 2:
            ep["since"] = now
            ep["count"] += 1
        elif q != 2 and ep["state"] == 2 and ep["since"] is not None:
            ep["last"] = (ep["since"], now)
            ep["since"] = None
        ep["state"] = q
        if q == 1:
            held += 1
        elif q == 2:
            lost += 1
            rejects = int(st.get("partition_rejects", 0)) if st else 0
            since = _hms(ep["since"]) if ep["since"] else "?"
            terms.append(f"{name}: LOST since {since} "
                         f"({rejects} partition reject(s))")
        if q != 2 and ep["last"] is not None:
            t0, t1 = ep["last"]
            terms.append(f"{name}: healed {_hms(t0)}->{_hms(t1)}")
    if held + lost == 0:
        return ""  # replication off: no quorum plane to report
    line = f"  QUORUM: {held}/{held + lost} shard(s) held"
    if terms:
        line += " | " + " | ".join(terms)
    return line


def _top(args) -> int:
    """``bfrun --top``: the live cluster dashboard.

    Polls every rank's ``bf.ts.<rank>`` delta stream over a raw
    control-plane client (the ``--status`` pattern: no jax, no mesh
    join, scalar/bytes gets only) and renders the merged view — per-rank
    convergence table with sparklines, active alerts, silent-rank
    detection, and the per-edge bytes/s + transit matrix assembled from
    cross-rank flow matching. ``--once`` renders a single plain frame;
    otherwise the screen refreshes in place every ``--interval``
    seconds until Ctrl-C."""
    import time as _time

    addr = _cp_address(args, "--top")
    if addr is None:
        return 1
    from .runtime import timeseries as _ts

    cl = _raw_client(addr, what="--top")
    if cl is None:
        return 1
    acc = _ts.HistoryAccumulator()
    quorum_eps: dict = {}
    try:
        while True:
            world = args.world or _discover_world(cl)
            for r in range(world):
                doc = _ts.read_rank(cl, r)
                if doc is not None:
                    acc.update(r, doc)
            frame = _ts.format_top(acc, world)
            tune = _format_tune_section(cl, world)
            if tune:
                frame += "\n" + tune
            from .serving import snapshot as _snap
            try:
                cids = _snap.live_client_ids(cl)
            except (OSError, RuntimeError):
                cids = []
            for cid in cids:
                doc = _ts.read_rank(cl, _ts.SERVE_TS_RANK_BASE + cid)
                if doc is not None:
                    acc.update(_ts.SERVE_TS_RANK_BASE + cid, doc)
            slo = _format_slo_section(acc, cids)
            if slo:
                frame += "\n" + slo
            quorum = _format_quorum_section(cl, quorum_eps)
            if quorum:
                frame += "\n" + quorum
            dead = _report_dead_shards(cl, "--top") \
                if hasattr(cl, "dead_shard_endpoints") else []
            if dead:
                frame += f"\n  DEAD control-plane shard(s): {dead}"
            if args.once:
                print(frame)
                return 0
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            _time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        cl.close()


def _dump(args) -> int:
    """``bfrun --dump``: cluster-wide flight-recorder retrieval.

    Bumps the ``bf.flight.trigger`` KV counter; every rank's
    heartbeat/watchdog tick sees it, dumps locally, and publishes its
    packed ring tail under ``bf.flight.<rank>``. This side waits for the
    per-rank acks (bounded by --dump-timeout), pulls the tails over the
    same raw connection, and writes per-rank JSON dumps plus one merged,
    clock-synced chrome trace — postmortem evidence with no filesystem
    access to any worker."""
    import json
    import time as _time

    addr = _cp_address(args, "--dump")
    if addr is None:
        return 1
    from .runtime import flight as _flight

    cl = _raw_client(addr, what="--dump")
    if cl is None:
        return 1
    try:
        trig = int(cl.fetch_add(_flight.TRIGGER_KEY, 1)) + 1
        world = int(cl.get("bf.metrics.world")) or \
            int(os.environ.get("BLUEFOG_CP_WORLD") or 0)
        if world <= 0:
            # no world hint published: scan the heartbeat keys (multi-
            # controller) and fall back to a single-rank probe window
            world = 1
            for r in range(256):
                if int(cl.get(f"bf.hb.{r}")) == 0 and r > 0:
                    break
                world = r + 1
        print(f"bfrun --dump: trigger #{trig} set; waiting for "
              f"{world} rank(s) (timeout {args.dump_timeout:.0f}s)")
        deadline = _time.monotonic() + max(1.0, args.dump_timeout)
        acked: set = set()
        while _time.monotonic() < deadline and len(acked) < world:
            for r in range(world):
                if r not in acked and \
                        int(cl.get(_flight.ACK_KEY_FMT.format(rank=r))) \
                        >= trig:
                    acked.add(r)
            if len(acked) < world:
                _time.sleep(0.25)
        docs = []
        os.makedirs(args.out, exist_ok=True)
        for r in sorted(acked):
            try:
                blob = cl.get_bytes(_flight.DATA_KEY_FMT.format(rank=r))
                doc = _flight.unpack_dump(blob)
            except (OSError, ValueError) as exc:
                print(f"bfrun --dump: rank {r} tail unreadable ({exc})",
                      file=sys.stderr)
                continue
            path = os.path.join(args.out, f"flight_{r}.json")
            with open(path, "w") as f:
                json.dump(doc, f)
            n = len(doc.get("events", {}).get("kind", []))
            print(f"  rank {r}: {n} events "
                  f"(reason: {doc['meta'].get('reason')}) -> {path}")
            docs.append(doc)
        missing = sorted(set(range(world)) - acked)
        if missing:
            print(f"bfrun --dump: no ack from rank(s) {missing} — wedged "
                  "hard (no heartbeat/watchdog tick) or already gone",
                  file=sys.stderr)
        if not docs:
            print("bfrun --dump: no rank published a tail", file=sys.stderr)
            return 1
        merged = _flight.merge_dumps(docs)
        mpath = os.path.join(args.out, "merged.json")
        with open(mpath, "w") as f:
            json.dump(merged, f)
        flows = sum(1 for e in merged if e.get("ph") in ("s", "f"))
        print(f"  merged: {len(merged)} events ({flows} flow events) -> "
              f"{mpath}")
        _report_dead_shards(cl, "--dump")
    finally:
        cl.close()
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.status:
        return _status(args)
    if args.top:
        return _top(args)
    if args.dump:
        return _dump(args)
    if args.serve:
        return _serve(args)
    if not args.command:
        build_parser().print_usage()
        return 1
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]

    # driver mode: a host list without an explicit --process-id means THIS
    # invocation fans out the whole job (children re-enter below with ids)
    if (args.hosts or args.hostfile) and args.process_id is None:
        return _fanout(args)

    env = dict(os.environ)
    if args.cp_shards and args.cp_shards > 1:
        # exec mode: rank 0's bf.init serves all N shards in-process
        # (driver mode above launches real server processes instead)
        env["BLUEFOG_CP_SHARDS"] = str(args.cp_shards)
    if args.timeline_filename:
        env["BLUEFOG_TIMELINE"] = args.timeline_filename
    if args.verbose:
        env["BLUEFOG_LOG_LEVEL"] = "debug"
    if args.chaos:
        # validate NOW so a typo'd spec fails the launch, not (silently,
        # as a warning) deep inside every child's native-runtime load
        from .runtime.native import parse_fault_spec
        parse_fault_spec(args.chaos)
        env["BLUEFOG_CP_FAULT"] = args.chaos
    if args.simulate:
        # Respect an explicit operator pin (JAX_PLATFORMS=cpu keeps a dev
        # box off a flaky accelerator tunnel: an unset value makes every
        # simulated child re-probe the TPU plugin, a multi-minute timeout
        # when the tunnel is down). Default stays "" — the CPU mesh can
        # coexist with a working default accelerator backend.
        if not env.get("JAX_PLATFORMS"):
            env["JAX_PLATFORMS"] = ""
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.simulate}"
        )
        env["BLUEFOG_SIMULATE_DEVICES"] = str(args.simulate)
    if args.num_proc and args.num_proc > 1:
        if not args.coordinator or args.process_id is None:
            print("bfrun: -np > 1 requires --coordinator and --process-id",
                  file=sys.stderr)
            return 1
        env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
        env["JAX_NUM_PROCESSES"] = str(args.num_proc)
        env["JAX_PROCESS_ID"] = str(args.process_id)

    cmd = args.command
    os.execvpe(cmd[0], cmd, env)


if __name__ == "__main__":
    sys.exit(main())
