"""Host runtime: state, config, handles, timeline, watchdog."""

from . import config, handles, logging, state, timeline, watchdog
