"""Client-side shard router for the sharded control plane.

One :class:`ControlPlaneServer` is both the throughput bottleneck and the
single point of failure at production scale. The sharded deployment runs N
independent server processes (``bfrun --cp-shards N`` /
``python -m bluefog_tpu.runtime.shard_server``) and every client routes each
key to its owning shard with a pure, stable hash — the ``bf.metrics.<rank>``,
``bf.q.<rank>.<inc>``, per-origin mailbox, and ``bf.flight.<rank>`` key
families already partition naturally, and a pure function of the key means
every client in the job agrees on the owner without any coordination.

:class:`ShardRouter` duck-types :class:`ControlPlaneClient` exactly — every
caller above (``ops/windows.py`` deposit/drain, heartbeats, metrics, flight
recorder) works unchanged — and adds two behaviors a single client cannot
have:

* **Replication** of the membership-critical scalar keys (the membership
  epoch, per-rank incarnation mirrors, quarantine phases, shutdown flags,
  and the control plane's own config/health keys). Writes fan out to EVERY
  live shard through the monotone ``put_max`` merge op (commutative +
  idempotent, so failover reordering can never regress a value) and reads
  take the max across live shards — a shard SIGKILL cannot lose membership
  state. Incarnation registration (``kAttach``) is inherently replicated:
  each per-shard connection registers with every shard, so every shard
  fences zombies independently.

* **Failover**: when a shard stops answering (its native client exhausted
  the r8 redial budget — the same path that survives transient drops), the
  router marks it dead, publishes a generation under
  ``bf.cp.shard_dead.<i>`` (odd = dead, even = rejoined) to the survivors
  so every other process converges on the same routing within a heartbeat
  interval, and re-routes the dead shard's keyspace to the next live shard
  on the ring. Each per-shard native client also carries its ring
  successor as a FAILOVER REDIRECT target: a call in flight when the
  shard dies redials the successor on the same client — same kSeqPre
  (cid, seq) identity — so on a WAL-replicated pair the successor replays
  the pre-recorded reply instead of double-applying (exactly-once across
  the failover boundary, including drained-haul replies: zero lost
  deposits).

* **Rejoin** (r16): a restarted shard server that caught up from its
  successor's snapshot + WAL publishes an EVEN generation under its
  ``bf.cp.shard_dead.<i>`` key; routers observe it on the next health
  poll, dial the endpoint fresh, and move the keyspace back. Redirected
  clients are replaced, never flipped back mid-stream.

With ``BLUEFOG_CP_REPLICATION=0`` (the r14 wire) the remaining caveats are
documented in docs/fault_tolerance.md: routed state on a killed shard is
lost with it and locks held there surface PeerLostError instead of
handing off.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from .config import knob_env
from .logging import logger
from .native import (ControlPlaneClient, PeerLostError,  # noqa: F401
                     QuorumLostError, StaleIncarnationError, _MultiReply)

# Scalar key families replicated on every shard (writes via put_max
# fan-out, reads as max over live shards). All are monotone by protocol:
# the epoch and incarnations only grow, quarantine phases go 1 -> 2 under
# per-(rank, incarnation) keys, shutdown flags/acks go 0 -> 1, and the
# bf.cp.* config/health keys (mailbox cap, shard-dead flags) are
# write-once / latching.
_REPL_EXACT = frozenset({"bf.membership.epoch"})
_REPL_PREFIX = ("bf.inc.", "bf.q.", "bf.shutdown.", "bf.cp.")

# Per-shard liveness GENERATION (monotone, merged with put_max): 0 = never
# died, odd = dead, even (> 0) = rejoined. A router declaring a death bumps
# an even value to the next odd one; a rejoined shard server publishes the
# next even value after its snapshot catch-up. Monotone merge keeps the
# transitions race-free under failover reordering (a late duplicate can
# never flip a rejoined shard back to dead).
_DEAD_FLAG = "bf.cp.shard_dead.{idx}"


def _gen_dead(gen: int) -> bool:
    return gen > 0 and gen % 2 == 1


# Published rejoin address (r19, lifting the r16 "must reuse its old
# host:port" limit): a shard server restarted SOMEWHERE ELSE publishes its
# new endpoint here, generation-stamped so the monotone put_max merge can
# never regress to a stale address. The key rides the ``bf.cp.`` replicated
# family, so any live shard can answer for it.
SHARD_ADDR_FMT = "bf.cp.shard_addr.{idx}"


def pack_shard_addr(gen: int, host: str, port: int) -> int:
    """``(gen << 48) | (ipv4 << 16) | port`` — monotone in the liveness
    generation, self-describing on decode. Hostname operands resolve to
    IPv4 here (the wire carries only the packed form)."""
    try:
        ip = struct.unpack("!I", socket.inet_aton(host))[0]
    except OSError:
        ip = struct.unpack(
            "!I", socket.inet_aton(socket.gethostbyname(host)))[0]
    return ((int(gen) & 0xFFFF) << 48) | (ip << 16) | (int(port) & 0xFFFF)


def unpack_shard_addr(value: int) -> Optional[Tuple[int, str, int]]:
    """Packed rejoin address -> (generation, host, port); None for the
    never-published (<= 0) value."""
    value = int(value)
    if value <= 0:
        return None
    host = socket.inet_ntoa(struct.pack("!I", (value >> 16) & 0xFFFFFFFF))
    return (value >> 48) & 0xFFFF, host, value & 0xFFFF

# Endpoints whose death was already ERROR-announced by THIS process: many
# routers (one per subsystem, hundreds in the soak) detect the same death
# within milliseconds, and one loud line per process is signal while N
# identical ones are noise. Guarded by the GIL (set.add is atomic enough
# for a log-dedup). _announced_alive dedups the matching REJOIN line per
# liveness generation.
_announced_dead: set = set()
_announced_alive: set = set()

_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3


def _fnv64(key: str) -> int:
    h = _FNV_OFFSET
    for b in key.encode():
        h ^= b
        h = (h * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def is_replicated_key(key: str) -> bool:
    return key in _REPL_EXACT or key.startswith(_REPL_PREFIX)


def parse_endpoints(spec: str) -> List[Tuple[str, int]]:
    """``host:port[,host:port...]`` -> [(host, port)] (BLUEFOG_CP_HOSTS /
    ``bfrun --cp`` grammar). Raises ValueError on a malformed entry."""
    out: List[Tuple[str, int]] = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        host, sep, port = item.rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"control-plane endpoint {item!r}: want HOST:PORT")
        out.append((host, int(port)))
    return out


class _ShardState:
    """Dead-set shared by every router of one attachment (the main client
    and heartbeat/subsystem extra clients must agree on routing)."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]]) -> None:
        self.endpoints = list(endpoints)
        self.dead: set = set()
        self.mu = threading.Lock()


class _NullReply:
    """Empty drain owner (zero-key take_bytes_many_views)."""

    view = memoryview(b"")

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ShardRouter:
    """N-shard control-plane client: consistent routing + failover.

    Duck-types :class:`ControlPlaneClient`. ``lenient=True`` (status/dump
    tooling) tolerates shards that are already unreachable at construction
    — they are marked dead and reported by name instead of raising. The
    default (job attach) is stricter but failover-aware: an unreachable
    shard is accepted only when the SURVIVORS have flagged it dead
    (``bf.cp.shard_dead.<i>`` — a respawned rank must be able to rejoin a
    legitimately degraded cluster), while a fresh job with a down,
    unflagged shard fails loudly — it would otherwise silently run with
    less replication than the operator configured.
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]], rank: int,
                 secret: str = "", streams: Optional[int] = None,
                 incarnation: Optional[int] = None,
                 shared_state: Optional[_ShardState] = None,
                 lenient: bool = False) -> None:
        if not endpoints:
            raise ValueError("ShardRouter needs at least one endpoint")
        self._st = shared_state or _ShardState(endpoints)
        self._rank = rank
        self._secret = secret
        self._streams = streams
        self.incarnation = None if incarnation is None else int(incarnation)
        self._clients: List[Optional[ControlPlaneClient]] = []
        # Clients superseded by a shard rejoin are parked here (closed at
        # router close): another thread may still be mid-call on one, and
        # closing a native client under a live call is a use-after-free.
        self._zombies: List[ControlPlaneClient] = []
        unreachable: List[int] = []

        def _bail(exc: Optional[Exception] = None):
            for cl in self._clients:
                if cl is not None:
                    cl.close()
            if exc is not None:
                raise exc

        for idx, (host, port) in enumerate(self._st.endpoints):
            if idx in self._st.dead:
                self._clients.append(None)
                continue
            try:
                self._clients.append(self._dial(idx))
            except StaleIncarnationError:
                _bail()
                raise
            except OSError:
                self._clients.append(None)
                unreachable.append(idx)
        if unreachable and not lenient:
            # failover-aware strictness: accept an unreachable shard only
            # when a survivor has flagged it dead (a rejoin into a
            # legitimately degraded cluster); otherwise raise — a FRESH
            # job must not start with less replication than configured
            flags = None
            for cl in self._clients:
                if cl is None:
                    continue
                try:
                    flags = cl.get_many(
                        [_DEAD_FLAG.format(idx=i) for i in unreachable])
                    break
                except OSError:
                    continue
            if flags is None or not all(_gen_dead(f) for f in flags):
                bad = [i for i in unreachable] if flags is None else \
                    [i for i, f in zip(unreachable, flags)
                     if not _gen_dead(f)]
                names = ", ".join(
                    "%s:%d" % self._st.endpoints[i] for i in bad)
                _bail(OSError(
                    f"control-plane shard(s) {names} unreachable and not "
                    "flagged dead by any survivor — refusing to attach a "
                    "job with less replication than configured (a shard "
                    "that legitimately died mid-job is announced under "
                    "bf.cp.shard_dead.<i> and tolerated)"))
        for idx in unreachable:  # after the list is complete: _mark_dead
            self._mark_dead(idx, "unreachable at attach")  # walks it
        if all(cl is None for cl in self._clients):
            raise OSError(
                "no control-plane shard reachable: "
                + ", ".join(f"{h}:{p}" for h, p in self._st.endpoints))
        self.streams = max(cl.streams for cl in self._clients
                           if cl is not None)

    def _dial(self, idx: int) -> ControlPlaneClient:
        """A fresh connection to shard ``idx``, armed with its ring
        successor(s) as the native failover-redirect targets (N > 1): an
        op in flight when the shard dies redials the successor on the
        SAME client — preserving the kSeqPre identity the successor's
        WAL-primed dedup table replays against. At quorum replication
        (R >= 3) the redirect is a CHAIN of the R-1 ring successors in
        walk order, so a run of consecutive dead shards (up to R-1 of
        them — a shard AND its successor dying together) still lands on
        a replica holding the keyspace."""
        host, port = self._st.endpoints[idx]
        cl = ControlPlaneClient(host, port, self._rank, secret=self._secret,
                                streams=self._streams,
                                incarnation=self.incarnation)
        n = len(self._st.endpoints)
        if n > 1:
            r = int(knob_env("BLUEFOG_CP_REPLICATION"))
            hops = min(r - 1, n - 1) if r >= 3 else 1
            if hops > 1:
                cl.set_failover_chain(
                    [self._st.endpoints[(idx + k) % n]
                     for k in range(1, hops + 1)])
            else:  # R <= 2: the r16 wire, single-successor redirect
                cl.set_failover(*self._st.endpoints[(idx + 1) % n])
        return cl

    # -- topology ----------------------------------------------------------

    @property
    def endpoints(self) -> List[Tuple[str, int]]:
        return list(self._st.endpoints)

    @property
    def shard_count(self) -> int:
        return len(self._st.endpoints)

    def shared_state(self) -> _ShardState:
        return self._st

    def dead_shards(self) -> set:
        with self._st.mu:
            return set(self._st.dead)

    def dead_shard_endpoints(self) -> List[str]:
        return [f"{h}:{p}" for i, (h, p) in enumerate(self._st.endpoints)
                if i in self.dead_shards()]

    def shard_of(self, key: str) -> int:
        """The key's PREFERRED shard (ignoring liveness): the pure hash
        every client in the job agrees on."""
        return _fnv64(key) % len(self._st.endpoints)

    def owner_of(self, key: str) -> int:
        """The key's CURRENT owner (the first live shard on its ring) —
        what the soak harness's per-era exactly-once oracle keys off."""
        return self._route(key)

    def _route(self, key: str) -> int:
        """The key's current owner: the first LIVE shard on its ring."""
        n = len(self._st.endpoints)
        pref = _fnv64(key) % n
        for attempt in range(2):
            with self._st.mu:
                for k in range(n):
                    idx = (pref + k) % n
                    if idx not in self._st.dead and \
                            self._clients[idx] is not None:
                        return idx
            # Last-chance probe before declaring the whole plane gone: a
            # shard may have REJOINED since this router last looked (its
            # even liveness generation is on the ring, but a router that
            # was blocked through the entire failover era — or adopted a
            # peer's flag just as the peer's shard was already
            # restarting — only polls health later, and with every shard
            # flagged dead there is no live client left to poll THROUGH).
            # A fresh dial per endpoint decides; anything that answers
            # rejoins the routing table.
            if attempt == 0 and not self._recover_all_dead():
                break
        raise OSError(
            "all control-plane shards are dead: "
            + ", ".join(f"{h}:{p}" for h, p in self._st.endpoints))

    def _recover_all_dead(self) -> bool:
        """Every shard is flagged dead: re-dial each endpoint once and
        adopt any that actually serves (``_mark_alive`` re-verifies under
        the shared state lock). Returns True when at least one shard came
        back. Genuinely dead endpoints refuse the dial fast, so the probe
        costs one connect attempt per shard on an already-fatal path."""
        for idx in range(len(self._st.endpoints)):
            self._mark_alive(idx, "all-dead recovery probe")
        return len(self.dead_shards()) < len(self._st.endpoints)

    def _live(self) -> List[int]:
        with self._st.mu:
            return [i for i in range(len(self._st.endpoints))
                    if i not in self._st.dead
                    and self._clients[i] is not None]

    def _mark_dead(self, idx: int, why) -> None:
        with self._st.mu:
            if idx in self._st.dead:
                return
            self._st.dead.add(idx)
            dead_n = len(self._st.dead)
        n = len(self._st.endpoints)
        host, port = self._st.endpoints[idx]
        succ = (idx + 1) % n
        first = (host, port) not in _announced_dead
        _announced_dead.add((host, port))
        (logger.error if first else logger.debug)(
            "control-plane shard %d (%s:%d) declared DEAD (%s); its "
            "keyspace fails over to shard %d, the next live shard on the "
            "ring — with WAL replication the successor already holds its "
            "mailbox/KV/lock state (zero lost deposits); unreplicated "
            "(BLUEFOG_CP_REPLICATION=0) routed state is lost with it "
            "(docs/fault_tolerance.md)", idx, host, port, why, succ)
        try:  # lazy: metrics -> control_plane -> router would be circular
            from . import metrics as _metrics
            from .timeline import timeline_instant

            _metrics.counter("cp.shard_failovers").inc()
            _metrics.counter("cp.shard_promotions").inc()
            _metrics.gauge("cp.dead_shards").set(dead_n)
            timeline_instant(f"cp.shard.{succ}", "SHARD_PROMOTED")
        except Exception:  # noqa: BLE001 — telemetry must not mask failover
            pass
        try:
            from . import flight as _flight

            _flight.recorder().instant("cp.shard_dead", a=float(idx))
            _flight.recorder().instant("cp.shard_promoted", a=float(succ))
        except Exception:  # noqa: BLE001
            pass
        # Tell every other process (best-effort): their routers adopt the
        # flag on the next heartbeat tick, so the job converges on one
        # routing instead of split-braining on per-process detection. The
        # flag is a GENERATION: bump the current (even/0) value to the
        # next odd one; monotone put_max makes concurrent announcers
        # converge on the same generation.
        flag = _DEAD_FLAG.format(idx=idx)
        for j in self._live():
            try:
                cur = self._clients[j].put_max(flag, 0)
                if cur >= 0 and not _gen_dead(cur):
                    self._clients[j].put_max(flag, cur + 1)
            except (OSError, RuntimeError):
                pass

    def _adopt_published_addr(self, idx: int) -> None:
        """A shard that rejoined on a NEW host:port published it under
        ``bf.cp.shard_addr.<idx>`` (generation-stamped, put_max-merged).
        Take the max across live shards and re-point the shared endpoint
        table before dialing — otherwise the rejoin dial would hit the
        dead old endpoint forever (the r16 same-port limitation)."""
        key = SHARD_ADDR_FMT.format(idx=idx)
        best = 0
        for j in self._live():
            cl = self._clients[j]
            if cl is None:
                continue
            try:
                best = max(best, int(cl.get(key)))
            except (OSError, RuntimeError):
                continue
        dec = unpack_shard_addr(best)
        if dec is None:
            return
        _gen, host, port = dec
        with self._st.mu:
            old = tuple(self._st.endpoints[idx])
            if (host, port) == old:
                return
            self._st.endpoints[idx] = (host, port)
        logger.warning(
            "control-plane shard %d moved: %s:%d -> %s:%d (published "
            "rejoin address adopted; generation %d)", idx, old[0], old[1],
            host, port, _gen)

    def _mark_alive(self, idx: int, why) -> None:
        """Shard rejoin (even liveness generation observed): dial the
        endpoint fresh and move its keyspace back. The superseded client
        (possibly failover-redirected) is parked, never closed mid-call."""
        with self._st.mu:
            if idx not in self._st.dead:
                return
        self._adopt_published_addr(idx)
        try:
            cl = self._dial(idx)
        except (OSError, RuntimeError):
            return  # not actually serving yet; retried on the next poll
        except StaleIncarnationError:
            return  # a newer incarnation of this rank owns the identity
        adopted = False
        with self._st.mu:
            if idx in self._st.dead:
                self._st.dead.discard(idx)
                adopted = True
                dead_n = len(self._st.dead)
        if not adopted:
            cl.close()
            return
        old, self._clients[idx] = self._clients[idx], cl
        if old is not None:
            self._zombies.append(old)
        host, port = self._st.endpoints[idx]
        _announced_dead.discard((host, port))
        first = (host, port, why) not in _announced_alive
        _announced_alive.add((host, port, why))
        (logger.warning if first else logger.debug)(
            "control-plane shard %d (%s:%d) REJOINED (%s): snapshot "
            "catch-up complete, keyspace routing restored", idx, host,
            port, why)
        try:
            from . import metrics as _metrics
            from .timeline import timeline_instant

            _metrics.counter("cp.shard_rejoins").inc()
            _metrics.gauge("cp.dead_shards").set(dead_n)
            timeline_instant(f"cp.shard.{idx}", "SHARD_REJOIN")
        except Exception:  # noqa: BLE001
            pass
        try:
            from . import flight as _flight

            _flight.recorder().instant("cp.shard_rejoin", a=float(idx))
        except Exception:  # noqa: BLE001
            pass

    def _check_failed_over(self, idx: int) -> None:
        """After a successful call on shard ``idx``'s client: if the
        native layer permanently redirected it to the ring successor, the
        primary endpoint is PROBABLY dead — but the redirect may also be
        stale (the shard has since rejoined) or spurious (a connect-storm
        dial failure on a live shard). A fresh dial to the true endpoint
        decides: success swaps the redirected client out (self-heal,
        no death published — publishing one would wedge the ring dead
        with a new odd generation nobody re-evens); failure declares the
        death for the whole job."""
        cl = self._clients[idx]
        if cl is None or idx in self._st.dead or not cl.failed_over():
            return
        try:
            fresh = self._dial(idx)
        except (OSError, RuntimeError):
            self._mark_dead(idx, "native failover redirect engaged")
            return
        except StaleIncarnationError:
            self._mark_dead(idx, "native failover redirect engaged")
            return
        self._zombies.append(cl)
        self._clients[idx] = fresh

    def poll_shard_health(self) -> set:
        """Heartbeat-tick probe: adopt peer-published liveness
        generations (odd = dead, even = rejoined), verify each live shard
        still answers, and notice clients whose calls silently redirected
        to the ring successor. Returns the dead set."""
        n = len(self._st.endpoints)
        keys = [_DEAD_FLAG.format(idx=i) for i in range(n)]
        gens: dict = {}
        for idx in self._live():
            cl = self._clients[idx]
            try:
                flags = cl.get_many(keys)
            except OSError as exc:
                self._mark_dead(idx, exc)
                continue
            self._check_failed_over(idx)
            for i, f in enumerate(flags):
                gens[i] = max(gens.get(i, 0), int(f))
        for i, g in sorted(gens.items()):
            if _gen_dead(g):
                self._mark_dead(i, "peer-published failover flag")
            elif g > 0 and i in self.dead_shards():
                self._mark_alive(i, f"liveness generation {g}")
        return self.dead_shards()

    # -- failover plumbing -------------------------------------------------

    def _on_key(self, key: str, fn: Callable):
        """Run ``fn(client)`` on the key's owner, failing over along the
        ring on wire death. Typed errors (StaleIncarnationError,
        PeerLostError, mailbox-full RuntimeError) propagate — failover is
        only for a shard that stopped answering."""
        last: Optional[Exception] = None
        for _ in range(len(self._st.endpoints)):
            idx = self._route(key)
            try:
                out = fn(self._clients[idx])
            except OSError as exc:
                self._mark_dead(idx, exc)
                last = exc
                continue
            # a call that succeeded by silently redirecting to the ring
            # successor proves the primary dead — record it so routing
            # (and every peer, via the published flag) converges now
            # instead of at the next heartbeat tick
            self._check_failed_over(idx)
            return out
        raise OSError(f"all control-plane shards failed for {key!r}: {last}")

    def _routed_batch(self, names: Sequence[str], call: Callable) -> list:
        """Partition ``names`` by owning shard, run ``call(client,
        positions)`` per shard (which must return one result per
        position), scatter results back in order; sub-batches on a shard
        that dies mid-call re-route through the shrunken ring."""
        names = list(names)
        out = [None] * len(names)
        pending = list(range(len(names)))
        while pending:
            groups: dict = {}
            for i in pending:
                groups.setdefault(self._route(names[i]), []).append(i)
            pending = []
            for sidx, idxs in groups.items():
                try:
                    res = call(self._clients[sidx], idxs)
                except OSError as exc:
                    self._mark_dead(sidx, exc)
                    pending.extend(idxs)
                    continue
                self._check_failed_over(sidx)
                for i, r in zip(idxs, res):
                    out[i] = r
        return out

    # -- replicated scalar class -------------------------------------------

    # NOTE on failure detection: the native scalar ``get``/``fetch_add``/
    # ``put_max`` report a wire failure IN-BAND as -1 (a scalar reply
    # cannot carry a side channel), so the router reaches shard-death
    # detection by riding the pipelined ``*_many`` paths for scalar reads
    # and RMWs — those raise OSError on a dead connection — and by
    # checking ``put_max`` results explicitly (replicated values are
    # non-negative by protocol, so a -1 there IS the wire failure).

    def _repl_write(self, key: str, value: int) -> None:
        """Fan a monotone write to every live shard (>= 1 must ack).

        A shard that answers with the quorum-lost rejection is ALIVE but
        on the minority side of a partition — skipping it (never marking
        it dead: its keyspace must not fail over while the process
        serves reads) keeps membership writes flowing through the
        majority side. Only when EVERY live shard is below quorum does
        the typed error propagate — the writer itself is then on the
        minority side."""
        ok = 0
        qlost: Optional[QuorumLostError] = None
        for idx in self._live():
            try:
                if self._clients[idx].put_max(key, int(value)) < 0:
                    raise OSError(
                        f"shard {idx}: put_max wire failure")
                ok += 1
            except QuorumLostError as exc:
                qlost = exc
            except OSError as exc:
                self._mark_dead(idx, exc)
        if not ok:
            if qlost is not None:
                raise qlost
            raise OSError(f"replicated write of {key!r}: no live shard")

    def _repl_read(self, key: str) -> int:
        """Max over live shards (each shard's copy is monotone; max is the
        merge that cannot regress after a failover)."""
        best: Optional[int] = None
        for idx in self._live():
            try:
                v = int(self._clients[idx].get_many([key])[0])
            except OSError as exc:
                self._mark_dead(idx, exc)
                continue
            best = v if best is None else max(best, v)
        if best is None:
            raise OSError(f"replicated read of {key!r}: no live shard")
        return best

    def replicated_get_all(self, key: str) -> List[Tuple[str, int]]:
        """(endpoint, value) per LIVE shard — the attach-time agreement
        check for bf.cp.mailbox_cap_bytes reads every copy."""
        out = []
        for idx in self._live():
            h, p = self._st.endpoints[idx]
            try:
                out.append((f"{h}:{p}",
                            int(self._clients[idx].get_many([key])[0])))
            except OSError as exc:
                self._mark_dead(idx, exc)
        return out

    # -- scalar ops --------------------------------------------------------

    def barrier(self, name: str = "default") -> int:
        return self._on_key(name, lambda cl: cl.barrier(name))

    def lock(self, name: str) -> None:
        return self._on_key(name, lambda cl: cl.lock(name))

    def unlock(self, name: str) -> None:
        return self._on_key(name, lambda cl: cl.unlock(name))

    def fetch_add(self, name: str, delta: int = 1) -> int:
        if is_replicated_key(name):
            # every live copy advances; the max pre-value preserves the
            # only contract consumers rely on (monotone, moves on change).
            # Quorum-lost shards are skipped alive (see _repl_write).
            pre: Optional[int] = None
            qlost: Optional[QuorumLostError] = None
            for idx in self._live():
                try:
                    v = int(self._clients[idx].fetch_add_many(
                        [name], deltas=[delta])[0])
                except QuorumLostError as exc:
                    qlost = exc
                    continue
                except OSError as exc:
                    self._mark_dead(idx, exc)
                    continue
                pre = v if pre is None else max(pre, v)
            if pre is None:
                if qlost is not None:
                    raise qlost
                raise OSError(f"replicated fetch_add of {name!r}: no live "
                              "shard")
            return pre
        return self._on_key(
            name, lambda cl: cl.fetch_add_many([name], deltas=[delta])[0])

    def put(self, name: str, value: int) -> None:
        if is_replicated_key(name):
            self._repl_write(name, value)
            return
        return self._on_key(name, lambda cl: cl.put(name, value))

    def put_max(self, name: str, value: int) -> int:
        if is_replicated_key(name):
            self._repl_write(name, value)
            return int(value)

        def one(cl):
            r = cl.put_max(name, value)
            if r == -1:  # in-band wire failure (see NOTE above)
                raise OSError("put_max wire failure")
            return r

        return self._on_key(name, one)

    def get(self, name: str) -> int:
        if is_replicated_key(name):
            return self._repl_read(name)
        return self._on_key(name, lambda cl: cl.get_many([name])[0])

    # -- pipelined scalar batches ------------------------------------------

    def _split_replicated(self, names: Sequence[str]):
        names = list(names)
        repl = [i for i, nm in enumerate(names) if is_replicated_key(nm)]
        routed = [i for i, nm in enumerate(names)
                  if not is_replicated_key(nm)]
        return names, repl, routed

    def get_many(self, names) -> list:
        names, repl, routed = self._split_replicated(names)
        if not names:
            return []
        out = [0] * len(names)
        for i in repl:
            out[i] = self._repl_read(names[i])
        if routed:
            sub = self._routed_batch(
                [names[i] for i in routed],
                lambda cl, idxs: cl.get_many(
                    [names[routed[j]] for j in idxs]))
            for j, i in enumerate(routed):
                out[i] = sub[j]
        return out

    def put_many(self, names, values) -> None:
        names = list(names)
        values = list(values)
        if not names:
            return
        repl = [i for i, nm in enumerate(names) if is_replicated_key(nm)]
        for i in repl:
            self._repl_write(names[i], values[i])
        routed = [i for i in range(len(names)) if i not in set(repl)]
        if routed:
            self._routed_batch(
                [names[i] for i in routed],
                lambda cl, idxs: cl.put_many(
                    [names[routed[j]] for j in idxs],
                    [values[routed[j]] for j in idxs]) or
                [None] * len(idxs))

    def fetch_add_many(self, names, deltas=None) -> list:
        names = list(names)
        if not names:
            return []
        deltas = [1] * len(names) if deltas is None else list(deltas)
        out = [0] * len(names)
        repl = [i for i, nm in enumerate(names) if is_replicated_key(nm)]
        for i in repl:
            out[i] = self.fetch_add(names[i], deltas[i])
        routed = [i for i in range(len(names)) if i not in set(repl)]
        if routed:
            sub = self._routed_batch(
                [names[i] for i in routed],
                lambda cl, idxs: cl.fetch_add_many(
                    [names[routed[j]] for j in idxs],
                    deltas=[deltas[routed[j]] for j in idxs]))
            for j, i in enumerate(routed):
                out[i] = sub[j]
        return out

    # -- bulk bytes (mailboxes / bytes slots are never replicated) ---------

    def append_bytes(self, name: str, data) -> int:
        return self._on_key(name, lambda cl: cl.append_bytes(name, data))

    def take_bytes(self, name: str) -> list:
        return self._on_key(name, lambda cl: cl.take_bytes(name))

    def put_bytes(self, name: str, data) -> None:
        return self._on_key(name, lambda cl: cl.put_bytes(name, data))

    def get_bytes(self, name: str) -> bytes:
        return self._on_key(name, lambda cl: cl.get_bytes(name))

    def bytes_len(self, name: str) -> int:
        return self._on_key(name, lambda cl: cl.bytes_len(name))

    def get_bytes_view(self, name: str):
        return self._on_key(name, lambda cl: cl.get_bytes_view(name))

    def append_bytes_many(self, names, blobs) -> list:
        names = list(names)
        blobs = list(blobs)
        return self._routed_batch(
            names,
            lambda cl, idxs: cl.append_bytes_many(
                [names[i] for i in idxs], [blobs[i] for i in idxs]))

    def append_bytes_tagged_many(self, names, blobs, tags) -> list:
        names, blobs, tags = list(names), list(blobs), list(tags)
        # per-shard sub-batches preserve the header-before-chunks arrival
        # order per mailbox key (one key never splits across shards)
        return self._routed_batch(
            names,
            lambda cl, idxs: cl.append_bytes_tagged_many(
                [names[i] for i in idxs], [blobs[i] for i in idxs],
                [tags[i] for i in idxs]))

    def put_bytes_many(self, names, blobs) -> None:
        names = list(names)
        blobs = list(blobs)
        self._routed_batch(
            names,
            lambda cl, idxs: cl.put_bytes_many(
                [names[i] for i in idxs], [blobs[i] for i in idxs]) or
            [None] * len(idxs))

    def take_bytes_many(self, names) -> list:
        names = list(names)
        return self._routed_batch(
            names,
            lambda cl, idxs: cl.take_bytes_many([names[i] for i in idxs]))

    def box_bytes_many(self, names) -> list:
        names = list(names)
        return self._routed_batch(
            names,
            lambda cl, idxs: cl.box_bytes_many([names[i] for i in idxs]))

    def get_bytes_many(self, names) -> list:
        names = list(names)
        return self._routed_batch(
            names,
            lambda cl, idxs: cl.get_bytes_many([names[i] for i in idxs]))

    def take_bytes_many_views(self, names, pooled: bool = True):
        names = list(names)
        if not names:
            return [], _NullReply()
        out = [None] * len(names)
        owners = []
        pending = list(range(len(names)))
        while pending:
            groups: dict = {}
            for i in pending:
                groups.setdefault(self._route(names[i]), []).append(i)
            pending = []
            for sidx, idxs in groups.items():
                try:
                    recs, owner = self._clients[sidx].take_bytes_many_views(
                        [names[i] for i in idxs], pooled=pooled)
                except OSError as exc:
                    self._mark_dead(sidx, exc)
                    pending.extend(idxs)
                    continue
                self._check_failed_over(sidx)
                owners.append(owner)
                for i, r in zip(idxs, recs):
                    out[i] = r
        return out, _MultiReply(owners)

    # -- per-shard introspection -------------------------------------------

    def server_stats_all(self) -> List[Tuple[str, Optional[dict]]]:
        """(endpoint, counter block or None-when-dead) per shard — the
        merged per-shard view behind ``bfrun --status --cp a,b,...``."""
        out: List[Tuple[str, Optional[dict]]] = []
        for idx, (h, p) in enumerate(self._st.endpoints):
            name = f"{h}:{p}"
            if idx in self.dead_shards() or self._clients[idx] is None:
                out.append((name, None))
                continue
            try:
                out.append((name, self._clients[idx].server_stats()))
            except OSError as exc:
                self._mark_dead(idx, exc)
                out.append((name, None))
        return out

    def close(self) -> None:
        for cl in self._clients:
            if cl is not None:
                cl.close()
        self._clients = [None] * len(self._clients)
        for cl in self._zombies:
            cl.close()
        self._zombies = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
