"""Rank-local online performance controller — the self-tuning wire.

ISSUE r16 (docs/self_tuning.md): every knob that realizes the paper's
cheap-neighbor-gossip bet (``BLUEFOG_WIN_PLANE``, ``BLUEFOG_WIN_CODEC``,
the topology's in-degree) is static, while the r18 telemetry plane
already measures exactly what those knobs trade off. This module closes
the DECISION loop the ROADMAP names: a controller, ticked from the
existing heartbeat/sampler cadence, that consumes the streaming series
and actuates three existing levers —

* **per-edge plane** — measured per-edge wire bytes feed
  ``PlanePlanner.ingest_live`` as online overrides of the static
  ``wire_scale`` floor estimate; the partition cache is invalidated only
  when a size-floor verdict actually flips, so re-planning happens on
  decision change, never per tick.
* **per-edge codec** — sustained-slow out-edges escalate
  ``none -> int8 -> topk`` through ``Window.set_edge_codec`` (r15's named
  upside: the codec id already rides every deposit header, so no receiver
  coordination); EF-residual pressure or a ``consensus_stall`` alert
  de-escalates (the CHOCO/EF convergence guard).
* **per-rank in-degree** — a sustained straggler (the r18 ``straggler``
  detector's step-counter spread) is demoted to fewer in-edges with
  total-preserving column renormalization
  (``topology_util.demote_in_edges`` semantics, realized through the
  optimizers' healed tables), and promoted back on recovery — the
  round-trip restores the weight matrix exactly.

Safety properties (all test-pinned):

* **Off by default**: ``BLUEFOG_TUNE=0`` takes zero KV reads, mutates
  nothing, and leaves every wire byte identical to the untuned build.
* **Epoch-fenced**: each decision snapshot captures the r9 membership
  epoch and re-checks it immediately before actuating; a rejoin or death
  racing the decision defers it to the next tick, where it is re-derived
  against the new membership. In-degree moves publish under
  ``bf.tune.demoted`` and then BUMP the membership epoch, so every
  optimizer applies them at the same re-plan boundary rejoins already
  use.
* **Hysteresis-gated**: a lever moves only after its trigger held for
  ``slow_for``/``straggler_for`` seconds (sustained breach, the r18 rule
  engine's shape) and never twice within ``dwell`` seconds of the same
  target (min-dwell) — the controller cannot flap.
* **Observable**: every decision lands as a flight instant
  (``tune.<lever>``), a ``tune.decisions`` series sample, and the
  ``bf.tune.<rank>`` KV document ``bfrun --top`` renders, so the wire's
  shape is always explainable after the fact.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .config import knob_env
from .logging import logger

Edge = Tuple[int, int]

# KV keys: the shared demotion document (one per job, epoch-fenced) and
# the per-rank decision trail (--top renders it; postmortems dump it).
DEMOTE_KEY = "bf.tune.demoted"
TUNE_KEY_FMT = "bf.tune.{rank}"

# Codec escalation ladder, cheapest wire last. Escalation only ever moves
# one rung per decision (hysteresis does the rest); de-escalation walks
# back the same rungs.
LADDER: Tuple[Optional[str], ...] = (None, "int8", "topk:0.01")

# Decision-table thresholds, overridable via BLUEFOG_TUNE_RULES
# (``key=value,...``). Kept as a flat dict so the grammar stays trivial
# and the table is printable in --top / docs.
DEFAULT_RULES: Dict[str, float] = {
    # an out-edge is SLOW when its measured bytes/s fall below
    # slow_ratio x the median across all measured edges...
    "slow_ratio": 0.5,
    # ...or below an absolute floor (bytes/s; 0 disables)...
    "min_bps": 0.0,
    # ...or its p99 deposit->drain transit exceeds this (ms; 0 disables)
    "transit_p99_ms": 0.0,
    # sustained-breach windows (seconds) before a lever may move
    "slow_for": 10.0,
    "straggler_for": 10.0,
    # min-dwell: seconds a target is immune after ANY actuation on it
    "dwell": 30.0,
    # de-escalate when the window EF residual norm exceeds this (0 =
    # only the consensus_stall alert de-escalates)
    "deesc_norm": 0.0,
    # in-edges a demoted straggler keeps (its fastest ones)
    "keep_in": 1.0,
}


def enabled() -> bool:
    return bool(knob_env("BLUEFOG_TUNE"))


def tune_interval() -> float:
    raw = knob_env("BLUEFOG_TUNE_INTERVAL")
    return 5.0 if raw is None else max(0.5, float(raw))


def parse_tune_rules(spec: Optional[str]) -> Dict[str, float]:
    """``key=value,...`` over :data:`DEFAULT_RULES`; unknown keys and
    malformed values warn and are skipped (tuning config must never take
    a job down — same contract as BLUEFOG_ALERT_RULES)."""
    rules = dict(DEFAULT_RULES)
    for term in (spec or "").split(","):
        term = term.strip()
        if not term:
            continue
        key, sep, val = term.partition("=")
        key = key.strip()
        if not sep or key not in rules:
            logger.warning("BLUEFOG_TUNE_RULES: skipping unknown term %r "
                           "(keys: %s)", term, ", ".join(sorted(rules)))
            continue
        try:
            rules[key] = float(val.strip())
        except ValueError:
            logger.warning("BLUEFOG_TUNE_RULES: skipping non-numeric term "
                           "%r", term)
    return rules


# ---------------------------------------------------------------------------
# Snapshot: everything one decision consumes, as plain data
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class EdgeSample:
    """One edge's measured wire state at snapshot time."""

    bps: float = 0.0                    # measured bytes/s (0 = no data)
    p99_us: Optional[float] = None      # deposit->drain transit p99


@dataclasses.dataclass
class Snapshot:
    """Sensor state for one controller tick. ``decide`` consumes ONLY
    this (plus the controller's own hysteresis state), which is what
    makes the decision table unit-testable with synthetic series."""

    now: float
    epoch: int
    rank: int                           # this controller's process index
    owned: Set[int]                     # ranks this controller owns
    edges: Dict[Edge, EdgeSample] = dataclasses.field(default_factory=dict)
    stragglers: Set[int] = dataclasses.field(default_factory=set)
    alerts: Set[str] = dataclasses.field(default_factory=set)
    ef_norm: float = 0.0


@dataclasses.dataclass
class Decision:
    """One actuation the decision table emitted."""

    lever: str                          # "plane" | "codec" | "indegree"
    target: object                      # Edge, rank, or None (plane)
    action: str                         # escalate/deescalate/demote/...
    arg: object = None                  # codec spec, dropped-edge list...
    reason: str = ""


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------

class Tuner:
    """One rank-local controller instance (usually the module singleton).

    ``decide`` is a pure function of (Snapshot, hysteresis state) and is
    what the unit tests drive; ``tick`` wraps it with sensor gathering,
    the epoch fence, actuation, and the decision-trail publication."""

    def __init__(self, rank: int, world: int,
                 rules: Optional[Dict[str, float]] = None) -> None:
        self.rank = int(rank)
        self.world = int(world)
        self.rules = dict(rules) if rules is not None else \
            parse_tune_rules(knob_env("BLUEFOG_TUNE_RULES"))
        # hysteresis state: first-breach times per candidate move and
        # last-actuation times per target (min-dwell)
        self._breach: Dict[tuple, float] = {}
        self._last_act: Dict[tuple, float] = {}
        # current codec escalation level per out-edge (index into LADDER)
        self._level: Dict[Edge, int] = {}
        # in-edges dropped per demoted rank (this controller's view)
        self._demoted: Dict[int, FrozenSet[Edge]] = {}
        # measured-bps deltas: edge -> (t, cumulative bytes)
        self._edge_mark: Dict[Edge, Tuple[float, float]] = {}
        self._decisions: List[dict] = []    # trail ring (last 64)
        self._last_tick = 0.0

    # -- hysteresis helpers -------------------------------------------------

    def _dwell_ok(self, target: tuple, now: float) -> bool:
        last = self._last_act.get(target)
        return last is None or now - last >= self.rules["dwell"]

    def _sustained(self, key: tuple, breaching: bool, now: float,
                   for_sec: float) -> bool:
        """Sustained-breach gate: True once ``breaching`` has held for
        ``for_sec`` seconds (the r18 rule-engine shape, per candidate)."""
        if not breaching:
            self._breach.pop(key, None)
            return False
        t0 = self._breach.setdefault(key, now)
        return now - t0 >= for_sec

    # -- the decision table -------------------------------------------------

    def decide(self, snap: Snapshot) -> List[Decision]:
        """Pure decision pass: consumes a snapshot, updates the breach
        clocks, returns the lever moves that cleared hysteresis. Does NOT
        actuate and does NOT start dwell windows — ``note_applied`` does,
        after the epoch-fenced actuation succeeds."""
        out: List[Decision] = []
        r = self.rules
        measured = sorted(s.bps for s in snap.edges.values() if s.bps > 0)
        med = measured[len(measured) // 2] if measured else 0.0

        # codec lever: escalate sustained-slow owned out-edges one rung
        for e in sorted(snap.edges):
            if e[0] not in snap.owned:
                continue
            s = snap.edges[e]
            slow = False
            why = ""
            if med > 0 and 0 < s.bps < r["slow_ratio"] * med:
                slow, why = True, (f"bps {s.bps:.0f} < {r['slow_ratio']:g}"
                                   f"x median {med:.0f}")
            if r["min_bps"] > 0 and 0 < s.bps < r["min_bps"]:
                slow, why = True, f"bps {s.bps:.0f} < floor {r['min_bps']:g}"
            if r["transit_p99_ms"] > 0 and s.p99_us is not None and \
                    s.p99_us > r["transit_p99_ms"] * 1000.0:
                slow, why = True, (f"transit p99 {s.p99_us / 1000:.0f} ms "
                                   f"> {r['transit_p99_ms']:g} ms")
            if self._sustained(("codec", e), slow, snap.now,
                               r["slow_for"]) and \
                    self._dwell_ok(("codec", e), snap.now):
                lvl = self._level.get(e, 0)
                if lvl < len(LADDER) - 1:
                    out.append(Decision("codec", e, "escalate",
                                        LADDER[lvl + 1], why))

        # codec de-escalation: compression is hurting convergence
        pressure = ""
        if "consensus_stall" in snap.alerts:
            pressure = "consensus_stall alert active"
        elif r["deesc_norm"] > 0 and snap.ef_norm > r["deesc_norm"]:
            pressure = (f"EF residual norm {snap.ef_norm:.3g} > "
                        f"{r['deesc_norm']:g}")
        if pressure:
            for e in sorted(self._level):
                lvl = self._level[e]
                if lvl > 0 and self._dwell_ok(("codec", e), snap.now):
                    out.append(Decision("codec", e, "deescalate",
                                        LADDER[lvl - 1], pressure))

        # in-degree lever: demote sustained stragglers, promote recovered
        for p in sorted(snap.stragglers):
            if p in self._demoted:
                self._breach.pop(("recover", p), None)
                continue
            if self._sustained(("straggler", p), True, snap.now,
                               r["straggler_for"]) and \
                    self._dwell_ok(("indegree", p), snap.now):
                out.append(Decision(
                    "indegree", p, "demote", None,
                    "step-counter spread straggler sustained "
                    f"{r['straggler_for']:g}s"))
        for p in sorted(set(self._demoted) - snap.stragglers):
            self._breach.pop(("straggler", p), None)
            if self._sustained(("recover", p), True, snap.now,
                               r["straggler_for"]) and \
                    self._dwell_ok(("indegree", p), snap.now):
                out.append(Decision("indegree", p, "promote", None,
                                    "straggler verdict cleared"))
        for p in snap.stragglers:
            if p not in self._demoted:
                self._breach.pop(("recover", p), None)
        return out

    def note_applied(self, d: Decision, now: float) -> None:
        """Fold one APPLIED decision back into controller state: start
        the target's dwell window and advance the codec/demotion maps.
        Split from ``decide`` so a deferred (epoch-fenced) or failed
        actuation neither burns the dwell nor desyncs the maps."""
        if d.lever == "codec":
            self._last_act[("codec", d.target)] = now
            if d.action == "escalate":
                self._level[d.target] = min(
                    self._level.get(d.target, 0) + 1, len(LADDER) - 1)
            elif d.action == "deescalate":
                lvl = self._level.get(d.target, 0) - 1
                if lvl <= 0:
                    self._level.pop(d.target, None)
                else:
                    self._level[d.target] = lvl
            self._breach.pop(("codec", d.target), None)
        elif d.lever == "indegree":
            self._last_act[("indegree", d.target)] = now
            if d.action == "demote":
                self._demoted[d.target] = frozenset(d.arg or ())
                self._breach.pop(("straggler", d.target), None)
            else:
                self._demoted.pop(d.target, None)
                self._breach.pop(("recover", d.target), None)

    # -- sensors ------------------------------------------------------------

    def gather(self, cl=None, now: Optional[float] = None) -> Snapshot:
        """Build the sensor snapshot from the r18 telemetry plane: the
        local store's edge estimators (+ peer-published edges when a
        control plane is attached), the active alert set, the windows'
        EF residual norm, and the step-spread straggler verdict."""
        from . import control_plane as _cp
        from . import heartbeat as _hb
        from . import metrics as _metrics
        from . import timeseries as _ts
        from .state import _global_state

        if now is None:
            now = time.time()
        epoch = _hb.membership_epoch()
        owned: Set[int] = set()
        ef_norm = 0.0
        try:
            st = _global_state()
            for win in list(st.windows.values()):
                owned.update(win.owned)
                ef_norm = max(ef_norm, win.ef_residual_norm())
        except Exception:  # noqa: BLE001 — sensors never raise
            pass
        if not owned:
            owned = {self.rank}
        edges: Dict[Edge, EdgeSample] = {}
        store = _ts.store()
        for name, es in store.edges().items():
            try:
                src_s, dst_s = name.split("->")
                e = (int(src_s), int(dst_s))
            except ValueError:
                continue
            mark = self._edge_mark.get(e)
            self._edge_mark[e] = (now, es.bytes)
            bps = 0.0
            if mark is not None and now > mark[0]:
                bps = max(0.0, (es.bytes - mark[1]) / (now - mark[0]))
            edges[e] = EdgeSample(bps=bps, p99_us=es.percentiles()[1])
        alerts = {name for name, rs in store._rule_state.items()
                  if rs.active}
        stragglers: Set[int] = set()
        if cl is None and _cp.active():
            cl = _cp.client()
        if cl is not None:
            try:
                health = _metrics.read_cluster_health(cl, self.world)
                stragglers = set(health.get("stragglers") or ())
                for p in range(self.world):
                    if p == self.rank:
                        continue
                    doc = _ts.read_rank(cl, p)
                    if not doc:
                        continue
                    for name, row in (doc.get("edges") or {}).items():
                        try:
                            src_s, dst_s = name.split("->")
                            e = (int(src_s), int(dst_s))
                        except ValueError:
                            continue
                        if e not in edges or edges[e].bps == 0.0:
                            edges[e] = EdgeSample(
                                bps=float(row.get("bps") or 0.0),
                                p99_us=row.get("p99_us"))
            except Exception:  # noqa: BLE001 — sensors never raise
                pass
        return Snapshot(now=now, epoch=epoch, rank=self.rank, owned=owned,
                        edges=edges, stragglers=stragglers, alerts=alerts,
                        ef_norm=ef_norm)

    # -- actuation ----------------------------------------------------------

    def _feed_planner(self, snap: Snapshot) -> bool:
        """Plane lever: push measured per-edge wire bytes into every
        hosted window's planner as online overrides. Returns True when
        any planner's size-floor verdict flipped (== a re-plan was
        scheduled); otherwise the ingest is free."""
        from . import timeseries as _ts
        from .state import _global_state

        store = _ts.store()
        per_deposit: Dict[Edge, float] = {}
        for name, es in store.edges().items():
            if not es.deposits:
                continue
            try:
                src_s, dst_s = name.split("->")
                e = (int(src_s), int(dst_s))
            except ValueError:
                continue
            per_deposit[e] = es.bytes / es.deposits
        if not per_deposit:
            return False
        flipped = False
        try:
            st = _global_state()
            for win in list(st.windows.values()):
                planner = getattr(win, "_planner", None)
                if planner is not None:
                    flipped |= planner.ingest_live(per_deposit)
        except Exception:  # noqa: BLE001
            return False
        return flipped

    def _leader(self) -> bool:
        """In-degree moves are actuated by ONE controller (the lowest
        live process index) — every tuner decides, one writes, everybody
        applies through the epoch-fenced demotion document."""
        from . import heartbeat as _hb

        dead = _hb.dead_controllers()
        live = [p for p in range(self.world) if p not in dead]
        return bool(live) and live[0] == self.rank

    def _demote_targets(self, snap: Snapshot, straggler: int) -> List[Edge]:
        """In-edges to drop for a demoted straggler: everything except
        its ``keep_in`` fastest measured in-edges (unmeasured edges rank
        slowest — no data means no recent traffic)."""
        from .state import _global_state

        in_srcs: Set[int] = set()
        try:
            st = _global_state()
            for win in list(st.windows.values()):
                in_srcs.update(win.in_neighbors.get(straggler, ()))
        except Exception:  # noqa: BLE001
            pass
        if not in_srcs:
            return []
        keep = max(1, int(self.rules["keep_in"]))
        ranked = sorted(
            in_srcs,
            key=lambda s: -(snap.edges.get((s, straggler),
                                           EdgeSample()).bps))
        return [(s, straggler) for s in ranked[keep:]]

    def _actuate(self, d: Decision, snap: Snapshot, cl=None) -> bool:
        from .state import _global_state

        if d.lever == "codec":
            src, dst = d.target
            changed = False
            try:
                st = _global_state()
                for win in list(st.windows.values()):
                    if getattr(win, "hosted", False) and src in win.owned:
                        changed |= win.set_edge_codec(src, dst, d.arg)
            except Exception as exc:  # noqa: BLE001
                logger.warning("tuner: codec actuation failed (%s)", exc)
                return False
            return changed
        if d.lever == "indegree":
            if not self._leader():
                return False
            if d.action == "demote":
                drops = self._demote_targets(snap, d.target)
                if not drops:
                    return False
                d.arg = drops
            current = dict(self._demoted)
            if d.action == "demote":
                current[d.target] = frozenset(d.arg)
            else:
                current.pop(d.target, None)
            edges = sorted({e for s in current.values() for e in s})
            return _publish_demotions(cl, edges, snap.epoch)
        return True  # "plane" already actuated through ingest_live

    # -- the tick -----------------------------------------------------------

    def tick(self, cl=None, now: Optional[float] = None) -> List[Decision]:
        """One controller pass: gather -> decide -> (epoch fence) ->
        actuate -> publish. Returns the APPLIED decisions."""
        from . import flight as _flight
        from . import heartbeat as _hb
        from . import metrics as _metrics
        from . import timeseries as _ts

        if now is None:
            now = time.time()
        self._last_tick = now
        snap = self.gather(cl, now)
        decisions = self.decide(snap)
        if self._feed_planner(snap):
            decisions.append(Decision(
                "plane", None, "replan",
                reason="measured edge bytes flipped a size-floor verdict"))
        applied: List[Decision] = []
        for d in decisions:
            # EPOCH FENCE: membership moved since the snapshot was taken
            # (a death, a rejoin) — this decision was derived against a
            # stale edge set. Defer; the next tick re-decides.
            if _hb.membership_epoch() != snap.epoch:
                _metrics.counter("tune.deferred").inc()
                self._record(d, now, "deferred")
                continue
            ok = self._actuate(d, snap, cl)
            if ok:
                self.note_applied(d, now)
                applied.append(d)
                _metrics.counter("tune.decisions").inc()
                _flight.recorder().instant(
                    f"tune.{d.lever}",
                    a=float(d.target if isinstance(d.target, int)
                            else d.target[1] if d.target else -1))
                logger.warning("tune: %s %s %s %s (%s)", d.lever, d.action,
                               d.target, d.arg if d.arg is not None else "",
                               d.reason)
            self._record(d, now, "applied" if ok else "skipped")
        if _ts.enabled():
            _ts.store().series("tune.decisions", "counter", "last").add(
                now, float(_metrics.counter("tune.decisions").value))
        self._publish_trail(cl, now)
        return applied

    def maybe_tick(self, cl=None, now: Optional[float] = None) -> None:
        """Interval-gated entry point (heartbeat tick / optimizer step
        funnel — mirrors ``timeseries.maybe_sample``). Never raises."""
        if not enabled():
            return
        if now is None:
            now = time.time()
        if now - self._last_tick < tune_interval():
            return
        try:
            self.tick(cl, now)
        except Exception as exc:  # noqa: BLE001 — tuning must not take
            logger.debug("tuner tick failed (%s)", exc)  # the job down

    # -- trail / publication ------------------------------------------------

    def _record(self, d: Decision, now: float, status: str) -> None:
        self._decisions.append({
            "t": round(now, 3), "lever": d.lever, "action": d.action,
            "target": list(d.target) if isinstance(d.target, tuple)
            else d.target,
            "arg": [list(e) for e in d.arg]
            if isinstance(d.arg, list) else d.arg,
            "status": status, "reason": d.reason})
        del self._decisions[:-64]

    def _publish_trail(self, cl=None, now: Optional[float] = None) -> None:
        from . import control_plane as _cp

        if cl is None and _cp.active():
            cl = _cp.client()
        if cl is None:
            return
        doc = {
            "rank": self.rank, "t": now,
            "levels": {f"{s}>{t}": LADDER[lvl]
                       for (s, t), lvl in sorted(self._level.items())},
            "demoted": {str(p): sorted([list(e) for e in v])
                        for p, v in sorted(self._demoted.items())},
            "decisions": self._decisions[-16:],
        }
        try:
            cl.put_bytes(TUNE_KEY_FMT.format(rank=self.rank),
                         json.dumps(doc).encode())
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass


# ---------------------------------------------------------------------------
# Module plumbing: singleton, demotion document, consumer accessor
# ---------------------------------------------------------------------------

_mu = threading.Lock()
_singleton: Optional[Tuner] = None
# demotion view: local mirror (authoritative single-controller, cache
# multi-controller) + the epoch it was read at
_local_demoted: FrozenSet[Edge] = frozenset()
_demote_cache: Dict[str, object] = {"epoch": None, "edges": frozenset()}


def instance() -> Tuner:
    """The process-wide controller (created on first use)."""
    global _singleton
    with _mu:
        if _singleton is None:
            from . import metrics as _metrics

            rank = _metrics._process_index()
            try:
                from .state import _global_state

                world = max(1, getattr(_global_state(), "process_count", 1))
            except Exception:  # noqa: BLE001
                world = 1
            _singleton = Tuner(rank, world)
        return _singleton


def reset_for_job() -> None:
    """Fresh controller + demotion view per ``bf.init`` (mirrors
    ``timeseries.reset_for_job``)."""
    global _singleton, _local_demoted
    with _mu:
        _singleton = None
        _local_demoted = frozenset()
        _demote_cache.update(epoch=None, edges=frozenset())


def maybe_tick(cl=None) -> None:
    """The heartbeat/step funnel: no-op unless ``BLUEFOG_TUNE=1`` (the
    knob gate runs BEFORE the singleton exists, so the off path touches
    nothing)."""
    if not enabled():
        return
    instance().maybe_tick(cl)


def _publish_demotions(cl, edges: List[Edge], epoch: int) -> bool:
    """Write the job-wide demotion document and bump the membership
    epoch so every optimizer re-plans at the same fence (single-
    controller: just swap the local set — the healed-table cache key
    change applies it on the very next gossip step)."""
    global _local_demoted
    from . import control_plane as _cp
    from . import heartbeat as _hb

    _local_demoted = frozenset(edges)
    if cl is None and _cp.active():
        cl = _cp.client()
    if cl is None:
        return True
    try:
        cl.put_bytes(DEMOTE_KEY, json.dumps(
            {"epoch": epoch, "edges": [list(e) for e in edges]}).encode())
        cl.fetch_add(_hb._EPOCH_KEY, 1)
    except Exception as exc:  # noqa: BLE001
        logger.warning("tuner: demotion publish failed (%s)", exc)
        return False
    _demote_cache.update(epoch=None)  # force re-read at the new epoch
    return True


def demoted_edges() -> FrozenSet[Edge]:
    """The directed edges currently demoted by the controller, as the
    optimizers' healed tables consume them. ``BLUEFOG_TUNE=0`` returns
    the empty set with no KV traffic and no singleton — the off path is
    byte-identical to the untuned build (test-pinned). Multi-controller
    reads are cached per membership epoch: demotions only ever change
    together with an epoch bump, so one KV read per epoch suffices."""
    if not enabled():
        return frozenset()
    from . import control_plane as _cp

    if not _cp.active():
        return _local_demoted
    from . import heartbeat as _hb

    ep = _hb.membership_epoch()
    if _demote_cache["epoch"] == ep:
        return _demote_cache["edges"]  # type: ignore[return-value]
    edges = _local_demoted
    try:
        blob = _cp.client().get_bytes(DEMOTE_KEY)
        if blob:
            doc = json.loads(bytes(blob).decode())
            edges = frozenset((int(s), int(d))
                              for s, d in doc.get("edges", []))
    except Exception:  # noqa: BLE001 — keep the previous view on error
        edges = _demote_cache["edges"]  # type: ignore[assignment]
    _demote_cache.update(epoch=ep, edges=edges)
    return edges  # type: ignore[return-value]
