"""Cluster telemetry plane: metrics registry + health aggregation (r10).

The paper's neighbor-averaging design trades one easy-to-observe collective
for many loosely-coupled asynchronous flows (window deposits, mailbox
drains, push-sum mass movement, heartbeat transitions), and since r8/r9 the
system changes *shape* at runtime (healed combine tables, incarnation
fencing, elastic respawn). The reference's answer was a per-process
timeline (common/timeline.{h,cc}); this module is the layer above it:
quantitative, cluster-wide, always-on telemetry that answers "is the gossip
converging, is mass conserved, which rank is the straggler, how many
retries/replays/force-releases happened" without attaching a tracer.

Three pieces:

* **Registry** — process-global counters / gauges / fixed-bucket
  histograms. The hot path is allocation-free: a counter increment is one
  attribute add on a ``__slots__`` object (< 100 ns, microbenched by
  ``make metrics-smoke``); cross-thread races can at worst drop a rare
  increment, which is the right trade for telemetry. Native-transport
  counters (bytes per op class, redials, dedup replays, stale frames —
  ``csrc/bf_runtime.cc``'s relaxed-atomic counter block) are merged into
  every snapshot as deltas against the registry's baseline.

* **Cluster health** — each controller publishes a compact packed snapshot
  to the control-plane KV under ``bf.metrics.<rank>`` on a
  ``BLUEFOG_METRICS_INTERVAL`` cadence, piggybacking the heartbeat thread
  (no new per-step RTT). :func:`cluster_health` merges the per-rank views:
  staleness, straggler detection via step-counter spread, and a global
  push-sum mass-conservation check across live ranks. ``bfrun --status``
  prints the same view from outside the job.

* **Prometheus** — ``BLUEFOG_METRICS_PROM=<path>`` dumps the text
  exposition format on the same cadence (atomic rename), ready for a
  node-exporter textfile collector or a sidecar scraper.

Collection is ALWAYS on (it is too cheap to gate); only *publication* is
gated by the env knobs, so enabling telemetry changes no training-path
behavior.
"""

from __future__ import annotations

import bisect
import os
import struct
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .logging import logger

# -- instruments -------------------------------------------------------------

# Default latency buckets (seconds): spans window-op dispatch (sub-ms) to a
# wedged-transport drain (tens of seconds).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0,
                   10.0, 30.0)


class Counter:
    """Monotonic counter. ``inc`` is the hot path: one attribute add, no
    lock, no allocation (a lost increment under a cross-thread race is an
    acceptable telemetry error; every call site is per-op or rarer)."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str) -> None:
        self.name = name
        self._v = 0

    def inc(self, n: int = 1) -> None:
        self._v += n

    @property
    def value(self) -> int:
        return self._v

    def _reset(self) -> None:
        self._v = 0


class Gauge:
    """Last-write-wins scalar (step counters, mass, queue depths)."""

    __slots__ = ("name", "_v")

    def __init__(self, name: str) -> None:
        self.name = name
        self._v = 0.0

    def set(self, v: float) -> None:
        self._v = float(v)

    def add(self, v: float) -> None:
        self._v += float(v)

    @property
    def value(self) -> float:
        return self._v

    def _reset(self) -> None:
        self._v = 0.0


class Histogram:
    """Fixed-bucket histogram (cumulative counts computed at export).

    ``observe`` costs one bisect + two adds; bounds are immutable after
    creation so pack/merge never have to reconcile bucket layouts."""

    __slots__ = ("name", "bounds", "counts", "sum", "count")

    def __init__(self, name: str, bounds=DEFAULT_BUCKETS) -> None:
        self.name = name
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(set(self.bounds)):
            raise ValueError(f"histogram {name}: bounds must be strictly "
                             "increasing")
        self.counts = [0] * (len(self.bounds) + 1)  # +1 overflow bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def _reset(self) -> None:
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0


class _Timed:
    __slots__ = ("_h", "_t0")

    def __init__(self, h: Histogram) -> None:
        self._h = h

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._h.observe(time.perf_counter() - self._t0)
        return False


# -- registry ----------------------------------------------------------------

class Registry:
    """Process-global instrument registry.

    Instrument *creation* takes a lock; the returned instruments are
    lock-free. ``reset()`` zeroes values in place (instrument identity is
    preserved, so call sites may cache bound methods across ``bf.init``
    cycles) and re-baselines the native counter block."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}
        self._docs: Dict[str, str] = {}
        self._native_base: Dict[str, float] = {}

    def _register_doc(self, name: str, doc: Optional[str]) -> None:
        if doc:
            self._docs[name] = doc

    def counter(self, name: str, doc: Optional[str] = None) -> Counter:
        c = self._counters.get(name)
        if c is None:
            with self._mu:
                c = self._counters.setdefault(name, Counter(name))
                self._register_doc(name, doc)
        return c

    def gauge(self, name: str, doc: Optional[str] = None) -> Gauge:
        g = self._gauges.get(name)
        if g is None:
            with self._mu:
                g = self._gauges.setdefault(name, Gauge(name))
                self._register_doc(name, doc)
        return g

    def histogram(self, name: str, bounds=DEFAULT_BUCKETS,
                  doc: Optional[str] = None) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            with self._mu:
                h = self._hists.setdefault(name, Histogram(name, bounds))
                self._register_doc(name, doc)
        return h

    def timed(self, name: str, bounds=DEFAULT_BUCKETS) -> _Timed:
        """Context manager observing the block's wall time in seconds."""
        return _Timed(self.histogram(name, bounds))

    def reset(self) -> None:
        """Zero every instrument in place and re-baseline native counters
        (each ``bf.init`` starts a fresh job's telemetry epoch)."""
        with self._mu:
            for c in self._counters.values():
                c._reset()
            for g in self._gauges.values():
                g._reset()
            for h in self._hists.values():
                h._reset()
            self._native_base = _native_counters()

    # -- snapshot ---------------------------------------------------------

    def snapshot(self, include_native: bool = True) -> dict:
        """Point-in-time view of every instrument, native transport
        counters merged in as deltas against the last ``reset()``."""
        from . import control_plane as _cp

        meta = {"schema": 1, "ts": time.time(), "rank": _process_index(),
                "inc": _cp.incarnation()}
        counters = {n: float(c._v) for n, c in self._counters.items()}
        gauges = {n: float(g._v) for n, g in self._gauges.items()}
        hists = {
            n: {"bounds": list(h.bounds), "counts": list(h.counts),
                "sum": h.sum, "count": h.count}
            for n, h in self._hists.items()
        }
        if include_native:
            base = self._native_base
            for name, v in _native_counters().items():
                # fault-injector counters reset on every arm — report them
                # raw; a baseline delta could go negative across an arm
                if name.startswith("cp.fault."):
                    counters[name] = v
                else:
                    counters[name] = v - base.get(name, 0.0)
            for name, v in _server_stats_flat().items():
                # live aggregates (depth/bytes/connections) are gauges;
                # event counts are counters
                if name.rsplit(".", 1)[-1] in _SERVER_GAUGE_FIELDS:
                    gauges[name] = v
                else:
                    counters[name] = v
        return {"meta": meta, "counters": counters, "gauges": gauges,
                "hists": hists}


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


# module-level conveniences (the instrumented subsystems' entry points)

def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, bounds=DEFAULT_BUCKETS) -> Histogram:
    return _REGISTRY.histogram(name, bounds)


def timed(name: str, bounds=DEFAULT_BUCKETS) -> _Timed:
    return _REGISTRY.timed(name, bounds)


def snapshot(include_native: bool = True) -> dict:
    return _REGISTRY.snapshot(include_native)


def reset_for_job() -> None:
    _REGISTRY.reset()


def _process_index() -> int:
    from .state import _global_state

    st = _global_state()
    return st.process_index if st.initialized else 0


# -- native counter merge ----------------------------------------------------

_SERVER_GAUGE_FIELDS = {"live_connections", "mailbox_records",
                        "mailbox_bytes", "locks_held", "kv_entries",
                        "bytes_slots", "bytes_slot_bytes"}


def _native_counters() -> Dict[str, float]:
    """Flattened native client + fault-injector counters (cumulative)."""
    from . import native as _native

    out: Dict[str, float] = {}
    stats = _native.client_stats()
    for group in ("ops", "bytes_out", "bytes_in"):
        for op, v in stats.get(group, {}).items():
            out[f"cp.client.{group}.{op}"] = float(v)
    for k in ("redials", "redial_attempts", "stale_frames",
              "striped_transfers"):
        if k in stats:
            out[f"cp.client.{k}"] = float(stats[k])
    fault = _native.fault_stats()
    out["cp.fault.ops"] = float(fault.get("ops", 0))
    out["cp.fault.drops"] = float(fault.get("drops", 0))
    return out


def _server_stats_flat() -> Dict[str, float]:
    """Flattened control-plane server stats (only on the serving rank)."""
    from . import control_plane as _cp

    srv = getattr(_cp, "_server", None)
    if srv is None:
        return {}
    try:
        stats = srv.stats()
    except Exception:  # noqa: BLE001 — telemetry must not raise
        return {}
    out: Dict[str, float] = {}
    for op, v in stats.get("ops", {}).items():
        out[f"cp.server.ops.{op}"] = float(v)
    for k, v in stats.items():
        if k != "ops":
            out[f"cp.server.{k}"] = float(v)
    return out


# -- packed snapshot wire format --------------------------------------------
#
#   magic "BFM1" | u16 schema | i32 rank | i64 inc | f64 ts
#   | u32 n_counters | (u16 len, name, f64 value)*
#   | u32 n_gauges   | (u16 len, name, f64 value)*
#   | u32 n_hists    | (u16 len, name, u16 nbounds, f64*nbounds bounds,
#                       u64*(nbounds+1) counts, f64 sum, u64 count)*
#
# Compact enough for the KV (a typical snapshot is a few KB), stable enough
# to read from an external process (bfrun --status) without importing jax.

_MAGIC = b"BFM1"


def _pack_kv(out: bytearray, items: Dict[str, float]) -> None:
    out += struct.pack("<I", len(items))
    for name in sorted(items):
        nb = name.encode()
        out += struct.pack("<H", len(nb)) + nb
        out += struct.pack("<d", float(items[name]))


def pack_snapshot(snap: dict) -> bytes:
    meta = snap["meta"]
    out = bytearray()
    out += _MAGIC
    out += struct.pack("<HiqD".replace("D", "d"), meta.get("schema", 1),
                       int(meta.get("rank", 0)), int(meta.get("inc", 0)),
                       float(meta.get("ts", 0.0)))
    _pack_kv(out, snap.get("counters", {}))
    _pack_kv(out, snap.get("gauges", {}))
    hists = snap.get("hists", {})
    out += struct.pack("<I", len(hists))
    for name in sorted(hists):
        h = hists[name]
        nb = name.encode()
        bounds = h["bounds"]
        out += struct.pack("<H", len(nb)) + nb
        out += struct.pack("<H", len(bounds))
        out += struct.pack(f"<{len(bounds)}d", *bounds)
        out += struct.pack(f"<{len(bounds) + 1}Q", *h["counts"])
        out += struct.pack("<dQ", float(h["sum"]), int(h["count"]))
    return bytes(out)


def _unpack_kv(buf: bytes, off: int):
    (n,) = struct.unpack_from("<I", buf, off)
    off += 4
    items: Dict[str, float] = {}
    for _ in range(n):
        (ln,) = struct.unpack_from("<H", buf, off)
        off += 2
        name = buf[off:off + ln].decode()
        off += ln
        (v,) = struct.unpack_from("<d", buf, off)
        off += 8
        items[name] = v
    return items, off


def unpack_snapshot(blob: bytes) -> dict:
    if len(blob) < 26 or blob[:4] != _MAGIC:
        raise ValueError("not a bluefog metrics snapshot (bad magic)")
    schema, rank, inc, ts = struct.unpack_from("<Hiqd", blob, 4)
    off = 4 + struct.calcsize("<Hiqd")
    counters, off = _unpack_kv(blob, off)
    gauges, off = _unpack_kv(blob, off)
    (nh,) = struct.unpack_from("<I", blob, off)
    off += 4
    hists: Dict[str, dict] = {}
    for _ in range(nh):
        (ln,) = struct.unpack_from("<H", blob, off)
        off += 2
        name = blob[off:off + ln].decode()
        off += ln
        (nb,) = struct.unpack_from("<H", blob, off)
        off += 2
        bounds = list(struct.unpack_from(f"<{nb}d", blob, off))
        off += 8 * nb
        counts = list(struct.unpack_from(f"<{nb + 1}Q", blob, off))
        off += 8 * (nb + 1)
        s, c = struct.unpack_from("<dQ", blob, off)
        off += 16
        hists[name] = {"bounds": bounds, "counts": counts, "sum": s,
                       "count": c}
    return {"meta": {"schema": schema, "rank": rank, "inc": inc, "ts": ts},
            "counters": counters, "gauges": gauges, "hists": hists}


# -- Prometheus text exposition ----------------------------------------------

# HELP text registry: instrument creation sites may pass ``doc=`` (stored
# per-registry); this curated table covers the fleet of implicitly-created
# names (subsystems create instruments by name on their hot paths, where a
# doc string per call would be noise). Prefix rules catch the generated
# families (per-op-class transport counters). Scrapes are self-describing:
# every sample gets a ``# HELP`` line (prom-lint asserts it).
_HELP_EXACT: Dict[str, str] = {
    "serve.publishes": "serving-plane snapshots committed behind the "
                       "version fence by this trainer (docs/serving.md)",
    "serve.publish_wire_bytes": "encoded snapshot bytes written to the "
                                "control plane by the serving publisher",
    "serve.version": "latest committed serving snapshot version "
                     "(bf.serve.ver fence value)",
    "serve.publish_sec": "wall seconds of the last serving snapshot "
                         "publish (encode + stripe writes + fence)",
    "opt.step": "optimizer step counter of this rank",
    "opt.step_sec": "wall seconds per optimizer step",
    "opt.pack_sec": "seconds packing the fusion buffer per gossip step",
    "opt.gossip_sec": "seconds in window gossip ops per step",
    "opt.unpack_sec": "seconds unpacking the fusion buffer per step",
    "opt.healed_rebuilds": "healed edge-table rebuilds after membership "
                           "changes",
    "opt.gossip_retries": "gossip steps retried once on a self-healed "
                          "topology after PeerLostError",
    "opt.consensus_dist": "neighborhood consensus distance: L2 from this "
                          "rank's params to the combine-weighted neighbor "
                          "mean (RMS over owned ranks; decays toward 0 as "
                          "the gossip converges — docs/observability.md)",
    "opt.mixing_rate": "effective per-second mixing rate fit from the "
                       "consensus-distance decay (< 1 = converging; ~1 = "
                       "stalled)",
    "alert.fired": "rank-local alert rules fired (sustained threshold "
                   "breaches; docs/observability.md)",
    "tune.decisions": "self-tuner lever actuations applied (codec "
                      "escalations, in-degree moves, plane re-plans; "
                      "docs/self_tuning.md)",
    "tune.deferred": "self-tuner decisions deferred by the membership-"
                     "epoch fence (re-derived on the next tick)",
    "cp.shards": "control-plane shards this process routes over",
    "cp.dead_shards": "control-plane shards currently failed over",
    "cp.shard_failovers": "shard keyspace failovers this client observed",
    "cp.shard_promotions": "times this server was promoted failover "
                           "primary for a dead shard's keyspace",
    "cp.shard_rejoins": "shard rejoin (snapshot catch-up) completions "
                        "observed",
    "cp.repl_lag": "max WAL records enqueued-but-unacked across live "
                   "shards (replication lag)",
    "cp.under_replicated": "shards serving DEGRADED (successor lagging "
                           "or absent — acked writes live nowhere else)",
    "cp.quorum_lost": "shards below their commit quorum (alive, serving "
                      "reads, rejecting mutating ops with "
                      "QuorumLostError)",
    "cp.partitions": "mutating control-plane ops rejected below quorum "
                     "(grows while a partition or correlated replica "
                     "loss is in effect)",
    "pushsum.mass": "this rank's share of global push-sum de-bias mass",
    "pushsum.minted": "push-sum mass minted (created, not transferred) by "
                      "this rank",
    "pushsum.debias_drift": "max |p - 1| over owned ranks (de-bias scalar "
                            "wander)",
    "membership.epoch": "membership epoch mirror (bumps on join/leave/"
                        "re-admission)",
    "hb.dead_peers": "controllers currently considered dead",
    "hb.suspect_peers": "resumed-but-unfenced controllers (still out of "
                        "membership)",
    "hb.dead_transitions": "live->dead membership transitions observed",
    "hb.suspect_transitions": "dead->suspect transitions (heartbeat "
                              "resumed without re-attach)",
    "hb.readmissions": "suspects re-admitted after fenced rejoin + "
                       "quarantine",
    "hb.quarantine_entries": "times this rank entered rejoin quarantine",
    "hb.quarantine_sec": "seconds spent in rejoin quarantine",
    "watchdog.stalls": "ops flagged stalled by the watchdog",
    "win.deposits_sent": "remote window deposits sent",
    "win.deposits_drained": "window deposits folded by this owner",
    "win.deposits_rejected": "deposits rejected by the server mailbox cap",
    "win.drain_records": "mailbox records drained",
    "win.drain_bytes": "mailbox bytes drained",
    "win.drain_orphans": "orphaned deposit chunks discarded",
    "win.plan_rebuilds": "per-edge plane partitions recomputed (membership "
                         "epoch / dead-set changes)",
    "win.compiled_edges": "edges on the compiled ppermute plane in the "
                          "latest partition",
    "win.hosted_edges": "edges on the hosted mailbox residual in the "
                        "latest partition",
    "cp.client.redials": "successful transparent control-plane reconnects",
    "cp.client.redial_attempts": "control-plane reconnect dials attempted",
    "cp.client.stale_frames": "incarnation-fence verdicts observed",
    "cp.client.striped_transfers": "whole striped put/get transfers",
    "cp.fault.ops": "client ops seen by the fault injector since arm",
    "cp.fault.drops": "connections killed by the fault injector since arm",
    "slo.requests": "serve requests submitted (admitted + shed) — the "
                    "burn-rate denominator (docs/slo.md)",
    "slo.shed": "serve requests refused by the admission gate — the "
                "availability-SLO error numerator",
    "slo.request_us": "end-to-end serve request latency (microseconds, "
                      "submit to reply)",
    "slo.staleness_ver": "snapshot versions between the fence and the "
                         "version that answered each request",
    "trace.requests": "serve requests traced into the flight ring "
                      "(BLUEFOG_TRACE_SERVE; docs/slo.md)",
}

_HELP_PREFIX = (
    ("cp.client.ops.", "control-plane client requests sent, by op class"),
    ("cp.client.bytes_out.", "control-plane client request bytes, by op "
                             "class"),
    ("cp.client.bytes_in.", "control-plane client reply bytes, by op "
                            "class"),
    ("cp.server.ops.", "control-plane server dispatches, by op class"),
    ("cp.server.", "control-plane server state/event counter"),
    ("win.", "hosted window data-plane op latency (seconds)"),
    ("slo.breach.", "serve requests that violated this SLO kind's "
                    "target, by objective (docs/slo.md)"),
    ("slo.burn.", "SLO error-budget burn rate over the fast/slow window, "
                  "by objective (docs/slo.md)"),
    ("slo.budget.", "fraction of the slow-window SLO error budget "
                    "remaining, by objective (<= 0 = exhausted)"),
    ("slo.phase.", "per-phase serve request latency percentile from the "
                   "trace analyzer (microseconds)"),
    ("slo.", "serving-plane SLO series (docs/slo.md)"),
    ("trace.", "serve request-path tracing series (docs/slo.md)"),
)

# Instrument-name prefix families the tree may create (first dotted
# segment). The bfcheck [metrics] analyzer enforces this plus HELP
# resolution for every creation site in the package — a new family must
# be added here (with curated HELP coverage) before it can ship.
_PREFIX_FAMILIES = ("alert", "cp", "hb", "membership", "opt", "pushsum",
                    "serve", "slo", "trace", "tune", "watchdog", "win")


def help_for(name: str) -> str:
    """HELP text for a metric: the creating site's ``doc=`` wins, then the
    curated table, then the prefix rules, then a generic fallback — every
    scraped sample is self-describing either way."""
    doc = _REGISTRY._docs.get(name) or _HELP_EXACT.get(name)
    if doc:
        return doc
    for prefix, text in _HELP_PREFIX:
        if name.startswith(prefix):
            return text
    return f"bluefog metric {name}"


def _prom_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _prom_name(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() or ch == "_" else "_")
    base = "".join(out)
    if base and base[0].isdigit():
        base = "_" + base
    return "bluefog_" + base


def _prom_value(v: float) -> str:
    if v == int(v) and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(float(v))


def prometheus_text(snap: Optional[dict] = None) -> str:
    """Render a snapshot in the Prometheus text exposition format v0.0.4
    (counters, gauges, and classic ``_bucket``/``_sum``/``_count``
    histograms, labeled with the publishing rank)."""
    if snap is None:
        snap = _REGISTRY.snapshot()
    rank = snap["meta"].get("rank", 0)
    label = f'{{rank="{rank}"}}'
    lines: List[str] = []
    for name in sorted(snap.get("counters", {})):
        m = _prom_name(name)
        lines.append(f"# HELP {m} {_prom_help(help_for(name))}")
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{label} "
                     f"{_prom_value(snap['counters'][name])}")
    for name in sorted(snap.get("gauges", {})):
        m = _prom_name(name)
        lines.append(f"# HELP {m} {_prom_help(help_for(name))}")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{label} {_prom_value(snap['gauges'][name])}")
    for name in sorted(snap.get("hists", {})):
        h = snap["hists"][name]
        m = _prom_name(name)
        lines.append(f"# HELP {m} {_prom_help(help_for(name))}")
        lines.append(f"# TYPE {m} histogram")
        cum = 0
        for bound, cnt in zip(h["bounds"], h["counts"]):
            cum += cnt
            lines.append(f'{m}_bucket{{rank="{rank}",le="{bound:g}"}} {cum}')
        cum += h["counts"][len(h["bounds"])]
        lines.append(f'{m}_bucket{{rank="{rank}",le="+Inf"}} {cum}')
        lines.append(f"{m}_sum{label} {_prom_value(h['sum'])}")
        lines.append(f"{m}_count{label} {h['count']}")
    return "\n".join(lines) + "\n"


# -- publication -------------------------------------------------------------

_WORLD_KEY = "bf.metrics.world"


def _metrics_key(rank: int) -> str:
    return f"bf.metrics.{rank}"


def publish_interval() -> float:
    """Seconds between snapshot publications; 0 = publication disabled.
    ``BLUEFOG_METRICS_PROM`` alone implies a 10 s default cadence."""
    raw = os.environ.get("BLUEFOG_METRICS_INTERVAL")
    if raw:
        try:
            return max(0.0, float(raw))
        except ValueError:
            logger.warning("BLUEFOG_METRICS_INTERVAL=%r is not a number; "
                           "metrics publication disabled", raw)
            return 0.0
    return 10.0 if os.environ.get("BLUEFOG_METRICS_PROM") else 0.0


def publication_enabled() -> bool:
    return publish_interval() > 0


_pub_mu = threading.Lock()
_last_publish = 0.0


def _write_prom_file(snap: dict) -> None:
    path = os.environ.get("BLUEFOG_METRICS_PROM")
    if not path:
        return
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(prometheus_text(snap))
        os.replace(tmp, path)  # atomic: scrapers never see a torn file
    except OSError as exc:
        logger.warning("metrics: prometheus dump to %s failed (%s)",
                       path, exc)


def publish_now(cl=None) -> Optional[dict]:
    """Publish one snapshot unconditionally (KV + prometheus file).
    Returns the snapshot, or None when nothing could be published."""
    return _publish(cl, force=True)


def maybe_publish(cl=None) -> None:
    """Interval-gated publish — the heartbeat tick calls this every cycle,
    so multi-controller jobs pay zero extra threads and no per-step RTT."""
    _publish(cl, force=False)


def _publish(cl, force: bool) -> Optional[dict]:
    global _last_publish
    interval = publish_interval()
    if not force and interval <= 0:
        return None
    now = time.monotonic()
    with _pub_mu:
        if not force and now - _last_publish < interval:
            return None
        _last_publish = now
    snap = _REGISTRY.snapshot()
    _emit_timeline_counters(snap)
    _write_prom_file(snap)
    from . import control_plane as _cp

    if cl is None and _cp.active():
        cl = _cp.client()
    if cl is not None:
        try:
            from .state import _global_state

            st = _global_state()
            cl.put_bytes(_metrics_key(snap["meta"]["rank"]),
                         pack_snapshot(snap))
            cl.put(_WORLD_KEY, st.process_count if st.initialized else 1)
        except Exception as exc:  # noqa: BLE001 — telemetry must not raise
            logger.debug("metrics publish failed (%s)", exc)
    return snap


def _emit_timeline_counters(snap: dict) -> None:
    """Mirror the gauges onto chrome counter tracks (mailbox depth, mass,
    epoch...) so traces and metrics share one vocabulary."""
    from .timeline import _timeline

    tl = _timeline()
    if tl is None:
        return
    for name, v in snap.get("gauges", {}).items():
        tl.counter(name, int(v))


class _Publisher:
    """Standalone cadence thread for deployments without a heartbeat
    monitor (single-controller jobs): the multi-controller path piggybacks
    :func:`maybe_publish` on the heartbeat tick instead."""

    def __init__(self) -> None:
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="bf-metrics-publisher", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(max(0.2, publish_interval() / 2.0)):
            try:
                maybe_publish()
                # the live time-series plane samples on the same cadence
                # (heartbeat jobs piggyback the monitor tick instead)
                from . import timeseries as _ts

                _ts.maybe_sample()
            except Exception as exc:  # noqa: BLE001 — observability thread
                logger.debug("metrics publisher tick failed (%s)", exc)


_publisher: Optional[_Publisher] = None


def start_publisher_if_needed(has_heartbeat: bool) -> None:
    """Called by ``bf.init``: start the cadence thread only when enabled
    AND no heartbeat monitor exists to piggyback on."""
    global _publisher
    if not publication_enabled() or has_heartbeat:
        return
    if _publisher is None:
        _publisher = _Publisher()
    _publisher.start()


def stop_publisher() -> None:
    global _publisher
    if _publisher is not None:
        _publisher.stop()
        _publisher = None


# -- cluster health ----------------------------------------------------------

def _straggler_threshold() -> int:
    try:
        return max(1, int(os.environ.get("BLUEFOG_STRAGGLER_STEPS", "3")))
    except ValueError:
        return 3


def health_from_snapshots(snaps: Dict[int, dict], world: int,
                          interval: Optional[float] = None,
                          now: Optional[float] = None) -> dict:
    """Merge per-rank snapshots into the cluster health view.

    * per-rank staleness (wall seconds since that rank published) and an
      ``alive`` verdict (stale past 3 publish intervals = presumed dead);
    * stragglers: ranks whose ``opt.step`` gauge trails the fleet maximum
      by at least ``BLUEFOG_STRAGGLER_STEPS`` (default 3) — the
      step-counter-spread detector;
    * push-sum mass conservation: sum of live ranks' ``pushsum.mass``
      gauges vs the mass they minted, within an ulp-scaled tolerance
      (conservation is exact in the protocol — r8 renormalization, r9
      mass split — so drift beyond rounding means lost deposits).
    """
    if interval is None:
        interval = publish_interval() or 10.0
    if now is None:
        now = time.time()
    stale_after = max(3.0 * interval, 15.0)
    ranks: Dict[int, dict] = {}
    steps: Dict[int, float] = {}
    epoch = 0
    repl_lag = under_repl = 0.0
    have_repl = False
    for pid, s in sorted(snaps.items()):
        staleness = max(0.0, now - s["meta"]["ts"])
        step = s["gauges"].get("opt.step")
        ranks[pid] = {
            "staleness_sec": staleness,
            "alive": staleness < stale_after,
            "incarnation": s["meta"].get("inc", 0),
            "step": None if step is None else int(step),
            # r17 rotation-drift signal: deposits dropped because the
            # origin's shard rotation disagreed with this owner's
            "shard_drops": int(s["counters"].get(
                "win.shard_stale_drops", 0)),
        }
        if step is not None:
            steps[pid] = step
        epoch = max(epoch, int(s["gauges"].get("membership.epoch", 0)))
        # r16 durability gauges (published by the heartbeat tick): the
        # single-endpoint probe's view of the sharded plane's health
        if "cp.repl_lag" in s["gauges"] or \
                "cp.under_replicated" in s["gauges"]:
            have_repl = True
            repl_lag = max(repl_lag, s["gauges"].get("cp.repl_lag", 0.0))
            under_repl = max(under_repl,
                             s["gauges"].get("cp.under_replicated", 0.0))
    missing = sorted(set(range(world)) - set(snaps))
    stragglers: List[int] = []
    if steps:
        mx = max(steps.values())
        thr = _straggler_threshold()
        stragglers = sorted(p for p, v in steps.items() if mx - v >= thr)
        # a rank too stale to publish is behind by definition
        stragglers = sorted(set(stragglers) | {
            p for p, r in ranks.items()
            if not r["alive"] and p in steps})
    live = {p: s for p, s in snaps.items() if ranks[p]["alive"]}
    mass = None
    if any("pushsum.mass" in s["gauges"] for s in live.values()):
        total = sum(s["gauges"].get("pushsum.mass", 0.0)
                    for s in live.values())
        minted = sum(s["gauges"].get("pushsum.minted", 0.0)
                     for s in live.values())
        drift = total - minted
        tol = max(1e-12,
                  float(np.spacing(max(1.0, abs(minted)))) * max(1, world))
        mass = {"total": total, "minted": minted, "drift": drift,
                "tolerance": tol, "conserved": abs(drift) <= tol}
    return {"world": world, "ranks": ranks, "missing": missing,
            "stragglers": stragglers, "mass": mass,
            "membership_epoch": epoch,
            "repl": ({"lag": repl_lag, "under_replicated": int(under_repl)}
                     if have_repl else None)}


def read_cluster_health(cl, world: Optional[int] = None) -> dict:
    """Build the health view from a raw control-plane client — usable from
    OUTSIDE the job (``bfrun --status``) as well as from within."""
    if world is None:
        world = int(cl.get(_WORLD_KEY)) or 1
    snaps: Dict[int, dict] = {}
    for r in range(world):
        try:
            blob = cl.get_bytes(_metrics_key(r))
        except OSError:
            continue
        if not blob:
            continue
        try:
            snaps[r] = unpack_snapshot(blob)
        except (ValueError, struct.error) as exc:
            logger.warning("metrics: snapshot for rank %d unreadable (%s)",
                           r, exc)
    return health_from_snapshots(snaps, world)


def cluster_health() -> dict:
    """The merged cluster health view (see :func:`health_from_snapshots`).

    Multi-controller jobs read every rank's published snapshot from the
    control-plane KV; without a control plane the view is built from this
    process's live registry (single-controller: local IS global). Publish
    cadence is ``BLUEFOG_METRICS_INTERVAL``; a rank that never published
    shows up in ``missing``.
    """
    from . import control_plane as _cp
    from .state import _global_state

    st = _global_state()
    world = st.process_count if st.initialized else 1
    if _cp.active():
        # Read peers from the KV, but use the LIVE registry for this
        # process: our own KV copy can be a full publish interval old (or
        # absent entirely when publication is disabled), and self-freshness
        # costs nothing.
        snaps = {_process_index(): _REGISTRY.snapshot()}
        cl = _cp.client()
        for r in set(range(world)) - {_process_index()}:
            try:
                blob = cl.get_bytes(_metrics_key(r))
                if blob:
                    snaps[r] = unpack_snapshot(blob)
            except (OSError, ValueError, struct.error):
                pass
        return health_from_snapshots(snaps, world)
    return health_from_snapshots({_process_index(): _REGISTRY.snapshot()},
                                 world)


def format_health(health: dict) -> str:
    """Human-readable rendering (the ``bfrun --status`` output)."""
    lines = [f"cluster health — world {health['world']}, membership epoch "
             f"{health['membership_epoch']}"]
    for pid in sorted(health["ranks"]):
        r = health["ranks"][pid]
        step = "-" if r["step"] is None else str(r["step"])
        flags = []
        if not r["alive"]:
            flags.append("STALE")
        if pid in health["stragglers"]:
            flags.append("STRAGGLER")
        drops = r.get("shard_drops", 0)
        lines.append(
            f"  rank {pid}: step {step}, inc {r['incarnation']}, "
            f"published {r['staleness_sec']:.1f}s ago"
            + (f", shard_drops {drops}" if drops else "")
            + (f"  [{' '.join(flags)}]" if flags else ""))
    for pid in health["missing"]:
        lines.append(f"  rank {pid}: no snapshot published")
    m = health["mass"]
    if m is not None:
        verdict = "conserved" if m["conserved"] else "DRIFTING"
        lines.append(
            f"  push-sum mass: total {m['total']:.12g} vs minted "
            f"{m['minted']:.12g} (drift {m['drift']:.3g}) — {verdict}")
    repl = health.get("repl")
    if repl is not None:
        state = (f"{repl['under_replicated']} shard(s) UNDER-REPLICATED"
                 if repl["under_replicated"] else "replicating")
        lines.append(f"  control-plane replication: max WAL lag "
                     f"{repl['lag']:.0f} — {state}")
    if health["stragglers"]:
        lines.append(f"  stragglers: {health['stragglers']}")
    return "\n".join(lines)
