"""Stall watchdog: warns about nonblocking ops that never complete.

Analog of BlueFog's coordinator stall check (reference: CheckForStalledTensors,
operations.cc:387-432, cadence STALL_WARNING_TIME=60s, operations.cc:46-47).
There is no negotiation table to inspect on TPU; instead the watchdog thread
polls the handle registry for dispatched-but-unfinished ops. A handle stuck
longer than the threshold usually means a multi-host collective where some
host never joined — the TPU equivalent of a missing rank.
"""

from __future__ import annotations

import threading

from . import flight as _flight
from . import handles
from . import metrics as _metrics
from .logging import logger
from .timeline import timeline_instant


class StallWatchdog:
    def __init__(self, warning_sec: float = 60.0, cycle_ms: float = 0.5) -> None:
        self.warning_sec = warning_sec
        # Poll at >= 1s: this thread is observability, not a dispatch loop, so
        # the reference's 0.5 ms cycle would be pure waste here.
        self.cycle_sec = max(cycle_ms / 1000.0, 1.0)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._warned: set[int] = set()

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="bf-stall-watchdog", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.cycle_sec):
            self._poll_flight_trigger()
            try:
                handles.sweep_completed_spans()
                pending = handles.outstanding()
            except Exception:  # never let observability kill the process
                continue
            # Prune warned entries for handles that completed or were
            # swept/evicted: without this the set grows one int per stalled-
            # then-finished handle for the LIFE of the job (long runs leak).
            # A handle that leaves the outstanding set and stalls again
            # later (e.g. re-registered by a timed-out synchronize) warns
            # again — it progressed in between, so the new stall is news.
            self._warned.intersection_update(pending)
            stalled = {
                h: (name, age)
                for h, (name, age) in pending.items()
                if age > self.warning_sec and h not in self._warned
            }
            for h, (name, age) in stalled.items():
                self._warned.add(h)
                # stalls are part of the telemetry plane, not just stderr:
                # a counter for the scrape and an instant event in the
                # trace, right where the silence is
                _metrics.counter("watchdog.stalls").inc()
                timeline_instant(name, "STALL")
                logger.warning(
                    "op '%s' (handle %d) has not completed for %.0f s; "
                    "likely a hung multi-host collective (some host absent)",
                    name, h, age,
                )
            if stalled:
                # black-box evidence of what led INTO the silence — the
                # wedge may never surface a Python exception to dump on
                # (rate-limited; one dump covers the whole stalled batch)
                _flight.recorder().instant("fatal.watchdog.stall")
                _flight.dump(reason="watchdog-stall", force=False)

    def _poll_flight_trigger(self) -> None:
        """`bfrun --dump` trigger poll for jobs without a heartbeat monitor
        (single-controller): the watchdog is the only always-on cadence
        thread there. Multi-controller jobs poll on the heartbeat tick."""
        try:
            from . import control_plane as _cp
            from .state import _global_state

            if _cp.active() and _global_state().peer_monitor is None:
                _flight.poll_remote_trigger(_cp.client())
        except Exception:  # noqa: BLE001 — observability thread
            pass
