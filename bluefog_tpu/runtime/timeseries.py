"""Live telemetry plane: streaming time-series, convergence gauges, alerts.

The r10 metrics registry answers "what is the value NOW" and the r12
flight recorder answers "what happened, after the fact". This module is
the layer between them: a fixed-memory, multi-resolution **history** of
the signals an operator (or the ROADMAP self-tuning controller) needs as
*continuous* inputs — per-edge wire bytes/s and deposit→drain transit
latency, step cadence, consensus distance and its decay rate, push-sum
mass trend, EF residual trend, shard-rotation drift — sampled on the
existing heartbeat tick and published as compact deltas under
``bf.ts.<rank>``, so nothing about the live view requires a postmortem
dump.

Four pieces (docs/observability.md):

* **Ring history** — every series keeps RRD-style tiers (~1 s / 10 s /
  60 s resolution) in preallocated numpy rings: recent samples at full
  resolution, hours of history downsampled, memory bounded forever. A
  :meth:`Series.add` is a handful of slotted stores (< 2 µs, asserted by
  ``make obs-smoke``), so sampling is always on.

* **Per-edge estimators** — fed from the flight recorder's flow events
  (``edge.<src>.<dst>`` starts, ``drain.<origin>`` finishes): live
  bytes/s, deposit counts, and transit-latency p50/p99 for pairs both
  sides of which this process observed. Recent raw flow digests ride the
  publication so an external consumer (``bfrun --top``,
  ``step_attribution --live``) can match pairs *across* ranks exactly
  like the postmortem merge does.

* **Convergence gauges** — the window optimizers record neighborhood
  consensus distance (L2 to the combine-weighted neighbor mean — see
  docs/observability.md for the identity that makes it one elementwise
  pass) into ``opt.consensus_dist``; the sampler derives the effective
  mixing rate from its decay plus trend/rate series for push-sum mass,
  EF residual norm, and ``win.shard_stale_drops`` velocity.

* **Rule engine** — declarative rank-local thresholds (defaults below,
  overridable via ``BLUEFOG_ALERT_RULES``) over any series: a sustained
  breach emits a flight instant (``alert.<name>``), bumps
  ``alert.fired``, and publishes under ``bf.alerts.<rank>``.

Collection is always on unless ``BLUEFOG_TS_DISABLE=1``; publication
rides the metrics cadence (``BLUEFOG_TS_INTERVAL`` overrides). Like the
registry, a rare lost sample under a cross-thread race is an acceptable
telemetry error.
"""

from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import knob_env
from .logging import logger

TS_KEY_FMT = "bf.ts.{rank}"
ALERTS_KEY_FMT = "bf.alerts.{rank}"

# Serve clients publish their own time-series docs in a rank band far
# above any trainer world size (``bf.ts.<4096 + cid>``), so client and
# trainer publications never collide and a consumer can tell the planes
# apart by rank alone.
SERVE_TS_RANK_BASE = 4096

_PACK_MAGIC = b"BFT1"

# (resolution seconds, ring slots): ~4 min at 1 s, 1 h at 10 s, 6 h at
# 60 s. Fixed — the whole store is a few hundred KB regardless of job
# length.
TIERS: Tuple[Tuple[float, int], ...] = ((1.0, 256), (10.0, 360),
                                        (60.0, 360))

# Every registry instrument the sampler records each tick:
# (instrument, instrument kind, within-slot aggregation). Checked by the
# bfcheck [metrics] analyzer — a binding naming an undeclared instrument
# fails `make check`. Counters are stored cumulative (consumers and the
# rule grammar use the derived `.rate` series below).
TS_BINDINGS: Tuple[Tuple[str, str, str], ...] = (
    ("opt.step", "gauge", "last"),
    ("opt.consensus_dist", "gauge", "last"),
    ("pushsum.mass", "gauge", "last"),
    ("pushsum.debias_drift", "gauge", "max"),
    ("win.codec.residual_norm", "gauge", "last"),
    ("win.shard_stale_drops", "counter", "last"),
    ("win.deposits_sent", "counter", "last"),
    ("win.deposits_drained", "counter", "last"),
    ("win.drain_bytes", "counter", "last"),
    ("hb.dead_peers", "gauge", "max"),
    ("hb.suspect_peers", "gauge", "max"),
    ("membership.epoch", "gauge", "last"),
    ("cp.repl_lag", "gauge", "max"),
    ("cp.under_replicated", "gauge", "max"),
    ("cp.server.mailbox_records", "gauge", "max"),
    ("cp.server.mailbox_bytes", "gauge", "max"),
    # serving-plane SLO series (docs/slo.md) — recorded by ServeClient,
    # absent (and silently skipped) in processes that never serve
    ("slo.requests", "counter", "last"),
    ("slo.shed", "counter", "last"),
    ("slo.breach.serve_p50", "counter", "last"),
    ("slo.breach.serve_p99", "counter", "last"),
    ("slo.breach.serve_avail", "counter", "last"),
    ("slo.breach.serve_staleness", "counter", "last"),
    ("slo.request_p50_us", "gauge", "last"),
    ("slo.request_p99_us", "gauge", "max"),
    ("slo.staleness_p99_ver", "gauge", "max"),
    ("slo.phase.admit.p50_us", "gauge", "last"),
    ("slo.phase.admit.p99_us", "gauge", "last"),
    ("slo.phase.queue.p50_us", "gauge", "last"),
    ("slo.phase.queue.p99_us", "gauge", "last"),
    ("slo.phase.swap_blocked.p50_us", "gauge", "last"),
    ("slo.phase.swap_blocked.p99_us", "gauge", "last"),
    ("slo.phase.linger.p50_us", "gauge", "last"),
    ("slo.phase.linger.p99_us", "gauge", "last"),
    ("slo.phase.decode.p50_us", "gauge", "last"),
    ("slo.phase.decode.p99_us", "gauge", "last"),
    ("slo.phase.reply.p50_us", "gauge", "last"),
    ("slo.phase.reply.p99_us", "gauge", "last"),
    ("trace.requests", "counter", "last"),
)

# Series the sampler computes itself (no registry instrument behind
# them) — declared here so the bfcheck [metrics] analyzer can resolve
# alert-rule and binding references against a closed vocabulary.
DERIVED_SERIES: Tuple[str, ...] = (
    "opt.mixing_rate",
    "opt.consensus_stalled",
)

# Counters (and the monotone step gauge) that additionally maintain a
# live `<name>.rate` series (units/second between samples).
RATE_SERIES: Tuple[str, ...] = (
    "opt.step",
    "win.shard_stale_drops",
    "win.deposits_sent",
    "win.deposits_drained",
    "win.drain_bytes",
    "slo.requests",
    "slo.shed",
)


# -- ring history ------------------------------------------------------------

class _Tier:
    """One resolution tier: a preallocated (time, value) ring.

    Samples land in the slot ``int(t / res)``; a slot in progress
    aggregates in scalars and is flushed into the ring when time moves to
    the next slot, so memory never grows with job length."""

    __slots__ = ("res", "cap", "t", "v", "n", "_slot", "_agg", "_sum",
                 "_cnt")

    def __init__(self, res: float, cap: int, agg: str) -> None:
        self.res = res
        self.cap = cap
        self.t = np.zeros(cap, np.float64)
        self.v = np.zeros(cap, np.float64)
        self.n = 0
        self._slot = -1
        self._agg = agg
        self._sum = 0.0
        self._cnt = 0

    def add(self, t: float, value: float) -> None:
        slot = int(t / self.res)
        if slot != self._slot:
            if self._slot >= 0:
                i = self.n % self.cap
                self.t[i] = self._slot * self.res
                self.v[i] = self._value()
                self.n += 1
            self._slot = slot
            self._sum = value
            self._cnt = 1
            return
        if self._agg == "last":
            self._sum = value
        elif self._agg == "max":
            self._sum = value if value > self._sum else self._sum
        elif self._agg == "sum":
            self._sum += value
        else:  # mean
            self._sum += value
            self._cnt += 1

    def _value(self) -> float:
        if self._agg == "mean" and self._cnt:
            return self._sum / self._cnt
        return self._sum

    def samples(self) -> Tuple[np.ndarray, np.ndarray]:
        """(times, values) oldest→newest, the in-progress slot included."""
        count = min(self.n, self.cap)
        idx = (self.n - count + np.arange(count)) % self.cap
        t = self.t[idx]
        v = self.v[idx]
        if self._slot >= 0:
            t = np.append(t, self._slot * self.res)
            v = np.append(v, self._value())
        return t, v


class Series:
    """One named series with RRD-style tiers (see module docstring)."""

    __slots__ = ("name", "kind", "agg", "tiers", "last_t", "last_v")

    def __init__(self, name: str, kind: str = "gauge",
                 agg: str = "last") -> None:
        self.name = name
        self.kind = kind
        self.agg = agg
        self.tiers = [_Tier(res, cap, agg) for res, cap in TIERS]
        self.last_t = 0.0
        self.last_v = float("nan")

    def add(self, t: float, value: float) -> None:
        """The hot path: one slotted add per tier plus two scalar
        stores — no allocation, no lock (a rare torn sample is an
        acceptable telemetry error, same trade as the registry)."""
        value = float(value)
        self.tiers[0].add(t, value)
        self.tiers[1].add(t, value)
        self.tiers[2].add(t, value)
        self.last_t = t
        self.last_v = value

    def latest(self) -> Tuple[float, float]:
        return self.last_t, self.last_v

    def window(self, span_sec: float) -> Tuple[np.ndarray, np.ndarray]:
        """Samples covering the last ``span_sec``: of the three tiers,
        the one holding the MOST samples inside the window (finer tiers
        win ties). A coarse tier only wins when the finer rings have
        already evicted the window's early samples."""
        now = self.last_t
        best = None
        for tier in self.tiers:
            tt, tv = tier.samples()
            keep = tt >= now - span_sec
            tt, tv = tt[keep], tv[keep]
            covered = float(tt[-1] - tt[0]) if len(tt) else -1.0
            # strictly greater: finer tiers (iterated first) win ties
            if best is None or covered > best[0]:
                best = (covered, tt, tv)
        return best[1], best[2]

    def rate(self, span_sec: float = 60.0) -> Optional[float]:
        """Average units/second across the window (for counters: the
        cumulative-value delta over elapsed time)."""
        t, v = self.window(span_sec)
        if len(t) < 2 or t[-1] <= t[0]:
            return None
        return float((v[-1] - v[0]) / (t[-1] - t[0]))

    def trend(self, span_sec: float = 120.0) -> Optional[float]:
        """Least-squares slope (units/second) over the window — the
        mass-drift / residual-norm trend signal."""
        t, v = self.window(span_sec)
        if len(t) < 3:
            return None
        t = t - t[0]
        denom = float(np.sum((t - t.mean()) ** 2))
        if denom <= 0:
            return None
        return float(np.sum((t - t.mean()) * (v - v.mean())) / denom)


# -- per-edge live estimators ------------------------------------------------

_TRANSIT_RING = 128


class EdgeStats:
    """Live per-edge estimator fed from flow events."""

    __slots__ = ("bytes", "deposits", "transit_us", "_tn", "_pub_bytes",
                 "_pub_t")

    def __init__(self) -> None:
        self.bytes = 0.0
        self.deposits = 0
        self.transit_us = np.zeros(_TRANSIT_RING, np.float64)
        self._tn = 0
        self._pub_bytes = 0.0
        self._pub_t = 0.0

    def on_start(self, nbytes: float) -> None:
        self.bytes += nbytes
        self.deposits += 1

    def on_transit(self, us: float) -> None:
        self.transit_us[self._tn % _TRANSIT_RING] = us
        self._tn += 1

    def percentiles(self) -> Tuple[Optional[float], Optional[float]]:
        n = min(self._tn, _TRANSIT_RING)
        if n == 0:
            return None, None
        window = self.transit_us[:n]
        return (float(np.percentile(window, 50)),
                float(np.percentile(window, 99)))

    def bps_since_publish(self, now: float) -> float:
        dt = now - self._pub_t if self._pub_t else 0.0
        bps = (self.bytes - self._pub_bytes) / dt if dt > 0 else 0.0
        self._pub_bytes = self.bytes
        self._pub_t = now
        return bps


# -- alert rules -------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Rule:
    """One declarative threshold: fire when ``series <op> threshold``
    holds for at least ``for_sec`` seconds of samples."""

    name: str
    series: str
    op: str          # one of > >= < <=
    threshold: float
    for_sec: float
    doc: str = ""


# Default rank-local rules (docs/observability.md has the grammar). Every
# referenced series must exist as a binding, a derived `.rate`, or a
# derived gauge — the bfcheck [metrics] analyzer enforces it.
DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0,
         "no optimizer-step progress while peers keep publishing"),
    Rule("mass_drift", "pushsum.debias_drift", ">", 0.5, 30.0,
         "push-sum de-bias scalar wandering far from 1"),
    Rule("wal_lag", "cp.repl_lag", ">", 4096.0, 15.0,
         "control-plane WAL replication lagging the successor"),
    Rule("mailbox_depth", "cp.server.mailbox_records", ">", 50000.0, 15.0,
         "served mailboxes backing up (owner not draining)"),
    Rule("consensus_stall", "opt.consensus_stalled", ">", 0.5, 60.0,
         "consensus distance positive but no longer decaying"),
    Rule("shard_drift", "win.shard_stale_drops.rate", ">", 0.0, 30.0,
         "sustained shard-rotation drift (a controller's comm rounds "
         "desynced)"),
)

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
}


def parse_rules(spec: Optional[str]) -> Tuple[Rule, ...]:
    """Rules = defaults overridden/extended by ``BLUEFOG_ALERT_RULES``.

    Grammar (comma-separated):
      ``name:series>value:for=SEC``  — add or replace a rule by name
      ``name:off``                   — disable a default rule
    Example: ``wal_lag:cp.repl_lag>100:for=5,mass_drift:off``.
    A malformed term is warned about and skipped (telemetry config must
    never take a job down)."""
    rules = {r.name: r for r in DEFAULT_RULES}
    if not spec:
        return tuple(rules.values())
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        parts = term.split(":")
        name = parts[0].strip()
        if len(parts) == 2 and parts[1].strip() == "off":
            rules.pop(name, None)
            continue
        try:
            cond = parts[1].strip()
            for op in (">=", "<=", ">", "<"):
                if op in cond:
                    series, thr = cond.split(op, 1)
                    break
            else:
                raise ValueError("no comparison operator")
            for_sec = 0.0
            for extra in parts[2:]:
                k, _, v = extra.partition("=")
                if k.strip() == "for":
                    for_sec = float(v)
            rules[name] = Rule(name, series.strip(), op, float(thr),
                               for_sec)
        except (ValueError, IndexError) as exc:
            logger.warning("BLUEFOG_ALERT_RULES: skipping malformed term "
                           "%r (%s)", term, exc)
    return tuple(rules.values())


class _RuleState:
    __slots__ = ("breach_since", "active", "value")

    def __init__(self) -> None:
        self.breach_since: Optional[float] = None
        self.active = False
        self.value = 0.0


# -- SLO objectives (docs/slo.md) --------------------------------------------

# The closed kind vocabulary keeps every derived series name static, so
# the bfcheck [metrics] analyzer can resolve the whole namespace.
SLO_KINDS = ("serve_p50", "serve_p99", "serve_avail", "serve_staleness")

# slow burn window = fast window x this (the classic 5m/1h pairing)
SLO_SLOW_FACTOR = 12.0


@dataclasses.dataclass(frozen=True)
class SLO:
    """One declarative serving objective.

    ``target`` is in the kind's native unit: microseconds for the
    latency kinds, snapshot versions for staleness, percent for
    availability. ``budget`` is the allowed error fraction the burn rate
    is measured against (p99 -> 1%, p50 -> 50%, staleness -> 1%,
    availability -> 1 - target)."""

    name: str
    target: float
    window_s: float
    budget: float


def _parse_duration_s(tok: str) -> float:
    tok = tok.strip().lower()
    for suf, mult in (("ms", 1e-3), ("us", 1e-6), ("h", 3600.0),
                      ("m", 60.0), ("s", 1.0)):
        if tok.endswith(suf):
            return float(tok[:-len(suf)]) * mult
    return float(tok)


def parse_slos(spec) -> Tuple[SLO, ...]:
    """``BLUEFOG_SLO`` grammar — comma-separated ``kind:target@window``:

      ``serve_p99:50ms@5m``       at most 1% of requests slower than
                                  50 ms, burn measured over a 5 m fast
                                  window (and a 12x slow window)
      ``serve_p50:2ms@1m``        at most 50% slower than 2 ms
      ``serve_avail:99.9@1h``     at least 99.9% of requests admitted
      ``serve_staleness:3ver@5m`` at most 1% answered more than 3
                                  snapshot versions behind the fence

    A malformed term is warned about and skipped (telemetry config must
    never take a job down); the window defaults to 5 m when omitted."""
    out: List[SLO] = []
    if not spec:
        return tuple(out)
    for term in str(spec).split(","):
        term = term.strip()
        if not term:
            continue
        try:
            kind, _, rest = term.partition(":")
            kind = kind.strip()
            if kind not in SLO_KINDS or not rest:
                raise ValueError(f"unknown SLO kind {kind!r}")
            tgt, _, win = rest.partition("@")
            window_s = max(1.0, _parse_duration_s(win)) if win else 300.0
            tgt = tgt.strip().lower()
            if kind == "serve_avail":
                pct = float(tgt.rstrip("%"))
                target, budget = pct, max(1e-6, 1.0 - pct / 100.0)
            elif kind == "serve_staleness":
                target = float(tgt[:-3]) if tgt.endswith("ver") \
                    else float(tgt)
                budget = 0.01
            else:
                target = _parse_duration_s(tgt) * 1e6  # -> microseconds
                budget = 0.5 if kind == "serve_p50" else 0.01
            out.append(SLO(kind, target, window_s, budget))
        except (ValueError, IndexError) as exc:
            logger.warning("BLUEFOG_SLO: skipping malformed term %r (%s)",
                           term, exc)
    return tuple(out)


# -- the store ---------------------------------------------------------------

_PENDING_FLOWS_CAP = 4096     # unmatched starts retained for matching
_FLOW_DIGEST_CAP = 256        # raw flow events shipped per publication
_SCAN_CAP = 8192              # flight-ring events processed per tick
_FULL_EVERY = 16              # every Nth publication carries tier history


class TimeSeriesStore:
    """Process-global store: series + edge estimators + rule engine +
    the ``bf.ts.<rank>`` publisher."""

    def __init__(self) -> None:
        self._mu = threading.Lock()      # series creation only
        self._series: Dict[str, Series] = {}
        self._edges: Dict[str, EdgeStats] = {}
        self._pending: Dict[int, Tuple[float, float, int, int]] = {}
        self._flow_starts: List[list] = []    # publication digest (delta)
        self._flow_finishes: List[list] = []
        self._scan_cursor = 0
        self._last_sample = 0.0
        self._last_publish = 0.0
        self._last_counter: Dict[str, Tuple[float, float]] = {}
        self._pub_mark: Dict[str, float] = {}  # series -> last shipped t
        self._seq = 0
        self._rules = parse_rules(knob_env("BLUEFOG_ALERT_RULES"))
        self._rule_state = {r.name: _RuleState() for r in self._rules}
        self._slos = parse_slos(knob_env("BLUEFOG_SLO"))
        self._slo_state = {o.name: _RuleState() for o in self._slos}
        self._slo_burn = float(knob_env("BLUEFOG_SLO_BURN"))
        # raw (t, v) consensus samples for the mixing-rate fit: the 1 s
        # tier collapses several same-second samples into one slot, and
        # the fit wants every point
        self._consensus_raw: List[Tuple[float, float]] = []

    # -- series ------------------------------------------------------------

    def series(self, name: str, kind: str = "gauge",
               agg: str = "last") -> Series:
        s = self._series.get(name)
        if s is None:
            with self._mu:
                s = self._series.setdefault(name, Series(name, kind, agg))
        return s

    def names(self) -> List[str]:
        return sorted(self._series)

    def edges(self) -> Dict[str, EdgeStats]:
        return self._edges

    # -- sampling ----------------------------------------------------------

    def sample(self, now: Optional[float] = None) -> None:
        """One sampling pass: registry bindings, derived rates/gauges,
        flow-event scan, rule evaluation. Bounded work per call; never
        raises (telemetry must not take the tick down)."""
        from . import metrics as _metrics

        if now is None:
            now = time.time()
        reg = _metrics.registry()
        for name, kind, agg in TS_BINDINGS:
            if name.startswith("cp.server."):
                continue  # server stats handled as a batch below
            inst = reg._gauges.get(name)
            v = None
            if inst is not None:
                v = inst.value
            else:
                c = reg._counters.get(name)
                if c is not None:
                    v = float(c.value)
            if v is None:
                continue
            self.series(name, kind, agg).add(now, v)
            if name in RATE_SERIES:
                self._record_rate(name, now, v)
        try:
            srv = _metrics._server_stats_flat()
        except Exception:  # noqa: BLE001 — telemetry must not raise
            srv = {}
        for name, kind, agg in TS_BINDINGS:
            if name.startswith("cp.server.") and name in srv:
                self.series(name, kind, agg).add(now, srv[name])
        self._scan_flows(now)
        self._derive(now)
        self._evaluate_rules(now)
        self._evaluate_slos(now)
        self._last_sample = now

    def _record_rate(self, name: str, now: float, v: float) -> None:
        prev = self._last_counter.get(name)
        self._last_counter[name] = (now, v)
        if prev is None or now <= prev[0]:
            return
        self.series(f"{name}.rate", "gauge", "mean").add(
            now, (v - prev[1]) / (now - prev[0]))

    def _scan_flows(self, now: float) -> None:
        """Feed edge estimators from the flight ring's flow events written
        since the last pass (no extra hot-path hook: the events the r12
        recorder already emits ARE the sensor)."""
        from . import flight as _flight

        rec = _flight.recorder()
        n = getattr(rec, "_n", 0)
        if n <= self._scan_cursor:
            self._scan_cursor = min(self._scan_cursor, n)
            return
        cap = getattr(rec, "capacity", 0)
        if not cap:
            return
        start = max(self._scan_cursor, n - cap, n - _SCAN_CAP)
        names = rec._names
        for i in range(start, n):
            j = i & rec._mask
            kind = int(rec._kind[j])
            if kind != _flight.FLOW_S and kind != _flight.FLOW_F:
                continue
            nid = int(rec._name[j])
            name = names[nid] if 0 <= nid < len(names) else ""
            t_us = rec._wall_us(int(rec._t[j]))
            fid = int(rec._b[j])
            nbytes = float(rec._a[j])
            if kind == _flight.FLOW_S and name.startswith("edge."):
                try:
                    _, src, dst = name.split(".")
                    src_i, dst_i = int(src), int(dst)
                except ValueError:
                    continue
                edge = f"{src_i}->{dst_i}"
                st = self._edges.get(edge)
                if st is None:
                    st = self._edges[edge] = EdgeStats()
                st.on_start(nbytes)
                if len(self._pending) < _PENDING_FLOWS_CAP:
                    self._pending[fid] = (t_us, nbytes, src_i, dst_i)
                if len(self._flow_starts) < _FLOW_DIGEST_CAP:
                    self._flow_starts.append(
                        [fid, int(t_us), int(nbytes), src_i, dst_i])
            elif kind == _flight.FLOW_F:
                pend = self._pending.pop(fid, None)
                if pend is not None:
                    t0, _, src_i, dst_i = pend
                    st = self._edges.get(f"{src_i}->{dst_i}")
                    if st is not None and t_us >= t0:
                        st.on_transit(t_us - t0)
                if len(self._flow_finishes) < _FLOW_DIGEST_CAP:
                    self._flow_finishes.append([fid, int(t_us)])
        self._scan_cursor = n

    def _derive(self, now: float) -> None:
        """Derived convergence gauges: effective mixing rate fit from the
        consensus-distance decay, plus the stall flag the rule engine
        thresholds (distance positive but no longer shrinking)."""
        from . import metrics as _metrics

        d = self._series.get("opt.consensus_dist")
        if d is None:
            return
        if not self._consensus_raw or \
                d.last_t > self._consensus_raw[-1][0]:
            self._consensus_raw.append((d.last_t, d.last_v))
            del self._consensus_raw[:-64]
        # fit points: the 1 s tier window, falling back to the raw ring
        # when several samples collapsed into one wall-second slot
        t, v = d.window(TIERS[0][0] * 16)
        pts = [(float(a), float(b)) for a, b in zip(t, v) if b > 0]
        if len(pts) < 3:
            pts = [(a, b) for a, b in self._consensus_raw
                   if a >= now - TIERS[0][0] * 16 and b > 0]
        rate = None
        if len(pts) >= 3:
            tt = np.asarray([a for a, _ in pts])
            vv = np.asarray([b for _, b in pts])
            span = tt[-1] - tt[0]
            if span > 0:
                # geometric decay per second, fit on the log values
                slope = np.polyfit(tt - tt[0], np.log(vv), 1)[0]
                rate = float(math.exp(np.clip(slope, -20.0, 2.0)))
        if rate is not None:
            self.series("opt.mixing_rate", "gauge", "last").add(now, rate)
            _metrics.gauge("opt.mixing_rate").set(rate)
        stalled = 1.0 if (rate is not None and rate >= 0.999
                          and d.last_v > 1e-9) else 0.0
        self.series("opt.consensus_stalled", "gauge", "max").add(
            now, stalled)

    def _evaluate_rules(self, now: float) -> None:
        from . import flight as _flight
        from . import metrics as _metrics

        for rule in self._rules:
            s = self._series.get(rule.series)
            if s is None or s.last_t == 0.0:
                continue
            st = self._rule_state[rule.name]
            st.value = s.last_v
            if _OPS[rule.op](s.last_v, rule.threshold):
                if st.breach_since is None:
                    st.breach_since = now
                if not st.active and \
                        now - st.breach_since >= rule.for_sec:
                    st.active = True
                    _metrics.counter("alert.fired").inc()
                    _flight.recorder().instant(f"alert.{rule.name}",
                                               a=s.last_v)
                    logger.warning(
                        "alert %s: %s %s %g held for %.0f s (value %g) — "
                        "docs/observability.md", rule.name, rule.series,
                        rule.op, rule.threshold, rule.for_sec, s.last_v)
            else:
                if st.active:
                    _flight.recorder().instant(
                        f"alert.{rule.name}.clear", a=s.last_v)
                st.breach_since = None
                st.active = False

    def _window_delta(self, name: str, span: float) -> Optional[float]:
        s = self._series.get(name)
        if s is None:
            return None
        t, v = s.window(span)
        if len(t) < 2:
            return None
        return float(v[-1] - v[0])

    def _evaluate_slos(self, now: float) -> None:
        """Multi-window burn-rate evaluation (docs/slo.md): for each
        objective, the error fraction over the fast (declared) window
        and a ``SLO_SLOW_FACTOR``x slow window, each divided by the
        error budget. ``alert.slo.<kind>`` fires when BOTH burn rates
        exceed ``BLUEFOG_SLO_BURN`` — a fast-only spike doesn't page, a
        long-gone burst aging through the slow window alone doesn't
        either — and clears as soon as the fast window recovers. The
        windows do the sustaining, so there is no ``for_sec`` here."""
        if not self._slos:
            return
        from . import flight as _flight
        from . import metrics as _metrics

        for obj in self._slos:
            err_series = "slo.shed" if obj.name == "serve_avail" \
                else f"slo.breach.{obj.name}"
            burns = {}
            for tag, win in (("fast", obj.window_s),
                             ("slow", obj.window_s * SLO_SLOW_FACTOR)):
                dreq = self._window_delta("slo.requests", win)
                derr = self._window_delta(err_series, win)
                err = (derr / dreq) if dreq and derr is not None else 0.0
                burns[tag] = err / obj.budget
                self.series(f"slo.burn.{obj.name}.{tag}", "gauge",
                            "last").add(now, burns[tag])
            # error budget left in the slow window; <= 0 is exhaustion
            # (the --status --strict exit-2 signal)
            self.series(f"slo.budget.{obj.name}", "gauge", "last").add(
                now, 1.0 - burns["slow"])
            st = self._slo_state[obj.name]
            st.value = burns["fast"]
            if burns["fast"] >= self._slo_burn and \
                    burns["slow"] >= self._slo_burn:
                if st.breach_since is None:
                    st.breach_since = now
                if not st.active:
                    st.active = True
                    _metrics.counter("alert.fired").inc()
                    _flight.recorder().instant(f"alert.slo.{obj.name}",
                                               a=burns["fast"])
                    logger.warning(
                        "SLO alert slo.%s: burn rate fast %.2f / slow "
                        "%.2f over threshold %.2f (budget %.4f) — "
                        "docs/slo.md", obj.name, burns["fast"],
                        burns["slow"], self._slo_burn, obj.budget)
            elif burns["fast"] < self._slo_burn:
                if st.active:
                    _flight.recorder().instant(
                        f"alert.slo.{obj.name}.clear", a=burns["fast"])
                st.breach_since = None
                st.active = False

    def slo_status(self) -> List[dict]:
        """Per-objective burn/budget snapshot (``--top``'s SLO section
        and the ``--status --strict`` budget-exhaustion finding)."""
        out = []
        for obj in self._slos:
            def _last(name):
                s = self._series.get(name)
                return s.last_v if s is not None and s.last_t else None

            out.append({
                "name": obj.name, "target": obj.target,
                "window_s": obj.window_s, "budget": obj.budget,
                "burn_fast": _last(f"slo.burn.{obj.name}.fast"),
                "burn_slow": _last(f"slo.burn.{obj.name}.slow"),
                "budget_remaining": _last(f"slo.budget.{obj.name}"),
                "active": self._slo_state[obj.name].active,
            })
        return out

    def active_alerts(self) -> List[dict]:
        out = []
        for rule in self._rules:
            st = self._rule_state[rule.name]
            if st.active:
                out.append({"name": rule.name, "series": rule.series,
                            "since": st.breach_since, "value": st.value})
        for obj in self._slos:
            st = self._slo_state[obj.name]
            if st.active:
                out.append({"name": f"slo.{obj.name}",
                            "series": f"slo.burn.{obj.name}.fast",
                            "since": st.breach_since, "value": st.value})
        return out

    # -- publication -------------------------------------------------------

    def build_doc(self, rank: int, inc: int, now: float,
                  interval: float) -> dict:
        """The ``bf.ts.<rank>`` document: per-series samples newer than
        the previous publication (delta encoding — timestamps ship as
        millisecond offsets), the per-edge estimator summaries, the raw
        flow digests for cross-rank matching, active alerts, and — every
        ``_FULL_EVERY``-th publication — the downsampled tier history so
        a late-joining consumer still gets the past."""
        full = (self._seq % _FULL_EVERY) == 0
        series: Dict[str, dict] = {}
        hist: Dict[str, dict] = {}
        latest: Dict[str, list] = {}
        for name in sorted(self._series):
            s = self._series[name]
            if s.last_t:
                # constant-size current-value row: a consumer reading
                # only the newest blob (late joiner, one-shot probe)
                # still sees every series even when its delta is empty
                latest[name] = [int(s.last_t * 1000),
                                float(f"{s.last_v:.6g}")]
            t, v = s.tiers[0].samples()
            mark = self._pub_mark.get(name, 0.0)
            keep = t > mark
            if np.any(keep):
                tt = t[keep]
                series[name] = {
                    "kind": s.kind,
                    "t0_ms": int(tt[0] * 1000),
                    "dt_ms": np.diff(tt * 1000).astype(np.int64).tolist(),
                    "v": [float(f"{x:.6g}") for x in v[keep]],
                }
                self._pub_mark[name] = float(tt[-1])
            if full:
                htiers = {}
                for tier in s.tiers[1:]:
                    ht, hv = tier.samples()
                    if len(ht):
                        htiers[str(int(tier.res))] = [
                            [int(x * 1000) for x in ht],
                            [float(f"{x:.6g}") for x in hv]]
                if htiers:
                    hist[name] = htiers
        edges = {}
        for edge in sorted(self._edges):
            st = self._edges[edge]
            p50, p99 = st.percentiles()
            edges[edge] = {"bytes": st.bytes, "deposits": st.deposits,
                           "bps": st.bps_since_publish(now),
                           "p50_us": p50, "p99_us": p99}
        starts, self._flow_starts = self._flow_starts, []
        finishes, self._flow_finishes = self._flow_finishes, []
        doc = {
            "schema": 1,
            "rank": rank,
            "inc": inc,
            "ts": now,
            "seq": self._seq,
            "interval": interval,
            "series": series,
            "latest": latest,
            "edges": edges,
            "flows": {"starts": starts, "finishes": finishes},
            "alerts": self.active_alerts(),
        }
        if hist:
            doc["hist"] = hist
        self._seq += 1
        return doc


def pack_doc(doc: dict) -> bytes:
    """Wire form: magic + zlib'd JSON — readable without numpy or jax."""
    return _PACK_MAGIC + zlib.compress(
        json.dumps(doc, separators=(",", ":")).encode(), level=6)


def unpack_doc(blob: bytes) -> dict:
    if len(blob) < 4 or blob[:4] != _PACK_MAGIC:
        raise ValueError("not a bluefog time-series blob (bad magic)")
    return json.loads(zlib.decompress(blob[4:]).decode())


# -- process-global wiring ---------------------------------------------------

_store_mu = threading.Lock()
_store: Optional[TimeSeriesStore] = None


def store() -> TimeSeriesStore:
    global _store
    s = _store
    if s is None:
        with _store_mu:
            if _store is None:
                _store = TimeSeriesStore()
            s = _store
    return s


def reset_for_job() -> None:
    """Fresh store per ``bf.init`` (re-reads the rule/disable knobs)."""
    global _store
    with _store_mu:
        _store = TimeSeriesStore()


def enabled() -> bool:
    return not knob_env("BLUEFOG_TS_DISABLE")


def publish_interval() -> float:
    """Publication cadence: ``BLUEFOG_TS_INTERVAL``, else the metrics
    cadence, else a 5 s default when a control plane is attached."""
    raw = knob_env("BLUEFOG_TS_INTERVAL")
    if raw is not None:
        return max(0.0, float(raw))
    from . import metrics as _metrics

    return _metrics.publish_interval() or 5.0


_SAMPLE_MIN_GAP = 0.9  # seconds — the 1 s tier's natural cadence


def maybe_sample(cl=None, force: bool = False,
                 publish: Optional[bool] = None) -> None:
    """Sampling entry point: the heartbeat tick, the metrics publisher
    thread, and the window optimizers' step path all funnel here. A
    monotonic-time gate keeps the cadence ~1 Hz no matter how often it is
    called; publication piggybacks on its own interval."""
    if not enabled():
        return
    s = store()
    now = time.time()
    if not force and now - s._last_sample < _SAMPLE_MIN_GAP:
        return
    try:
        s.sample(now)
    except Exception as exc:  # noqa: BLE001 — observability never raises
        logger.debug("timeseries sample failed (%s)", exc)
        return
    interval = publish_interval()
    want_pub = publish if publish is not None else (
        interval > 0 and now - s._last_publish >= interval)
    if want_pub:
        publish_now(cl, now=now)


def publish_now(cl=None, now: Optional[float] = None) -> Optional[dict]:
    """Publish one ``bf.ts.<rank>`` delta (and ``bf.alerts.<rank>`` when
    alerts are active). Returns the doc, or None when no client."""
    from . import control_plane as _cp
    from . import metrics as _metrics

    if not enabled():
        return None
    s = store()
    if now is None:
        now = time.time()
    if cl is None and _cp.active():
        cl = _cp.client()
    if cl is None:
        return None
    rank = _metrics._process_index()
    try:
        inc = _cp.incarnation()
    except Exception:  # noqa: BLE001
        inc = 0
    doc = s.build_doc(rank, inc, now, publish_interval())
    try:
        cl.put_bytes(TS_KEY_FMT.format(rank=rank), pack_doc(doc))
        if doc["alerts"]:
            cl.put_bytes(ALERTS_KEY_FMT.format(rank=rank),
                         zlib.compress(json.dumps(doc["alerts"]).encode()))
        s._last_publish = now
    except Exception as exc:  # noqa: BLE001 — telemetry must not raise
        logger.debug("timeseries publish failed (%s)", exc)
        return None
    return doc


# -- consumer side (raw client, no jax) --------------------------------------

def read_rank(cl, rank: int) -> Optional[dict]:
    """One rank's latest published doc (None when absent/unreadable)."""
    try:
        blob = cl.get_bytes(TS_KEY_FMT.format(rank=rank))
    except (OSError, RuntimeError):
        return None
    if not blob:
        return None
    try:
        return unpack_doc(bytes(blob))
    except (ValueError, zlib.error, json.JSONDecodeError):
        return None


class HistoryAccumulator:
    """Consumer-side merge of successive delta publications: per-rank
    series history, cross-rank flow matching (deposit on rank A, drain
    on rank B), and silent-rank detection."""

    def __init__(self, cap: int = 2048) -> None:
        self.cap = cap
        self.series: Dict[Tuple[int, str], List[Tuple[float, float]]] = {}
        self.edges: Dict[int, dict] = {}
        self.alerts: Dict[int, list] = {}
        self.meta: Dict[int, dict] = {}
        self._starts: Dict[int, Tuple[float, float, int, int]] = {}
        self.transits: Dict[str, List[float]] = {}
        self._seen_seq: Dict[int, int] = {}

    def update(self, rank: int, doc: dict) -> None:
        if doc is None:
            return
        if self._seen_seq.get(rank) == doc.get("seq"):
            return  # same publication polled twice
        self._seen_seq[rank] = doc.get("seq", -1)
        self.meta[rank] = {"ts": doc.get("ts", 0.0),
                           "inc": doc.get("inc", 0),
                           "interval": doc.get("interval", 5.0),
                           "seq": doc.get("seq", 0)}
        for name, rec in doc.get("series", {}).items():
            key = (rank, name)
            hist = self.series.setdefault(key, [])
            t = rec.get("t0_ms", 0) / 1000.0
            vals = rec.get("v", [])
            dts = [0] + rec.get("dt_ms", [])
            for dt, v in zip(dts, vals):
                t += dt / 1000.0
                hist.append((t, v))
            del hist[:-self.cap]
        for name, tiers in doc.get("hist", {}).items():
            key = (rank, name)
            if key in self.series:
                continue  # live deltas already cover it
            finest = min(tiers, key=lambda r: int(r))
            ts, vs = tiers[finest]
            self.series[key] = [(tm / 1000.0, v)
                                for tm, v in zip(ts, vs)][-self.cap:]
        for name, (t_ms, v) in doc.get("latest", {}).items():
            key = (rank, name)
            hist = self.series.setdefault(key, [])
            t = t_ms / 1000.0
            if not hist or t > hist[-1][0]:
                hist.append((t, v))
                del hist[:-self.cap]
        self.edges[rank] = doc.get("edges", {})
        self.alerts[rank] = doc.get("alerts", [])
        flows = doc.get("flows", {})
        for fid, t_ms, nbytes, src, dst in flows.get("starts", []):
            if len(self._starts) < _PENDING_FLOWS_CAP:
                self._starts[fid] = (t_ms, nbytes, src, dst)
        for fid, t_ms in flows.get("finishes", []):
            st = self._starts.pop(fid, None)
            if st is not None and t_ms >= st[0]:
                edge = f"{st[2]}->{st[3]}"
                self.transits.setdefault(edge, []).append(t_ms - st[0])

    def latest(self, rank: int, name: str) -> Optional[float]:
        hist = self.series.get((rank, name))
        return hist[-1][1] if hist else None

    def values(self, rank: int, name: str, last: int = 32) -> List[float]:
        hist = self.series.get((rank, name), [])
        return [v for _, v in hist[-last:]]

    def silent_ranks(self, world: int,
                     now: Optional[float] = None) -> List[int]:
        """Ranks that never published or whose stream went stale (> 3
        publish intervals + a floor) — the SIGKILL detector."""
        if now is None:
            now = time.time()
        out = []
        for r in range(world):
            m = self.meta.get(r)
            if m is None:
                out.append(r)
                continue
            stale_after = max(3.0 * m.get("interval", 5.0), 6.0)
            if now - m["ts"] > stale_after:
                out.append(r)
        return out

    def edge_transit(self, edge: str) -> Tuple[Optional[float],
                                               Optional[float]]:
        """Cross-rank matched transit (p50, p99) µs for an edge, merged
        with the ranks' own locally-matched estimates."""
        samples = list(self.transits.get(edge, []))
        for edges in self.edges.values():
            st = edges.get(edge)
            if st and st.get("p50_us") is not None:
                samples.append(st["p50_us"])
        if not samples:
            return None, None
        arr = np.asarray(samples, np.float64)
        return (float(np.percentile(arr, 50)),
                float(np.percentile(arr, 99)))


# -- rendering (`bfrun --top`) -----------------------------------------------

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 16) -> str:
    vals = [v for v in values[-width:] if v == v]  # drop NaN
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in vals)


def _fmt(v: Optional[float], spec: str = ".3g") -> str:
    if v is None or (isinstance(v, float) and v != v):
        return "-"
    return format(v, spec)


def format_top(acc: HistoryAccumulator, world: int,
               now: Optional[float] = None) -> str:
    """The ``bfrun --top`` frame: per-rank table, per-edge matrix,
    sparklines, alerts, silent ranks — plain text, ANSI-free (the
    launcher owns screen clearing)."""
    if now is None:
        now = time.time()
    silent = set(acc.silent_ranks(world, now))
    lines = [f"bluefog cluster — {world} rank(s), "
             f"{time.strftime('%H:%M:%S', time.localtime(now))}"]
    lines.append(
        f"  {'rank':>4} {'step':>8} {'step/s':>7} {'consensus':>10} "
        f"{'mix':>6} {'mass':>8} {'ef_norm':>8} {'drops/s':>8} "
        f"{'trend':<18} status")
    for r in range(world):
        if r in silent and r not in acc.meta:
            lines.append(f"  {r:>4} {'-':>8} {'-':>7} {'-':>10} {'-':>6} "
                         f"{'-':>8} {'-':>8} {'-':>8} {'':<18} SILENT "
                         "(never published)")
            continue
        step = acc.latest(r, "opt.step")
        rate = acc.latest(r, "opt.step.rate")
        cons = acc.latest(r, "opt.consensus_dist")
        mix = acc.latest(r, "opt.mixing_rate")
        mass = acc.latest(r, "pushsum.mass")
        ef = acc.latest(r, "win.codec.residual_norm")
        drops = acc.latest(r, "win.shard_stale_drops.rate")
        spark = sparkline(acc.values(
            r, "opt.consensus_dist" if cons is not None else "opt.step"))
        status = []
        if r in silent:
            status.append("SILENT")
        for a in acc.alerts.get(r, []):
            status.append(f"ALERT:{a['name']}")
        lines.append(
            f"  {r:>4} {_fmt(step, '.0f'):>8} {_fmt(rate, '.2f'):>7} "
            f"{_fmt(cons):>10} {_fmt(mix, '.3f'):>6} {_fmt(mass):>8} "
            f"{_fmt(ef):>8} {_fmt(drops, '.2f'):>8} {spark:<18} "
            + (" ".join(status) if status else "ok"))
    if silent:
        lines.append(f"  SILENT rank(s): {sorted(silent)} — no "
                     "bf.ts publication within 3 intervals (killed or "
                     "wedged)")
    # per-edge matrix: union of every rank's estimators
    edges: Dict[str, dict] = {}
    for r, per in sorted(acc.edges.items()):
        for edge, st in per.items():
            cur = edges.setdefault(edge, {"bps": 0.0, "deposits": 0,
                                          "bytes": 0.0})
            cur["bps"] += st.get("bps") or 0.0
            cur["deposits"] += st.get("deposits") or 0
            cur["bytes"] += st.get("bytes") or 0.0
    if edges:
        lines.append("  edges (live):")
        for edge in sorted(edges):
            st = edges[edge]
            p50, p99 = acc.edge_transit(edge)
            lines.append(
                f"    {edge:<8} {st['bps'] / 1e6:8.2f} MB/s  "
                f"{st['deposits']:6d} deposits  "
                f"transit p50 {_fmt(p50 and p50 / 1e3, '.2f')} ms  "
                f"p99 {_fmt(p99 and p99 / 1e3, '.2f')} ms")
    return "\n".join(lines)
