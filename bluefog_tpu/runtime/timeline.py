"""Chrome-tracing timeline profiler.

Analog of BlueFog's Timeline subsystem (reference: common/timeline.{h,cc}):
named activities streamed through a lock-free queue to a dedicated writer
thread producing catapult/chrome-tracing JSON (load in chrome://tracing or
Perfetto). Enabled by ``BLUEFOG_TIMELINE=<prefix>`` -> one file
``<prefix><process>.json`` (operations.cc:449-458), or programmatically.

Device-side timing on TPU comes from ``jax.profiler`` xplane traces;
:func:`trace_context` bridges the two by emitting a named activity and a
jax.profiler TraceAnnotation for the same span.

When the native host runtime extension is built (csrc/), the writer is backed
by the C++ spsc-queue implementation; this pure-Python writer (daemon thread +
queue.SimpleQueue) is the fallback and the semantics are identical.
"""

from __future__ import annotations

import contextlib
import json
import queue
import threading
import time
from typing import Optional

import jax

from .logging import logger

# Counter-event name anchoring each per-process trace to the wall clock;
# scripts/merge_timelines.py keys on it to align files before merging.
CLOCK_SYNC_COUNTER = "bf.clock_sync_us"


class Timeline:
    """Streaming chrome-tracing writer with named activities per (tensor, lane)."""

    _SENTINEL = object()

    def __init__(self, prefix: str, process_index: Optional[int] = None,
                 use_native: bool = True) -> None:
        if process_index is None:
            # The runtime's backend-aware index, not argless
            # jax.process_index(): the DEFAULT backend can be a
            # single-process plugin while the mesh is multi-process, and
            # co-hosted controllers must not share a trace file.
            from .state import _global_state

            st = _global_state()
            pid = st.process_index if st.initialized else jax.process_index()
        else:
            pid = process_index
        self.path = f"{prefix}{pid}.json"
        self._t0 = time.perf_counter_ns()
        self._pid = pid
        self._closed = False
        self._failed = False  # writer died: stop producing so the queue can't grow
        self._native = None
        self._native_lib = None
        # Serializes native event emission against close(): bf_timeline_close
        # frees the C++ writer, so no producer may hold the handle across it.
        self._native_mu = threading.Lock()
        if use_native:
            from . import native as _native_mod

            lib = _native_mod.load()
            if lib is not None:
                handle = lib.bf_timeline_open(self.path.encode(), pid)
                if handle:
                    self._native = handle
                    self._native_lib = lib
        if self._native is None:
            self._q: "queue.SimpleQueue" = queue.SimpleQueue()
            self._writer = threading.Thread(
                target=self._writer_loop, name="bf-timeline-writer", daemon=True
            )
            self._writer.start()
        # Clock-sync anchor: timestamps are a per-process perf_counter
        # origin, useless across processes until anchored to a shared
        # clock. The first event of every trace is a counter carrying the
        # wall-clock microseconds at (approximately) ts=0;
        # scripts/merge_timelines.py shifts each file onto the common
        # wall-clock axis using (value - ts) before concatenating.
        self.counter(CLOCK_SYNC_COUNTER, time.time_ns() // 1000)

    # -- producer side (any thread) ---------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    def activity_start(self, tensor_name: str, activity: str, tid: int = 0) -> None:
        if self._failed or self._closed:
            return
        if self._native is not None:
            with self._native_mu:
                if self._native is not None:
                    self._native_lib.bf_timeline_event(
                        self._native, activity.encode(), tensor_name.encode(),
                        b"B", int(self._now_us()), tid)
            return
        self._q.put(
            {"name": activity, "cat": tensor_name, "ph": "B",
             "ts": self._now_us(), "pid": self._pid, "tid": tid}
        )

    def activity_end(self, tensor_name: str, tid: int = 0) -> None:
        if self._failed or self._closed:
            return
        if self._native is not None:
            with self._native_mu:
                if self._native is not None:
                    self._native_lib.bf_timeline_event(
                        self._native, b"", tensor_name.encode(),
                        b"E", int(self._now_us()), tid)
            return
        self._q.put(
            {"ph": "E", "ts": self._now_us(), "pid": self._pid, "tid": tid,
             "cat": tensor_name}
        )

    def instant(self, tensor_name: str, activity: str, tid: int = 0) -> None:
        if self._failed or self._closed:
            return
        if self._native is not None:
            with self._native_mu:
                if self._native is not None:
                    self._native_lib.bf_timeline_event(
                        self._native, activity.encode(), tensor_name.encode(),
                        b"i", int(self._now_us()), tid)
            return
        self._q.put(
            {"name": activity, "cat": tensor_name, "ph": "i", "s": "t",
             "ts": self._now_us(), "pid": self._pid, "tid": tid}
        )

    @contextlib.contextmanager
    def activity(self, tensor_name: str, activity: str, tid: int = 0):
        self.activity_start(tensor_name, activity, tid)
        try:
            yield
        finally:
            self.activity_end(tensor_name, tid)

    # -- counter + flow events (r10 trace correlation) ---------------------

    def counter(self, name: str, value: int, tid: int = 0) -> None:
        """Chrome counter-track sample (``ph: "C"``): mailbox depth,
        push-sum mass, and the clock-sync anchor ride these."""
        if self._failed or self._closed:
            return
        if self._native is not None:
            with self._native_mu:
                if self._native is not None:
                    self._native_lib.bf_timeline_event2(
                        self._native, name.encode(), b"bf", b"C",
                        int(self._now_us()), tid, int(value))
            return
        self._q.put(
            {"name": name, "cat": "bf", "ph": "C", "ts": self._now_us(),
             "pid": self._pid, "tid": tid, "args": {"value": int(value)}}
        )

    def _flow(self, phase: bytes, name: str, flow_id: int, tid: int) -> None:
        if self._failed or self._closed:
            return
        if self._native is not None:
            with self._native_mu:
                if self._native is not None:
                    self._native_lib.bf_timeline_event2(
                        self._native, name.encode(), b"bf.flow", phase,
                        int(self._now_us()), tid, int(flow_id))
            return
        ev = {"name": name, "cat": "bf.flow", "ph": phase.decode(),
              "id": int(flow_id), "ts": self._now_us(), "pid": self._pid,
              "tid": tid}
        if phase == b"f":
            ev["bp"] = "e"  # bind to the enclosing slice
        self._q.put(ev)

    def flow_start(self, name: str, flow_id: int, tid: int = 0) -> None:
        """Open a cross-process flow arrow (``ph: "s"``). The id is the
        binding key: the hosted window plane uses the deposit tag's
        ``(origin << 32) | counter`` sequence, which the draining side
        recovers from the wire, so a ``win_put`` on rank A visually
        connects to its drain inside rank B's ``win_update`` when the
        per-rank trace files are merged."""
        self._flow(b"s", name, flow_id, tid)

    def flow_finish(self, name: str, flow_id: int, tid: int = 0) -> None:
        """Close a flow arrow (``ph: "f"``, bound to the enclosing slice)."""
        self._flow(b"f", name, flow_id, tid)

    # -- writer side -------------------------------------------------------

    def _writer_loop(self) -> None:
        try:
            with open(self.path, "w") as f:
                f.write("[\n")
                first = True
                while True:
                    ev = self._q.get()
                    if ev is Timeline._SENTINEL:
                        break
                    if not first:
                        f.write(",\n")
                    f.write(json.dumps(ev))
                    first = False
                    f.flush()
                f.write("\n]\n")
        except OSError as exc:  # disk full / bad prefix: drop, don't crash train
            self._failed = True
            logger.error("timeline writer failed, disabling timeline: %s", exc)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._native is not None:
            with self._native_mu:
                handle, self._native = self._native, None
            self._native_lib.bf_timeline_close(handle)
            return
        self._q.put(Timeline._SENTINEL)
        self._writer.join(timeout=5.0)


# -- module-level API mirroring bf.timeline_* (basics.py:308-388) -----------

def _timeline() -> Optional[Timeline]:
    from .state import _global_state

    return _global_state().timeline


def timeline_start_activity(tensor_name: str, activity: str, tid: int = 0) -> bool:
    tl = _timeline()
    if tl is None:
        return False
    tl.activity_start(tensor_name, activity, tid)
    return True


def timeline_end_activity(tensor_name: str, tid: int = 0) -> bool:
    tl = _timeline()
    if tl is None:
        return False
    tl.activity_end(tensor_name, tid)
    return True


def timeline_counter(name: str, value, tid: int = 0) -> bool:
    """Sample a chrome counter track (no-op when the timeline is off)."""
    tl = _timeline()
    if tl is None:
        return False
    tl.counter(name, int(value), tid)
    return True


def timeline_instant(tensor_name: str, activity: str, tid: int = 0) -> bool:
    """Emit an instant event (stall warnings, membership transitions)."""
    tl = _timeline()
    if tl is None:
        return False
    tl.instant(tensor_name, activity, tid)
    return True


def timeline_flow_start(name: str, flow_id: int, tid: int = 0) -> bool:
    tl = _timeline()
    if tl is None:
        return False
    tl.flow_start(name, flow_id, tid)
    return True


def timeline_flow_finish(name: str, flow_id: int, tid: int = 0) -> bool:
    tl = _timeline()
    if tl is None:
        return False
    tl.flow_finish(name, flow_id, tid)
    return True


@contextlib.contextmanager
def timeline_context(tensor_name: str, activity: str, tid: int = 0):
    """Named span in the host timeline AND the jax.profiler device trace."""
    tl = _timeline()
    with jax.profiler.TraceAnnotation(f"{tensor_name}.{activity}"):
        if tl is not None:
            tl.activity_start(tensor_name, activity, tid)
        try:
            yield
        finally:
            if tl is not None:
                tl.activity_end(tensor_name, tid)


def start_timeline(prefix: str) -> bool:
    """Enable the timeline at runtime (reference: basics.py timeline start)."""
    from .state import _global_state

    st = _global_state()
    if st.timeline is not None:
        logger.warning("timeline already running; ignoring start_timeline")
        return False
    st.timeline = Timeline(prefix)
    return True


def stop_timeline() -> bool:
    from .state import _global_state

    st = _global_state()
    if st.timeline is None:
        return False
    st.timeline.close()
    st.timeline = None
    return True
