"""Controller heartbeats: failure detection + coordinated shutdown.

The reference's background loop gives every rank two liveness guarantees:
a stalled peer is *detected* (CheckForStalledTensors, operations.cc:387-432)
and shutdown is *coordinated* — any worker's shutdown request reaches the
coordinator, which broadcasts SHUTDOWN so no rank blocks on a departed peer
(operations.cc:830-909, 1074-1095).

The TPU-native analog rides the control-plane KV instead of MPI messages:

  * every controller process bumps ``bf.hb.<pid>`` on a cadence;
  * a monitor thread watches the other controllers' counters and reports a
    peer whose heartbeat stops advancing for longer than
    ``BLUEFOG_HEARTBEAT_TIMEOUT`` seconds (default 30) — the analog of the
    missing-rank stall warning, but cross-process;
  * ``bf.shutdown()`` publishes ``bf.shutdown.flag``; peers' monitors
    surface it via :func:`shutdown_requested`, so a training loop can exit
    cleanly instead of hanging in the next collective.

Single-controller jobs (no control plane) skip all of this — there is no
peer to detect or coordinate with.

Coordination protocol: every process announces ITS OWN shutdown under
``bf.shutdown.flag.<pid>``; a monitor that sees any peer's flag latches
``shutdown_requested`` and acknowledges under ``bf.shutdown.ack.<pid>``.
The first announcer waits (bounded) until every peer has either acked or
announced its own shutdown before tearing its control-plane server down —
otherwise process 0 would kill the server before the 5-second-cadence
monitors ever read the flag.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import control_plane as _cp
from . import flight as _flight
from . import metrics as _metrics
from . import timeseries as _timeseries
from . import tuner as _tuner
from .logging import logger
from .timeline import timeline_instant

_FLAG = "bf.shutdown.flag."
_ACK = "bf.shutdown.ack."
_EPOCH_KEY = "bf.membership.epoch"
# Per-rank incarnation mirror (written by the server's kAttach handler) and
# per-(rank, incarnation) quarantine phase: 1 = attached + quarantined
# (state transfer pending), 2 = transfer complete (eligible for
# re-admission). See docs/fault_tolerance.md, "Rejoin & fencing".
_INC = "bf.inc."
_QUARANTINE = "bf.q."
_Q_ENTERED = 1
_Q_COMPLETE = 2


class PeerMonitor:
    """Heartbeat publisher + peer liveness / shutdown-flag watcher.

    Elastic membership (r9): a peer whose heartbeat RESUMES after it was
    declared dead is **not** silently re-admitted — it moves to a
    ``suspect`` set (logged at ERROR) while staying in the dead set, and
    only returns to live membership once the control plane shows a NEW
    incarnation registered for it AND that incarnation's quarantine (state
    transfer) completed. A flapping peer — same incarnation, stale
    parameters, stale server-side identity — therefore never rejoins the
    averaging graph; ``dead_ranks()`` semantics are unchanged for peers
    that never resume.
    """

    def __init__(self, process_index: int, process_count: int,
                 interval_sec: Optional[float] = None,
                 timeout_sec: Optional[float] = None) -> None:
        self.me = process_index
        self.world = process_count
        self.interval = interval_sec if interval_sec is not None else float(
            os.environ.get("BLUEFOG_HEARTBEAT_INTERVAL", "5"))
        self.timeout = timeout_sec if timeout_sec is not None else float(
            os.environ.get("BLUEFOG_HEARTBEAT_TIMEOUT", "30"))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._shutdown_seen = threading.Event()
        self._last_value: Dict[int, int] = {}
        self._last_change: Dict[int, float] = {}
        self._dead: set = set()
        self._suspect: set = set()       # resumed-but-unfenced peers
        self._dead_inc: Dict[int, int] = {}  # incarnation at death time
        self._epoch: int = 0             # membership-epoch mirror
        self._cl = None  # dedicated control-plane connection (see start())
        self._partition_rejects_seen = 0  # cp.partitions counter baseline
        self._quorum_lost_last = 0       # edge-detect for timeline instants

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None or not _cp.active():
            return
        # Dedicated connection: the SHARED client's mutex is held for the
        # full round-trip of every call, and window ops park it inside
        # blocking server-side locks (hosted win mutexes, barriers). A
        # heartbeat riding that connection would go silent exactly when the
        # job is busiest — and silence past BLUEFOG_HEARTBEAT_TIMEOUT makes
        # live peers declare this controller dead. Own socket = the
        # heartbeat's cadence depends on nothing but the server being up.
        try:
            self._cl = _cp.extra_client()
        except (OSError, RuntimeError) as exc:
            logger.warning(
                "heartbeat: dedicated control-plane connection failed (%s); "
                "falling back to the shared one", exc)
            self._cl = None
        self._thread = threading.Thread(
            target=self._loop, name="bf-heartbeat", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=2.0)
            if thread.is_alive():
                # The tick is wedged in a control-plane call (hung server —
                # the very scenario this monitor exists to detect). Closing
                # the native client now would free the C++ ControlClient out
                # from under the thread; leave the daemon thread's connection
                # to be reclaimed at process exit instead.
                logger.warning(
                    "heartbeat thread did not exit within 2 s (control plane "
                    "unresponsive?); leaving its connection open")
                self._cl = None
                return
        if self._cl is not None:
            self._cl.close()
            self._cl = None

    # -- queries -----------------------------------------------------------

    @property
    def shutdown_seen(self) -> bool:
        return self._shutdown_seen.is_set()

    def dead_peers(self) -> set:
        return set(self._dead)

    def suspect_peers(self) -> set:
        """Peers whose heartbeat resumed but whose re-admission gate has
        not cleared (still counted dead for membership purposes)."""
        return set(self._suspect)

    @property
    def membership_epoch(self) -> int:
        """Locally mirrored shared membership epoch (refreshed per tick and
        bumped synchronously on local transitions) — readable every gossip
        step without a server round-trip."""
        return self._epoch

    # -- the loop ----------------------------------------------------------

    def _bump_epoch(self, cl) -> None:
        try:
            self._epoch = int(cl.fetch_add(_EPOCH_KEY, 1)) + 1
        except OSError:
            self._epoch += 1  # local monotonicity is what consumers need

    def _poll_shards(self, cl) -> None:
        """Per-shard control-plane liveness (sharded deployments only):
        adopt peer-published failover flags, verify each live shard still
        answers, and surface transitions in the telemetry/timeline planes.
        The router logs the failure itself; this is the cadence that makes
        every process converge on the same shrunken shard ring within one
        heartbeat interval of a shard death."""
        before = cl.dead_shards()
        dead = cl.poll_shard_health()
        _metrics.gauge("cp.shards").set(cl.shard_count)
        _metrics.gauge("cp.dead_shards").set(len(dead))
        for idx in sorted(dead - before):
            timeline_instant(f"cp.shard.{idx}", "SHARD_DEAD")
        for idx in sorted(before - dead):
            timeline_instant(f"cp.shard.{idx}", "SHARD_REJOIN")
        # Replication health (durable plane, r16): the max WAL lag across
        # live shards and the count of shards serving UNREPLICATED
        # (degraded / successor lost) — the gauges `bfrun --status
        # --strict` mirrors as under-replication findings.
        try:
            lag = 0
            under = 0
            qlost = 0
            rejects = 0
            for _name, st in cl.server_stats_all():
                if not st:
                    continue
                if st.get("repl_status") == 1:
                    lag = max(lag, st["wal_enqueued"] - st["wal_acked"])
                elif st.get("repl_status") == 2:
                    under += 1
                # quorum replication (r20): a shard below its commit
                # quorum is ALIVE (it serves reads) but rejects mutating
                # ops — the partition-alert gauge routers/operators watch
                if st.get("quorum_state") == 2:
                    qlost += 1
                rejects += int(st.get("partition_rejects", 0))
            _metrics.gauge("cp.repl_lag").set(lag)
            _metrics.gauge("cp.under_replicated").set(under)
            _metrics.gauge("cp.quorum_lost").set(qlost)
            prev = self._partition_rejects_seen
            if rejects > prev:
                # counter trail + one flight instant per NEW episode (the
                # first rejected op after a clean interval), so postmortem
                # dumps pin when the cut engaged
                _metrics.counter("cp.partitions").inc(rejects - prev)
                if prev == 0:
                    try:
                        from . import flight as _flight

                        _flight.recorder().instant("cp.partition",
                                                   a=float(rejects))
                    except Exception:  # noqa: BLE001 — telemetry only
                        pass
                self._partition_rejects_seen = rejects
            if qlost != self._quorum_lost_last:
                for_ = "LOST" if qlost else "RESTORED"
                timeline_instant("cp.quorum", f"QUORUM_{for_}")
                self._quorum_lost_last = qlost
        except (OSError, RuntimeError):
            pass  # stats probe must never break the heartbeat cadence

    def _tick(self) -> None:
        cl = self._cl if self._cl is not None else _cp.client()
        if hasattr(cl, "poll_shard_health"):
            self._poll_shards(cl)
        cl.put(f"bf.hb.{self.me}", int(time.monotonic_ns() & 0x7FFFFFFFFFFF))
        now = time.monotonic()
        for peer in range(self.world):
            if peer == self.me:
                continue
            v = cl.get(f"bf.hb.{peer}")
            if v != self._last_value.get(peer):
                self._last_value[peer] = v
                self._last_change[peer] = now
                if peer in self._dead and peer not in self._suspect:
                    # Flapping-peer hole (r9): a raw heartbeat resume alone
                    # must NEVER shrink the dead set — the peer's parameters
                    # and server-side identity (dedup tables, mailbox
                    # deposits, lock holdership) are stale, and silently
                    # re-admitting it corrupts the average (and push-sum
                    # mass). It becomes a tracked suspect until the
                    # re-admission gate below clears it.
                    self._suspect.add(peer)
                    self._bump_epoch(cl)
                    _metrics.counter("hb.suspect_transitions").inc()
                    timeline_instant(f"controller.{peer}", "SUSPECT")
                    logger.error(
                        "controller %d heartbeat RESUMED without a new "
                        "incarnation registration — keeping it out of live "
                        "membership (suspect) until it re-attaches with a "
                        "bumped incarnation and completes quarantined state "
                        "transfer; a flapping peer must not rejoin with "
                        "stale state (docs/fault_tolerance.md)", peer)
            elif (now - self._last_change.get(peer, now) > self.timeout
                  and peer not in self._dead):
                self._dead.add(peer)
                self._suspect.discard(peer)
                try:
                    self._dead_inc[peer] = int(cl.get(f"{_INC}{peer}"))
                except OSError:
                    self._dead_inc[peer] = 0
                self._bump_epoch(cl)
                _metrics.counter("hb.dead_transitions").inc()
                timeline_instant(f"controller.{peer}", "DEAD")
                logger.error(
                    "controller %d heartbeat missing for %.0f s — peer "
                    "failure detected; collectives involving its devices "
                    "will hang (reference analog: missing-rank stall, "
                    "operations.cc:387-432)", peer, self.timeout)
        # Re-admission gate: a suspect returns to live membership only once
        # the server shows a NEW incarnation registered for it (it went
        # through the fenced rejoin path, so its zombie predecessor is cut
        # off) AND that incarnation finished quarantine — the striped
        # neighbor state transfer (or checkpoint fallback) completed, so the
        # values it gossips are current, and for push-sum its mass was
        # donor-split rather than freshly minted.
        for peer in sorted(self._suspect):
            try:
                inc = int(cl.get(f"{_INC}{peer}"))
                phase = int(cl.get(f"{_QUARANTINE}{peer}.{inc}")) \
                    if inc > self._dead_inc.get(peer, 0) else 0
            except OSError:
                continue
            if phase >= _Q_COMPLETE:
                self._suspect.discard(peer)
                self._dead.discard(peer)
                self._dead_inc[peer] = inc
                self._bump_epoch(cl)
                _metrics.counter("hb.readmissions").inc()
                timeline_instant(f"controller.{peer}", "READMIT")
                logger.warning(
                    "controller %d re-admitted to live membership: "
                    "incarnation %d registered and quarantine complete — "
                    "window optimizers re-include its ranks at their next "
                    "epoch check", peer, inc)
        try:
            shared = int(cl.get(_EPOCH_KEY))
            if shared > self._epoch:
                self._epoch = shared
        except OSError:
            pass
        # Telemetry plane: mirror membership into the registry, then let
        # the interval-gated publisher piggyback this tick (the whole
        # cluster-health publication costs zero extra threads and no
        # per-step RTT in multi-controller jobs).
        _metrics.gauge("membership.epoch").set(self._epoch)
        _metrics.gauge("hb.dead_peers").set(len(self._dead))
        _metrics.gauge("hb.suspect_peers").set(len(self._suspect))
        _metrics.maybe_publish(cl)
        # Live time-series plane (docs/observability.md): sample the ring
        # history + per-edge estimators and publish the bf.ts.<rank>
        # delta on its own cadence — same zero-extra-threads discipline
        # as the metrics piggyback above.
        _timeseries.maybe_sample(cl)
        # Self-tuning controller (docs/self_tuning.md): interval-gated
        # like the sampler above, a no-op import-and-return unless
        # BLUEFOG_TUNE=1. Riding the heartbeat gives the controller a
        # cadence even when the training step stalls — which is exactly
        # when it has work to do.
        _tuner.maybe_tick(cl)
        # cluster-wide postmortem trigger (`bfrun --dump`): one KV read per
        # tick; on a bump this rank dumps locally and publishes its packed
        # tail under bf.flight.<rank> (docs/flight_recorder.md)
        _flight.poll_remote_trigger(cl)
        if not self._shutdown_seen.is_set() and any(
                cl.get(f"{_FLAG}{p}") for p in range(self.world)
                if p != self.me):
            self._shutdown_seen.set()
            cl.put(f"{_ACK}{self.me}", 1)  # let the announcer stop waiting
            logger.info(
                "coordinated shutdown requested by a peer controller "
                "(reference analog: SHUTDOWN broadcast, operations.cc"
                ":1074-1095)")

    def _loop(self) -> None:
        failures = 0
        while not self._stop.wait(self.interval):
            try:
                self._tick()
                if failures >= 3:
                    logger.warning(
                        "heartbeat recovered after %d failed ticks", failures)
                failures = 0
            except Exception as exc:  # noqa: BLE001 — observability thread
                # Keep retrying forever: the monitor must outlive transient
                # KV/socket outages (it tolerates `timeout` seconds of peer
                # silence, so it must tolerate at least that much of its
                # own). Shutdown stops this thread BEFORE detaching the
                # control plane, so teardown never strands it spinning.
                failures += 1
                if failures == 3:
                    logger.warning(
                        "heartbeat ticks failing (%s); retrying every "
                        "%.1f s — peer failure detection degraded until the "
                        "control plane recovers", exc, self.interval)
                else:
                    logger.debug("heartbeat tick failed (retrying): %s", exc)


def announce_shutdown(process_index: int, process_count: int,
                      grace_sec: Optional[float] = None) -> None:
    """Publish this process's shutdown flag and wait for peers to see it.

    The wait is what makes the coordination real: the announcer may host the
    control-plane server, and tearing it down before the (interval-cadence)
    peer monitors have read the flag would defeat the broadcast. A peer
    counts as "notified" once it acks or announces its own shutdown; the
    wait is bounded by ``BLUEFOG_SHUTDOWN_GRACE`` seconds (default: 3x the
    heartbeat interval) so crashed peers cannot hang teardown.
    """
    if not _cp.active():
        return
    try:
        cl = _cp.client()
        peer_already_announced = any(
            cl.get(f"{_FLAG}{p}") for p in range(process_count)
            if p != process_index)
        cl.put(f"{_FLAG}{process_index}", 1)
        cl.put(f"{_ACK}{process_index}", 1)
        if peer_already_announced:
            return  # coordination already under way; no need to wait
        grace = grace_sec if grace_sec is not None else float(
            os.environ.get("BLUEFOG_SHUTDOWN_GRACE",
                           3 * float(os.environ.get(
                               "BLUEFOG_HEARTBEAT_INTERVAL", "5"))))
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            if all(cl.get(f"{_ACK}{p}") or cl.get(f"{_FLAG}{p}")
                   for p in range(process_count)):
                return
            time.sleep(0.05)
        logger.warning(
            "shutdown grace (%.1f s) expired with unacknowledged peers; "
            "proceeding with teardown", grace)
    except Exception as exc:  # noqa: BLE001 — best effort during teardown
        logger.debug("shutdown announce failed: %s", exc)


def shutdown_requested() -> bool:
    """True once any controller in the job has called ``bf.shutdown()``.

    Training loops in multi-controller deployments can poll this to exit
    before issuing a collective that would hang on the departed peer.
    """
    from .state import _global_state

    mon = _global_state().peer_monitor
    return bool(mon is not None and mon.shutdown_seen)


def dead_controllers() -> set:
    """Controller process indexes whose heartbeats have gone silent.

    A peer lands here after ``BLUEFOG_HEARTBEAT_TIMEOUT`` seconds without a
    counter advance — a *crash* signal (no coordinated announce), the
    cross-process analog of the reference's missing-rank stall report
    (operations.cc:387-432). Training loops can poll this alongside
    :func:`shutdown_requested` to abandon collectives that would hang on
    the departed peer. Empty in single-controller jobs.
    """
    from .state import _global_state

    mon = _global_state().peer_monitor
    return mon.dead_peers() if mon is not None else set()


def suspect_controllers() -> set:
    """Controllers whose heartbeat resumed but which are still fenced out
    of live membership (see :class:`PeerMonitor`). Subset of
    :func:`dead_controllers` — membership-wise they are still dead."""
    from .state import _global_state

    mon = _global_state().peer_monitor
    return mon.suspect_peers() if mon is not None else set()


def membership_epoch() -> int:
    """Monotonic membership-epoch counter (0 when single-controller).

    Bumped by the control-plane server on every incarnation registration
    (join/rejoin) and by heartbeat monitors on dead-set transitions
    (death, suspect, re-admission). Window optimizers compare it per
    gossip step and rebuild their healed neighbor tables only when it
    moved — the cheap "did membership change?" probe that replaces
    re-deriving edge tables every step. With a live monitor the read is a
    local mirror (no server round-trip)."""
    from .state import _global_state

    mon = _global_state().peer_monitor
    if mon is not None:
        return mon.membership_epoch
    return _cp.membership_epoch_kv()


# -- quarantine state machine (the rejoining process's side) -----------------
#
# A respawned rank (BLUEFOG_INCARNATION > 0) is *quarantined* between its
# fenced attach and the completion of state transfer: it is visible in
# membership (its incarnation is registered, so its zombie is cut off) but
# survivors keep its ranks out of averaging until `complete_quarantine`
# publishes phase 2 — the re-admission gate PeerMonitor._tick checks.

_q_state = {"pending": False, "pid": 0, "inc": 0, "t0": 0.0}


def quarantine_pending() -> bool:
    """True between this process's quarantined attach and the completion of
    its state transfer (always False for incarnation-0 launches)."""
    return _q_state["pending"]


def enter_quarantine(process_index: int) -> None:
    """Mark this process quarantined (called by ``bf.init`` when attaching
    with a bumped incarnation). Publishes phase 1 under the per-(rank,
    incarnation) key so survivors can observe the rejoin in progress."""
    inc = _cp.incarnation()
    if not _cp.active() or inc <= 0:
        _q_state["pending"] = False
        return
    _q_state.update(pending=True, pid=process_index, inc=inc,
                    t0=time.monotonic())
    _metrics.counter("hb.quarantine_entries").inc()
    timeline_instant(f"controller.{process_index}", "QUARANTINE_ENTER")
    try:
        _cp.client().put(f"{_QUARANTINE}{process_index}.{inc}", _Q_ENTERED)
    except OSError as exc:
        logger.warning("quarantine entry publish failed (%s)", exc)
    logger.warning(
        "rejoining as incarnation %d: QUARANTINED until state transfer "
        "completes — this rank is registered (zombie fenced) but excluded "
        "from averaging", inc)


def complete_quarantine() -> None:
    """Publish quarantine completion (phase 2) and bump the membership
    epoch so survivors' monitors re-admit this rank. Idempotent."""
    if not _q_state["pending"]:
        return
    _q_state["pending"] = False
    # quarantine duration: how long this rank sat fenced-but-transferring —
    # the elastic-rejoin latency the health plane watches
    _metrics.histogram("hb.quarantine_sec").observe(
        time.monotonic() - _q_state["t0"])
    timeline_instant(f"controller.{_q_state['pid']}", "QUARANTINE_COMPLETE")
    try:
        cl = _cp.client()
        cl.put(f"{_QUARANTINE}{_q_state['pid']}.{_q_state['inc']}",
               _Q_COMPLETE)
        _cp.bump_membership_epoch()
    except (OSError, RuntimeError) as exc:
        logger.warning("quarantine completion publish failed (%s)", exc)
        return
    logger.warning(
        "quarantine complete: state transfer finished; survivors will "
        "re-admit this rank at their next heartbeat tick")


def dead_ranks() -> set:
    """Mesh ranks whose hosting controller's heartbeat has gone silent.

    The rank-level projection of :func:`dead_controllers`: every rank whose
    device shard lives on a dead controller process. This is the set the
    self-healing gossip layer consults each step — window optimizers drop
    these ranks from their send/recv edge sets and renormalize averaging
    weights, so survivors keep training on the shrunken graph instead of
    depositing into (and waiting on) a corpse's mailboxes (cf. AD-PSGD /
    SGP: decentralized averaging tolerates vertex removal as long as the
    live subgraph stays connected). Empty in single-controller jobs.
    """
    from . import control_plane as _cp
    from .state import _global_state

    st = _global_state()
    mon = st.peer_monitor
    if mon is None:
        return set()
    dead = mon.dead_peers()
    if not dead:
        return set()
    return {r for pidx in dead
            for r in _cp.owned_ranks(st.devices, pidx)}
