"""Process-global control plane: distributed scalar state for windows.

The reference implements cross-process window mutexes as MPI_Fetch_and_op
spin-locks (reference: mpi_controller.cc:1532-1602) and per-edge version
counters as MPI RMA "version windows" (mpi_controller.cc:1281-1393). On TPU
those small-scalar protocols ride the native TCP control plane
(csrc/bf_runtime.cc) instead of MPI RMA: one server per job, one client per
controller process.

Activation:
  * multi-controller jobs (``jax.process_count() > 1``): process 0 serves on
    ``BLUEFOG_CP_PORT`` (default: coordinator port + 17) and every process
    connects to the coordinator host. Wired automatically by ``bf.init``.
  * forced: set ``BLUEFOG_CP_HOST``/``BLUEFOG_CP_PORT`` (tests, external
    actors). ``BLUEFOG_CP_DISABLE=1`` turns the subsystem off entirely —
    window scalar state then stays controller-local.

Ownership: every window rank is owned by exactly one controller process (the
process whose devices host that rank's shard). Only the owner WRITES that
rank's scalars to the shared KV; every process READS from it. Since all
controllers execute the same SPMD op sequence, this gives exactly-once
update semantics without compare-and-swap loops.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import List, Optional

from .config import knob_env
from .logging import logger
from .native import (ControlPlaneClient, ControlPlaneServer,
                     StaleIncarnationError)
from .router import ShardRouter, parse_endpoints

_mu = threading.Lock()
_client = None  # ControlPlaneClient (1 endpoint) or ShardRouter (N shards)
_server: Optional[ControlPlaneServer] = None
_servers: list = []  # in-process shard servers (BLUEFOG_CP_SHARDS > 1)
_world: int = 1
_tried = False
_conn_params = None  # (host, port, rank, secret) of the live attachment
_endpoints = None    # [(host, port)] of a sharded attachment
_incarnation: int = 0  # incarnation this attachment registered


def _env_port(default: Optional[int] = None) -> Optional[int]:
    v = os.environ.get("BLUEFOG_CP_PORT")
    return int(v) if v else default


def _env_incarnation() -> int:
    """BLUEFOG_INCARNATION: this process's membership incarnation (0 on a
    first launch; bfrun --elastic bumps it on every respawn). Registered
    with the control-plane server in attach() so a zombie predecessor is
    fenced the moment this process connects."""
    try:
        return max(0, int(os.environ.get("BLUEFOG_INCARNATION", "0") or 0))
    except ValueError:
        return 0


def _distributed_client_info():
    """(coordinator_address, num_processes, process_id) from a live
    jax.distributed client, or (None, 1, 0). Internal-API probe: pods that
    called argument-free ``jax.distributed.initialize()`` are multi-process
    without any env set."""
    try:
        import jax
        from jax._src import distributed as _jd

        state = _jd.global_state
        if state.client is not None and state.coordinator_address:
            return (state.coordinator_address, jax.process_count(),
                    jax.process_index())
    except Exception:  # noqa: BLE001 — internal layout may change by version
        pass
    return None, 1, 0


def attach() -> Optional[ControlPlaneClient]:
    """Connect (starting the server if this is process 0) when configured.

    Returns the process-global client, or None when the control plane is
    not configured / disabled / the native runtime is unavailable.
    """
    global _client, _server, _world, _tried, _conn_params, _incarnation
    with _mu:
        if _client is not None or _tried:
            return _client
        _tried = True
        if os.environ.get("BLUEFOG_CP_DISABLE") == "1":
            return None

        host = os.environ.get("BLUEFOG_CP_HOST")
        port = _env_port()
        rank = int(os.environ.get("BLUEFOG_CP_RANK", "0"))
        world = int(os.environ.get("BLUEFOG_CP_WORLD", "0"))
        # Shared-secret authentication (reference: HMAC-signed driver/task
        # messages, run/horovodrun/common/util/network.py:69-86). The
        # launcher generates one per job and distributes it via env; without
        # it the server accepts any TCP connect (single-host dev only).
        secret = os.environ.get("BLUEFOG_CP_SECRET", "")

        # Sharded control plane, explicit endpoints (ISSUE r14):
        # BLUEFOG_CP_HOSTS names N external shard server processes (what
        # ``bfrun --cp-shards`` exports) — no host derivation needed, but
        # (rank, world) still come from the launcher/jax.distributed env
        # when BLUEFOG_CP_RANK/WORLD are not set explicitly.
        hosts_spec = os.environ.get("BLUEFOG_CP_HOSTS")
        if hosts_spec and world <= 0:
            nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
            pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
            if nproc <= 1:
                _, nproc, pid = _distributed_client_info()
            world, rank = max(1, nproc), pid
        if hosts_spec:
            return _attach_sharded(hosts_spec, 1, host, port, rank, world,
                                   secret)

        if host is None:
            # Automatic multi-controller wiring: prefer the launcher's env,
            # fall back to the live jax.distributed client (pods initialized
            # with argument-free jax.distributed.initialize() have
            # process_count > 1 without the env being set).
            coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
            nproc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
            pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
            if coord is None or nproc <= 1:
                coord, nproc, pid = _distributed_client_info()
            if coord is None or nproc <= 1:
                return None
            chost, _, cport = coord.partition(":")
            host = chost
            port = port or int(cport) + 17
            rank = pid
            world = nproc
        # Sharded control plane over a derived host/port:
        # BLUEFOG_CP_SHARDS=N uses ports port..port+N-1 and rank 0 serves
        # all N in-process (tests, single-host jobs). One endpoint keeps
        # the legacy single-client path below, byte for byte.
        shards = int(knob_env("BLUEFOG_CP_SHARDS") or 1)
        if shards > 1:
            return _attach_sharded(None, shards, host, port, rank, world,
                                   secret)

        if port is None or world <= 0:
            logger.warning("control plane env incomplete; staying local")
            return None

        served_cap = None
        if rank == 0 and os.environ.get("BLUEFOG_CP_SERVE", "1") != "0":
            try:
                # single authoritative default: the knob registry
                # (runtime/config.py KNOBS; bfcheck flags per-site literals)
                max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
                _server = ControlPlaneServer(
                    world, port, secret=secret,
                    max_mailbox_bytes=int(max_mb * (1 << 20)))
                served_cap = int(max_mb * (1 << 20))
            except (OSError, RuntimeError) as exc:
                # Another actor (launcher, tests) may already serve this port.
                logger.debug("control plane server not started here (%s)", exc)
                _server = None

        deadline = time.monotonic() + float(
            os.environ.get("BLUEFOG_CP_CONNECT_TIMEOUT", "30"))
        last: Optional[Exception] = None
        inc = _env_incarnation()
        while time.monotonic() < deadline:
            try:
                _client = ControlPlaneClient(host, port, rank, secret=secret,
                                             incarnation=inc)
                break
            except StaleIncarnationError:
                # typed, non-retryable: a newer incarnation of this rank is
                # already attached — this process must not join the job
                if _server is not None:
                    _server.stop()
                    _server = None
                raise
            except (OSError, RuntimeError) as exc:
                last = exc
                time.sleep(0.2)
        if _client is None:
            if _server is not None:
                _server.stop()
                _server = None
            if world > 1:
                # A multi-process job degrading to world-of-one would train
                # silently wrong answers (each partition averaging with
                # itself): window scalars, mutexes, heartbeats, and the
                # hosted data plane would all be process-local while the
                # job believes it is coordinating. Fail loudly instead —
                # the soft local fallback below is only for forced
                # single-controller runs (world == 1: tests, external
                # actors), where "local" IS globally consistent.
                raise RuntimeError(
                    f"control plane connect to {host}:{port} failed after "
                    "BLUEFOG_CP_CONNECT_TIMEOUT with a declared world of "
                    f"{world} processes (rank {rank}): refusing to degrade "
                    "a multi-controller job to local-only coordination. "
                    f"Last error: {last}")
            logger.warning("control plane connect failed (%s); staying local", last)
            return None
        _world = world
        _conn_params = (host, port, rank, secret)
        _incarnation = inc
        if served_cap is not None:
            # Publish the SERVING process's effective mailbox cap under a
            # well-known key (value + 1, so a missing key's 0 is
            # distinguishable from an explicit unlimited cap). Origins size
            # their deposit pre-checks against this instead of their own
            # BLUEFOG_CP_MAILBOX_MAX_MB, so a cross-host env mismatch
            # cannot tear a multi-record deposit (ADVICE r5 low).
            _client.put(_MAILBOX_CAP_KEY, served_cap + 1)
        logger.info("control plane attached: %s:%d rank=%d world=%d",
                    host, port, rank, world)
        return _client


def _stop_servers() -> None:
    global _servers
    for srv in _servers:
        srv.stop()
    _servers = []


def _attach_sharded(hosts_spec, shards, host, port, rank, world, secret):
    """Sharded attachment (caller holds ``_mu``): connect a
    :class:`ShardRouter` over N endpoints, optionally serving the N shards
    in-process on rank 0, and assert per-shard mailbox-cap agreement.
    Returns the router (stored as the process-global client) or None."""
    global _client, _servers, _world, _conn_params, _endpoints, _incarnation
    if hosts_spec:
        try:
            endpoints = parse_endpoints(hosts_spec)
        except ValueError as exc:
            raise RuntimeError(f"BLUEFOG_CP_HOSTS: {exc}") from None
        serve_here = False  # endpoints name external shard server processes
    else:
        if host is None or port is None:
            logger.warning("BLUEFOG_CP_SHARDS set without a control-plane "
                           "host/port; staying local")
            return None
        endpoints = [(host, port + i) for i in range(max(1, shards))]
        serve_here = rank == 0 and \
            os.environ.get("BLUEFOG_CP_SERVE", "1") != "0"
    if world <= 0:
        logger.warning("control plane env incomplete; staying local")
        return None

    if serve_here:
        max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
        served_cap = int(max_mb * (1 << 20))
        try:
            for _, p in endpoints:
                _servers.append(ControlPlaneServer(
                    world, p, secret=secret, max_mailbox_bytes=served_cap))
        except (OSError, RuntimeError) as exc:
            # Another actor (launcher, tests) may already serve these ports.
            logger.debug("shard servers not started here (%s)", exc)
            _stop_servers()
        else:
            if len(_servers) > 1 and int(knob_env("BLUEFOG_CP_REPLICATION")):
                # durable plane (r16): each in-process shard streams its
                # WAL to its ring successor, so a key's failover target
                # already holds its mailbox/KV/lock state
                for i, srv in enumerate(_servers):
                    _, sp = endpoints[(i + 1) % len(endpoints)]
                    srv.set_successor("127.0.0.1", sp, len(endpoints), i)
            # Every shard publishes ITS OWN effective cap (value + 1, so a
            # missing key's 0 stays distinguishable). Deliberately written
            # per shard, never through the router: a router write would
            # max-merge the copies and MASK a mixed-cap cluster instead of
            # letting the agreement check below reject it.
            for _, p in endpoints:
                c = ControlPlaneClient("127.0.0.1", p, rank, secret=secret,
                                       streams=1)
                c.put(_MAILBOX_CAP_KEY, served_cap + 1)
                c.close()

    deadline = time.monotonic() + float(
        os.environ.get("BLUEFOG_CP_CONNECT_TIMEOUT", "30"))
    last: Optional[Exception] = None
    inc = _env_incarnation()
    router = None
    while time.monotonic() < deadline:
        try:
            router = ShardRouter(endpoints, rank, secret=secret,
                                 incarnation=inc)
            break
        except StaleIncarnationError:
            _stop_servers()
            raise
        except (OSError, RuntimeError) as exc:
            last = exc
            time.sleep(0.2)
    names = ",".join(f"{h}:{p}" for h, p in endpoints)
    if router is None:
        _stop_servers()
        if world > 1:
            # same loud-failure contract as the single-server path: a
            # multi-process job must never degrade to local coordination
            raise RuntimeError(
                f"control plane connect to shards [{names}] failed after "
                "BLUEFOG_CP_CONNECT_TIMEOUT with a declared world of "
                f"{world} processes (rank {rank}): refusing to degrade "
                "a multi-controller job to local-only coordination. "
                f"Last error: {last}")
        logger.warning("sharded control plane connect failed (%s); "
                       "staying local", last)
        return None

    # Mixed-cap clusters fail loudly AT ATTACH: every shard advertises its
    # own cap, and a disagreement would otherwise truncate deposits on the
    # smaller shard only — silently, and only for the keys routed there.
    caps = {ep: v - 1
            for ep, v in router.replicated_get_all(_MAILBOX_CAP_KEY)
            if v > 0}
    if len(set(caps.values())) > 1:
        router.close()
        _stop_servers()
        raise RuntimeError(
            "control-plane shards advertise DIFFERENT mailbox caps: " +
            ", ".join(f"{ep}={cap}" for ep, cap in sorted(caps.items())) +
            " — set BLUEFOG_CP_MAILBOX_MAX_MB identically on every shard "
            "server (a mixed-cap cluster truncates deposits on the "
            "smaller shards only)")

    _client = router
    _world = world
    _conn_params = (None, None, rank, secret)
    _endpoints = list(endpoints)
    _incarnation = inc
    logger.info("control plane attached (sharded): %d shard(s) [%s] "
                "rank=%d world=%d", len(endpoints), names, rank, world)
    return router


def active() -> bool:
    return _client is not None


def client() -> ControlPlaneClient:
    if _client is None:
        raise RuntimeError("control plane is not attached")
    return _client


def extra_client(streams: Optional[int] = None) -> ControlPlaneClient:
    """A NEW dedicated connection to the attached server (caller closes it).

    The shared :func:`client` connection serializes calls and can be parked
    for seconds inside a blocking server-side op (window mutex lock,
    barrier). Subsystems that must stay live regardless — the heartbeat
    above all, whose silence marks this controller DEAD — run their traffic
    over their own connection instead. ``streams`` overrides the client's
    striped-pool width (the microbench's single-stream ceiling probe pins
    it to 1).
    """
    if _conn_params is None:
        raise RuntimeError("control plane is not attached")
    host, port, rank, secret = _conn_params
    if _endpoints is not None:
        # Sharded attachment: the dedicated connection set is a router of
        # its own, SHARING the main router's dead-shard state so every
        # subsystem of this process agrees on routing.
        return ShardRouter(_endpoints, rank, secret=secret, streams=streams,
                           incarnation=_incarnation,
                           shared_state=_client.shared_state())
    return ControlPlaneClient(host, port, rank, secret=secret,
                              streams=streams, incarnation=_incarnation)


def world() -> int:
    return _world


def incarnation() -> int:
    """The incarnation this process registered at attach time (0 for a
    first launch or when no control plane is attached)."""
    return _incarnation


# Well-known monotonic membership-epoch counter: bumped by the SERVER on
# every incarnation registration (join) and by heartbeat monitors on dead-set
# transitions (leave / re-admission). Window optimizers rebuild their healed
# neighbor tables only when it moves — see runtime/heartbeat.membership_epoch.
_EPOCH_KEY = "bf.membership.epoch"


def membership_epoch_kv() -> int:
    """Raw read of the shared membership-epoch counter (0 when detached)."""
    if _client is None:
        return 0
    try:
        return int(_client.get(_EPOCH_KEY))
    except OSError:
        return 0


def bump_membership_epoch() -> None:
    """Advance the shared membership epoch (best-effort, idempotent in
    effect: consumers only compare for change)."""
    if _client is not None:
        try:
            _client.fetch_add(_EPOCH_KEY, 1)
        except OSError:
            pass


def detach() -> None:
    """Close the client (and server, when owned). Safe to call repeatedly."""
    global _client, _server, _tried, _world, _conn_params, _cap_cache, \
        _incarnation, _endpoints
    with _mu:
        if _client is not None:
            _client.close()
            _client = None
        if _server is not None:
            _server.stop()
            _server = None
        _stop_servers()
        _tried = False
        _world = 1
        _conn_params = None
        _endpoints = None
        _cap_cache = None
        _incarnation = 0


def reset_for_test() -> None:
    """Forget the cached attach decision so tests can re-configure the env."""
    detach()


def barrier(name: str = "default") -> None:
    if _client is not None:
        _client.barrier(name)


# Well-known key holding the serving process's effective per-mailbox byte
# cap, stored as (cap_bytes + 1) so 0 still means "not published".
_MAILBOX_CAP_KEY = "bf.cp.mailbox_cap_bytes"
_cap_cache: Optional[int] = None


def mailbox_cap_bytes() -> int:
    """The server's effective per-mailbox byte cap (0 = unlimited).

    Reads the value the SERVING process published at startup; falls back
    to this process's own ``BLUEFOG_CP_MAILBOX_MAX_MB`` when the server
    predates the publish (an external actor's server, e.g. tests that
    start :class:`ControlPlaneServer` directly). Cached per attachment —
    the cap is fixed at server startup."""
    global _cap_cache
    if _cap_cache is not None:
        return _cap_cache
    cap = None
    if _client is not None:
        v = _client.get(_MAILBOX_CAP_KEY)
        if v > 0:
            cap = int(v) - 1
    if cap is None:
        cap = int(float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB")) * (1 << 20))
    _cap_cache = cap
    return cap


# -- float scalars over the int64 KV (IEEE754 bit-packing) ------------------

def put_float(cl: ControlPlaneClient, key: str, value: float) -> None:
    cl.put(key, struct.unpack("<q", struct.pack("<d", float(value)))[0])


def get_float(cl: ControlPlaneClient, key: str) -> float:
    return struct.unpack("<d", struct.pack("<q", cl.get(key)))[0]


def owned_ranks(devices, process_index: int) -> List[int]:
    """Ranks whose device shard this controller hosts."""
    return [
        r for r, d in enumerate(devices)
        if getattr(d, "process_index", 0) == process_index
    ]
