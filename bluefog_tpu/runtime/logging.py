"""Logging for the bluefog_tpu runtime.

Analog of BlueFog's BFLOG macros (reference: common/logging.{h,cc}); level is
controlled by BLUEFOG_LOG_LEVEL (trace..fatal) and BLUEFOG_LOG_HIDE_TIME.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(_LEVELS["trace"], "TRACE")

logger = logging.getLogger("bluefog_tpu")


class _RankPrefixFilter(logging.Filter):
    """Injects a ``[rank r / inc i]`` prefix once ``bf.init`` has run.

    Interleaved multi-process logs (bfrun fan-out multiplexes every
    child's stderr onto one terminal) are unattributable without it. The
    identity is resolved LAZILY per record — at import time neither the
    process index nor the incarnation exists yet — and any failure
    degrades to an empty prefix: log formatting must never raise.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        record.bfprefix = self._prefix()
        return True

    @staticmethod
    def _prefix() -> str:
        try:
            from .state import _global_state

            st = _global_state()
            if not st.initialized:
                return ""
            from . import control_plane as _cp

            return f"[rank {st.process_index} / inc {_cp.incarnation()}] "
        except Exception:  # noqa: BLE001 — formatting must never raise
            return ""


def _configure() -> None:
    if logger.handlers:
        return
    level = _LEVELS.get(os.environ.get("BLUEFOG_LOG_LEVEL", "warn").lower(),
                        logging.WARNING)
    hide_time = os.environ.get("BLUEFOG_LOG_HIDE_TIME", "0") == "1"
    fmt = "[%(levelname)s] %(bfprefix)s%(message)s" if hide_time else \
        "%(asctime)s [%(levelname)s] %(bfprefix)s%(message)s"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(fmt))
    handler.addFilter(_RankPrefixFilter())
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False


_configure()
