"""Logging for the bluefog_tpu runtime.

Analog of BlueFog's BFLOG macros (reference: common/logging.{h,cc}); level is
controlled by BLUEFOG_LOG_LEVEL (trace..fatal) and BLUEFOG_LOG_HIDE_TIME.
"""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "trace": logging.DEBUG - 5,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

logging.addLevelName(_LEVELS["trace"], "TRACE")

logger = logging.getLogger("bluefog_tpu")


def _configure() -> None:
    if logger.handlers:
        return
    level = _LEVELS.get(os.environ.get("BLUEFOG_LOG_LEVEL", "warn").lower(),
                        logging.WARNING)
    hide_time = os.environ.get("BLUEFOG_LOG_HIDE_TIME", "0") == "1"
    fmt = "[%(levelname)s] %(message)s" if hide_time else \
        "%(asctime)s [%(levelname)s] %(message)s"
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(fmt))
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False


_configure()
