"""Global runtime state: device mesh, topology, windows.

This is the TPU-native analog of BlueFog's ``BluefogGlobalState`` +
``bluefog_init``/``bluefog_set_topology`` C API (reference: common/global_state.h:44-100,
operations.cc:1165-1304, basics.py:47-65). The big design departure: there is
no background communication thread and no rank-0 negotiation. Ranks are
*devices in a jax Mesh* driven by one SPMD program, so op ordering is static
at compile time — which is exactly the fast path BlueFog exposes as
``skip_negotiate_stage`` (operations.cc:1113-1135). Validation that the
negotiation stage performed (shape/dtype/name consistency across ranks) is
done eagerly in Python in the ops layer instead.

Topology changes are a host-side re-plan followed by fresh jit traces — the
analog of the reference's 3-flag epoch handshake pausing the background loop
(operations.cc:1273-1283) is simply cache invalidation here.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional

import networkx as nx
import numpy as np

import jax
from jax.sharding import Mesh

from .. import topology as topology_util
from . import handles
from .config import Config
from .logging import logger


class BluefogTPUState:
    """Singleton process state. One per Python process (controller)."""

    def __init__(self) -> None:
        self.initialized = False
        self.config: Config = Config()
        self.devices: List[Any] = []
        self.size: int = 0
        self.local_size: int = 1
        self.local_rank: int = 0
        self.process_index: int = 0
        self.process_count: int = 1
        self.mesh: Optional[Mesh] = None
        self.machine_mesh: Optional[Mesh] = None
        self.topology: Optional[nx.DiGraph] = None
        self.is_topo_weighted: bool = False
        # Window registry: name -> bluefog_tpu.ops.windows.Window
        self.windows: Dict[str, Any] = {}
        self.win_mutex_lock = threading.RLock()
        # Window gossip plane policy (policy, hosted_forced), resolved once
        # per init from BLUEFOG_WIN_PLANE / the legacy alias — every window
        # created in this job sees one consistent verdict even if the env
        # mutates mid-run (ops/windows._plane_policy).
        self.win_plane = None
        # Global toggle: win ops also move the associated push-sum scalar p
        # (reference: mpi_ops.py:1339-1363).
        self.win_ops_with_associated_p = False
        self.skip_negotiate: bool = False
        self.timeline = None  # runtime.timeline.Timeline when enabled
        self.watchdog = None  # runtime.watchdog.StallWatchdog when enabled
        self.peer_monitor = None  # runtime.heartbeat.PeerMonitor (multi-ctrl)
        self._plan_cache: Dict[Any, Any] = {}  # compiled combine plans
        # combine-matrix hashes every controller has agreed on
        # (ops.neighbors.cross_controller_topo_check)
        self._topo_check_agreed: set = set()
        self._topo_check_calls: int = 0  # re-arm cadence counter

    # -- lifecycle ---------------------------------------------------------

    def check_initialized(self) -> None:
        if not self.initialized:
            raise RuntimeError(
                "bluefog_tpu is not initialized; call bluefog_tpu.init() first."
            )


_state = BluefogTPUState()


def _global_state() -> BluefogTPUState:
    return _state


_distributed_initialized = False


def _maybe_init_distributed() -> None:
    """Join the multi-host job when the launcher exported coordinator env.

    The analog of the reference's MPI_Init across ranks (operations.cc
    :1165-1182): ``bfrun -np K --coordinator host:port --process-id i``
    exports JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    (launcher.py), and jax.distributed stitches the hosts into one global
    device set. On TPU pods with the runtime's own metadata, argument-free
    initialize() also works; we only force it when the env is present so
    single-host usage stays zero-config.
    """
    global _distributed_initialized
    import os

    if _distributed_initialized or "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
    )
    _distributed_initialized = True
    logger.info(
        "joined distributed job: process %d/%d",
        jax.process_index(), jax.process_count(),
    )


def init(
    topology_fn=None,
    is_weighted: bool = False,
    devices: Optional[List[Any]] = None,
    local_size: Optional[int] = None,
) -> None:
    """Initialize the runtime over the available TPU devices.

    Analog of ``bf.init(topology_fn, is_weighted)`` (reference: basics.py:47-65).
    Rather than MPI_Init across processes, this builds a 1-D rank mesh (and a
    2-D machine × local mesh for hierarchical ops) over ``jax.devices()``.

    Args:
      topology_fn: size -> nx.DiGraph; defaults to ExponentialTwoGraph, the
        reference default (basics.py:59-65).
      is_weighted: use the graph's edge weights for averaging instead of
        uniform 1/(indegree+1).
      devices: explicit device list (default jax.devices()).
      local_size: devices per "machine" for hierarchical ops; defaults to
        jax.local_device_count() (all devices of this host).
    """
    st = _state
    if st.initialized:
        # Re-init: tear down locally WITHOUT announcing coordinated shutdown
        # — the job is not ending, and the flag would spuriously (and
        # permanently) trip every peer's shutdown_requested().
        shutdown(_announce=False)

    st.config = Config.from_env()
    for knob in st.config.ignored_set:
        logger.info("env %s has no effect on TPU (transport is XLA-managed)", knob)

    _maybe_init_distributed()
    # Multi-controller scalar coordination (window mutexes/versions/p,
    # cross-controller barrier). No-op unless the job is multi-process or
    # BLUEFOG_CP_HOST is set (runtime/control_plane.py).
    from . import control_plane as _cp
    _cp.attach()
    # Fresh telemetry epoch for the job: instruments zero in place (cached
    # bound methods in subsystems stay valid) and the native transport
    # counter block re-baselines, so snapshots report this job's deltas.
    from . import metrics as _metrics
    _metrics.reset_for_job()
    # Fresh live time-series plane (ring history, per-edge estimators,
    # alert-rule state; re-reads BLUEFOG_ALERT_RULES/TS_* knobs).
    from . import timeseries as _timeseries
    _timeseries.reset_for_job()
    # Fresh self-tuning controller state (hysteresis clocks, codec
    # levels, demotion view; re-reads BLUEFOG_TUNE* knobs).
    from . import tuner as _tuner
    _tuner.reset_for_job()
    # Fresh flight-recorder ring + wall-clock anchor (a postmortem dump
    # belongs to THIS job), and the abnormal-exit hook so an uncaught
    # exception leaves a dump behind (docs/flight_recorder.md).
    from . import flight as _flight
    _flight.reset_for_job()
    _flight.install_excepthook()
    if _cp.active():
        # eager remote-trigger latch: bumps AFTER this point fire even if
        # they land before the first heartbeat/watchdog poll tick
        _flight.latch_trigger(_cp.client())
    if devices is None and st.config.simulate_devices > 0:
        # bfrun --simulate N: rank over forced-CPU devices even when an
        # accelerator backend registered (launcher.py:62-68). N counts
        # devices PER PROCESS; a multi-controller simulate job ranks over
        # the whole aggregated CPU device set.
        want = st.config.simulate_devices * jax.process_count("cpu")
        devices = jax.devices("cpu")[:want]
        if len(devices) < want:
            raise RuntimeError(
                f"BLUEFOG_SIMULATE_DEVICES={st.config.simulate_devices} but "
                f"only {len(devices)} CPU devices exist; set XLA_FLAGS="
                "--xla_force_host_platform_device_count (bfrun does this)"
            )
    st.devices = list(devices if devices is not None else jax.devices())
    st.size = len(st.devices)
    # Process identity of the backend the mesh actually lives on. The
    # argless jax.process_index()/process_count() read the DEFAULT backend,
    # which can be a different (single-process) platform than the mesh —
    # e.g. ranks on a multi-process CPU job while an accelerator plugin is
    # the default. Reference analog: rank comes from the communicator the
    # job runs on, not from the environment at large.
    platform = getattr(st.devices[0], "platform", None)
    try:
        st.process_index = jax.process_index(platform)
        st.process_count = jax.process_count(platform)
    except RuntimeError:
        st.process_index = jax.process_index()
        st.process_count = jax.process_count()
    if local_size:
        st.local_size = int(local_size)
    else:
        mine = [
            d for d in st.devices
            if getattr(d, "process_index", 0) == st.process_index
        ]
        st.local_size = max(1, len(mine))
    if st.size % st.local_size != 0:
        # Heterogeneous layout: hierarchical ops will refuse to run
        # (reference requires homogeneity too, mpi_ops.py:693-741).
        logger.warning(
            "size %d not divisible by local_size %d; hierarchical ops disabled",
            st.size, st.local_size,
        )
        st.machine_mesh = None
    st.mesh = Mesh(np.array(st.devices), ("rank",))
    if st.size % st.local_size == 0 and st.size >= st.local_size:
        st.machine_mesh = Mesh(
            np.array(st.devices).reshape(st.size // st.local_size, st.local_size),
            ("machine", "local"),
        )
    st.local_rank = _compute_local_rank()
    # Elastic rejoin: a respawned rank (BLUEFOG_INCARNATION > 0, exported
    # by bfrun --elastic) attached with a bumped incarnation above — the
    # server fenced its zombie predecessor and GC'd its state. It now
    # enters QUARANTINE: registered in membership but excluded from
    # averaging until a window optimizer completes state transfer
    # (runtime/heartbeat.py, docs/fault_tolerance.md "Rejoin & fencing").
    from .heartbeat import enter_quarantine

    enter_quarantine(st.process_index)
    st.skip_negotiate = st.config.skip_negotiate
    st.windows = {}
    # One plane-policy verdict per job (ISSUE r13): windows consult this
    # instead of re-reading the env per creation, so a mid-job env change
    # can't give two windows of one optimizer different planes.
    from ..ops.windows import _plane_policy

    st.win_plane = _plane_policy()
    if st.win_plane[0] != "auto" or st.win_plane[1] is not None:
        logger.info("window plane policy: %s (hosted forced: %s)",
                    st.win_plane[0], st.win_plane[1])
    st.win_ops_with_associated_p = False
    st._plan_cache = {}
    st._topo_check_agreed = set()
    st._topo_check_calls = 0
    st.initialized = True

    if topology_fn is not None:
        topo = topology_fn(st.size)
    else:
        topo = topology_util.ExponentialTwoGraph(st.size)
        is_weighted = False
    if not set_topology(topo, is_weighted=is_weighted):
        raise RuntimeError("failed to set initial topology")

    if st.config.timeline_prefix:
        from .timeline import Timeline

        # st.process_index, not the Timeline default (argless
        # jax.process_index() reads the DEFAULT backend): co-hosted
        # controllers must not clobber each other's trace file.
        st.timeline = Timeline(st.config.timeline_prefix,
                               process_index=st.process_index)

    from .watchdog import StallWatchdog

    st.watchdog = StallWatchdog(
        warning_sec=st.config.stall_warning_sec,
        cycle_ms=st.config.cycle_time_ms,
    )
    st.watchdog.start()

    # Cross-controller failure detection + coordinated shutdown (reference:
    # stall check operations.cc:387-432, SHUTDOWN broadcast :1074-1095).
    if st.process_count > 1:
        from .heartbeat import PeerMonitor

        st.peer_monitor = PeerMonitor(st.process_index, st.process_count)
        st.peer_monitor.start()

    # Telemetry publication (BLUEFOG_METRICS_INTERVAL / _PROM): the
    # heartbeat tick carries it in multi-controller jobs; single-controller
    # jobs get a dedicated cadence thread (runtime/metrics.py).
    _metrics.start_publisher_if_needed(
        has_heartbeat=st.peer_monitor is not None)

    logger.info(
        "bluefog_tpu initialized: %d rank(s) on %s, local_size=%d",
        st.size, st.devices[0].platform, st.local_size,
    )


def shutdown(_announce: bool = True) -> None:
    """Tear down runtime state; analog of ``bf.shutdown`` (operations.cc:1205-1215).

    Outstanding window state is dropped; the stall watchdog, heartbeat
    monitor, and timeline writer threads are joined. In multi-controller
    jobs the coordinated-shutdown flag is published first (the analog of
    the reference's SHUTDOWN broadcast, operations.cc:1074-1095) so peers
    can exit before hanging on a collective with this process's devices.
    """
    st = _state
    if not st.initialized:
        return
    from . import control_plane as _cp
    from .heartbeat import announce_shutdown
    if _announce and st.process_count > 1:
        # Coordinated: peers learn the job is ending BEFORE this process
        # (possibly the control-plane server host) tears anything down.
        announce_shutdown(st.process_index, st.process_count)
    from . import metrics as _metrics
    if _metrics.publication_enabled():
        # final flush: short jobs (and clean exits generally) leave a
        # current scrape + KV snapshot even if no cadence tick ever fired
        try:
            _metrics.publish_now()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
    from . import timeseries as _timeseries
    try:
        # same final flush for the live series: one last sample + delta
        _timeseries.maybe_sample(force=True, publish=True)
    except Exception:  # noqa: BLE001 — teardown must not raise
        pass
    _metrics.stop_publisher()
    if st.peer_monitor is not None:
        st.peer_monitor.stop()
        st.peer_monitor = None
    # Release hosted-plane server state (published tensors, pending
    # deposits) BEFORE detaching the client it needs. Best-effort and
    # unaligned: peers may already be gone, so no close-time barriers —
    # an externally shared control-plane server must not keep dead
    # windows' bytes for its lifetime (ADVICE r3).
    for win in list(st.windows.values()):
        try:
            win.close(aligned=False)
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass
    _cp.detach()
    if st.watchdog is not None:
        st.watchdog.stop()
        st.watchdog = None
    # close open per-op spans BEFORE the timeline so the trace stays
    # balanced (every B gets its E edge)
    handles.close_all_spans()
    if st.timeline is not None:
        st.timeline.close()
        st.timeline = None
    st.windows.clear()
    st._plan_cache.clear()
    handles.clear()
    st.mesh = None
    st.machine_mesh = None
    st.topology = None
    st.initialized = False


atexit.register(shutdown)


# -- introspection (parity: basics.py:120-186) -----------------------------

def size() -> int:
    _state.check_initialized()
    return _state.size


def local_size() -> int:
    _state.check_initialized()
    return _state.local_size


def num_machines() -> int:
    _state.check_initialized()
    return _state.size // _state.local_size


def machine_size() -> int:
    return num_machines()


def rank() -> int:
    """Index of this controller process.

    In the reference each process is one rank; on TPU one controller drives
    many devices, so per-device rank only exists inside SPMD code (as the
    rank-axis index). This returns the process index of the mesh's backend
    for launcher parity.
    """
    _state.check_initialized()
    return _state.process_index


def _compute_local_rank() -> int:
    """Index of this controller among controllers on the same physical host.

    The reference reads this off MPI's LOCAL communicator
    (mpi_context.cc local comm split). Multi-controller jobs here register
    their hostname in the control-plane KV and count lower-indexed
    co-hosted processes; single-controller jobs are trivially 0.
    """
    from . import control_plane as _cp

    st = _state
    if st.process_count <= 1 or not _cp.active():
        return 0
    import socket
    import zlib

    cl = _cp.client()
    me = st.process_index
    h = zlib.crc32(socket.gethostname().encode())
    cl.put(f"bf.host.{me}", h)
    if _cp.incarnation() == 0:
        cl.barrier("bf.local_rank")
    # A rejoining incarnation must NOT barrier: the surviving peers are deep
    # in their training loops and would never arrive — their host keys from
    # the original launch are already published, which is all we read.
    return sum(
        1 for i in range(st.process_count)
        if i < me and cl.get(f"bf.host.{i}") == h
    )


def local_rank() -> int:
    """This controller's index among co-hosted controllers (see
    :func:`_compute_local_rank`); 0 in single-controller deployments."""
    _state.check_initialized()
    return _state.local_rank


def is_homogeneous() -> bool:
    """All machines have the same device count (reference: mpi_controller.cc:71-96)."""
    _state.check_initialized()
    return _state.size % _state.local_size == 0


def mesh() -> Mesh:
    _state.check_initialized()
    return _state.mesh


def machine_mesh() -> Mesh:
    _state.check_initialized()
    if _state.machine_mesh is None:
        raise RuntimeError("hierarchical mesh unavailable (heterogeneous layout)")
    return _state.machine_mesh


# -- topology management (parity: basics.py:188-291) -----------------------

def set_topology(topology: Optional[nx.DiGraph] = None, is_weighted: bool = False) -> bool:
    """Install a new virtual topology; returns False if rejected.

    Mirrors ``bf.set_topology`` semantics (basics.py:188-271): rejected with a
    warning when windows exist (torch_basics_test.py:63-78 relies on this) or
    when the node count mismatches; equivalent topology is a cheap no-op.
    """
    st = _state
    st.check_initialized()
    if topology is None:
        topology = topology_util.ExponentialTwoGraph(st.size)
        is_weighted = False
    if not isinstance(topology, nx.DiGraph):
        logger.error("set_topology requires a networkx.DiGraph")
        return False
    if topology.number_of_nodes() != st.size:
        logger.error(
            "topology has %d nodes but runtime has %d ranks",
            topology.number_of_nodes(), st.size,
        )
        return False
    if st.windows:
        logger.error(
            "cannot change topology while windows exist; call win_free first"
        )
        return False
    if (
        st.topology is not None
        and topology_util.IsTopologyEquivalent(topology, st.topology)
        and is_weighted == st.is_topo_weighted
    ):
        logger.debug("topology unchanged; skipping re-plan")
        return True
    st.topology = topology
    st.is_topo_weighted = is_weighted
    st._plan_cache.clear()  # new graph -> new combine plans / jit traces
    st._topo_check_agreed.clear()
    st._topo_check_calls = 0
    return True


def load_topology() -> nx.DiGraph:
    _state.check_initialized()
    return _state.topology


def is_topo_weighted() -> bool:
    _state.check_initialized()
    return _state.is_topo_weighted


def in_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    """Sorted in-neighbors of ``rank_`` (default: rank 0 for parity calls)."""
    _state.check_initialized()
    r = 0 if rank_ is None else rank_
    return topology_util.in_neighbor_ranks(_state.topology, r)


def out_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    _state.check_initialized()
    r = 0 if rank_ is None else rank_
    return topology_util.out_neighbor_ranks(_state.topology, r)


def set_skip_negotiate_stage(value: bool) -> None:
    """Disable eager cross-rank validation in the ops layer.

    Under jit there is never a negotiation stage (op order is compiled); this
    only controls the eager debug checks (reference: basics.py:293-306).
    """
    _state.check_initialized()
    _state.skip_negotiate = bool(value)


def get_skip_negotiate_stage() -> bool:
    """Whether eager cross-rank validation is skipped (basics.py:304-306)."""
    _state.check_initialized()
    return _state.skip_negotiate


def unified_mpi_window_model_supported() -> bool:
    """Always True: the mailbox window model has one coherent store per
    rank by construction — the property the reference probes MPI for
    (basics.py:119-128, MPI_WIN_UNIFIED) before allowing win ops."""
    return True


def mpi_threads_supported() -> bool:
    """Always True: op dispatch is plain thread-safe Python/XLA calls, the
    guarantee the reference asks MPI_THREAD_MULTIPLE for (basics.py
    :129-143). (The name keeps the reference's spelling; there is no MPI.)"""
    return True


def nccl_built() -> bool:
    """Always False: there is no NCCL transport — collectives ride XLA over
    ICI/DCN (basics.py:285-292's probe, answered honestly)."""
    return False
