"""ctypes bindings for the native host-runtime extension (csrc/bf_runtime.cc).

The native library provides the C++ subsystems of the rebuild (the analog of
the reference's C++ core, cf. SURVEY.md §2.1): the timeline writer
(timeline.cc) and the control-plane scalar protocols (distributed mutex /
fetch-and-op / barrier — mpi_controller.cc:1532-1602's window mutexes and
version counters, served over TCP for multi-controller deployments).

Built lazily with g++ on first use; every consumer must degrade gracefully
when the toolchain is unavailable (``load()`` returns None).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from .logging import logger

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SO = os.path.join(_CSRC, "build", "libbf_runtime.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bf_timeline_open.restype = ctypes.c_void_p
    lib.bf_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bf_timeline_event.restype = None
    lib.bf_timeline_event.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int,
    ]
    lib.bf_timeline_close.restype = None
    lib.bf_timeline_close.argtypes = [ctypes.c_void_p]

    lib.bf_cp_serve.restype = ctypes.c_void_p
    lib.bf_cp_serve.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.bf_cp_server_port.restype = ctypes.c_int
    lib.bf_cp_server_port.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_stop.restype = None
    lib.bf_cp_server_stop.argtypes = [ctypes.c_void_p]
    lib.bf_cp_connect.restype = ctypes.c_void_p
    lib.bf_cp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    for fname in ("bf_cp_barrier", "bf_cp_lock", "bf_cp_unlock", "bf_cp_get"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    for fname in ("bf_cp_fetch_add", "bf_cp_put"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.bf_cp_disconnect.restype = None
    lib.bf_cp_disconnect.argtypes = [ctypes.c_void_p]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            script = os.path.join(_CSRC, "build.sh")
            if not os.path.exists(script):
                return None
            try:
                subprocess.run(["sh", script], check=True,
                               capture_output=True, timeout=120)
            except (subprocess.SubprocessError, OSError) as exc:
                logger.info("native runtime build failed (%s); "
                            "using pure-Python fallbacks", exc)
                return None
        try:
            _lib = _configure(ctypes.CDLL(_SO))
        except OSError as exc:
            logger.info("native runtime load failed (%s)", exc)
            _lib = None
        return _lib


class ControlPlaneServer:
    """Coordinator side of the scalar control plane (one per job)."""

    def __init__(self, world: int, port: int = 0) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.bf_cp_serve(port, world)
        if not self._h:
            raise OSError(f"control plane failed to bind port {port}")
        self.port = lib.bf_cp_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.bf_cp_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class ControlPlaneClient:
    """Per-controller client: mutexes, counters, barriers, scalar KV."""

    def __init__(self, host: str, port: int, rank: int) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.bf_cp_connect(host.encode(), port, rank)
        if not self._h:
            raise OSError(f"control plane connect to {host}:{port} failed")

    def barrier(self, name: str = "default") -> int:
        return self._lib.bf_cp_barrier(self._h, name.encode())

    def lock(self, name: str) -> None:
        self._lib.bf_cp_lock(self._h, name.encode())

    def unlock(self, name: str) -> None:
        self._lib.bf_cp_unlock(self._h, name.encode())

    def fetch_add(self, name: str, delta: int = 1) -> int:
        """Atomic fetch-then-add; returns the pre-add value
        (MPI_Fetch_and_op semantics, mpi_controller.cc:1532-1602)."""
        return self._lib.bf_cp_fetch_add(self._h, name.encode(), delta)

    def put(self, name: str, value: int) -> None:
        self._lib.bf_cp_put(self._h, name.encode(), value)

    def get(self, name: str) -> int:
        return self._lib.bf_cp_get(self._h, name.encode())

    def close(self) -> None:
        if self._h:
            self._lib.bf_cp_disconnect(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
