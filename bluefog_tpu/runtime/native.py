"""ctypes bindings for the native host-runtime extension (csrc/bf_runtime.cc).

The native library provides the C++ subsystems of the rebuild (the analog of
the reference's C++ core, cf. SURVEY.md §2.1): the timeline writer
(timeline.cc) and the control-plane scalar protocols (distributed mutex /
fetch-and-op / barrier — mpi_controller.cc:1532-1602's window mutexes and
version counters, served over TCP for multi-controller deployments).

Built lazily with g++ on first use; every consumer must degrade gracefully
when the toolchain is unavailable (``load()`` returns None).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Optional

from .logging import logger
from .protocol import OP_CODES, OP_NAMES as _OP_NAMES  # noqa: F401 — re-export

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SO = os.path.join(_CSRC, "build", "libbf_runtime.so")


def _so_path() -> str:
    """The shared library to load: ``BLUEFOG_NATIVE_SO`` overrides the
    default build product — how ``make tsan`` / ``make asan`` point the
    whole Python runtime at a sanitizer-instrumented build without
    touching the normal artifact."""
    return os.environ.get("BLUEFOG_NATIVE_SO") or _SO

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


class PeerLostError(RuntimeError):
    """A blocking control-plane primitive was woken because a peer died.

    Raised instead of hanging when a lock/mutex holder's connection closed
    (or its lease expired), a barrier's bounded wait hit its deadline, or a
    critical section was force-broken mid-hold. ``dead`` carries the
    heartbeat monitor's dead-controller set at raise time (it may still be
    empty when the server noticed the death before a heartbeat timeout
    elapsed). The contract is documented in docs/fault_tolerance.md.
    """

    def __init__(self, message: str, dead=()) -> None:
        self.dead = set(dead)
        if self.dead:
            message += (f" [dead controller(s) {sorted(self.dead)} per "
                        "bf.dead_controllers()]")
        super().__init__(message)


def _dead_controller_set() -> set:
    """The heartbeat monitor's current dead set (empty when unavailable).

    Imported lazily: heartbeat -> control_plane -> native is the module
    load order, so a top-level import here would be circular."""
    try:
        from .heartbeat import dead_controllers

        return dead_controllers()
    except Exception:  # noqa: BLE001 — raise-path helper must not mask
        return set()


def _peer_lost(message: str) -> PeerLostError:
    return PeerLostError(message, dead=_dead_controller_set())


class StaleIncarnationError(RuntimeError):
    """This client's (rank, incarnation) registration was superseded.

    Raised when the control-plane server fences a request because the same
    rank re-registered with a NEWER incarnation — this process is a zombie
    of a restarted rank (its replacement is already attached). The server
    has garbage-collected this incarnation's dedup records, mailbox
    deposits, and lock holdings; nothing this process does can reach shared
    state again, so the only correct reaction is to exit. Never retried by
    the transport (unlike a wire failure). See docs/fault_tolerance.md,
    "Rejoin & fencing".
    """


class QuorumLostError(RuntimeError):
    """A mutating control-plane op was rejected: the shard is below its
    commit quorum (r20 quorum replication, ``BLUEFOG_CP_REPLICATION>=3``).

    The serving shard cannot reach ack-from-⌈R/2⌉ of its replica set —
    it is on the minority side of a network partition (or too many
    replicas died at once). Rather than silently applying the write
    locally and minting split-brain state, the server degrades to
    READ-ONLY: reads still serve, every mutation gets this typed
    rejection. The condition clears when the partition heals (or enough
    replicas return); callers that can wait should back off and retry,
    callers that cannot should surface the error. Never raised at R<=2
    (the legacy chain degrades to unreplicated instead; see
    docs/fault_tolerance.md, "Partitions & quorum").
    """


# Status codes shared with csrc/bf_runtime.cc: -1 wire failure, -2 mailbox
# byte cap, -3 dead holder / deadline on a blocking primitive, -4 stale
# incarnation (fenced zombie), -5 below commit quorum (partition-aware
# read-only degrade; typed as QuorumLostError).
_DEAD_HOLDER = -3
_STALE = -4
_QUORUM_LOST = -5


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bf_timeline_open.restype = ctypes.c_void_p
    lib.bf_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bf_timeline_event.restype = None
    lib.bf_timeline_event.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int,
    ]
    lib.bf_timeline_close.restype = None
    lib.bf_timeline_close.argtypes = [ctypes.c_void_p]
    # arg-carrying events (r10): counter tracks ('C') and flow binding
    # ('s'/'f') need an int64 value/id alongside the classic fields
    lib.bf_timeline_event2.restype = None
    lib.bf_timeline_event2.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
    ]

    lib.bf_cp_serve.restype = ctypes.c_void_p
    lib.bf_cp_serve.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.bf_cp_serve_auth.restype = ctypes.c_void_p
    lib.bf_cp_serve_auth.argtypes = [ctypes.c_int, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int64]
    lib.bf_cp_serve_auth2.restype = ctypes.c_void_p
    lib.bf_cp_serve_auth2.argtypes = [ctypes.c_int, ctypes.c_int,
                                      ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_int]
    lib.bf_cp_serve_auth3.restype = ctypes.c_void_p
    lib.bf_cp_serve_auth3.argtypes = [ctypes.c_int, ctypes.c_int,
                                      ctypes.c_char_p, ctypes.c_int64,
                                      ctypes.c_int, ctypes.c_int]
    lib.bf_cp_server_port.restype = ctypes.c_int
    lib.bf_cp_server_port.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_stop.restype = None
    lib.bf_cp_server_stop.argtypes = [ctypes.c_void_p]
    lib.bf_cp_connect.restype = ctypes.c_void_p
    lib.bf_cp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.bf_cp_connect_auth.restype = ctypes.c_void_p
    lib.bf_cp_connect_auth.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_char_p]
    lib.bf_cp_connect_auth2.restype = ctypes.c_void_p
    lib.bf_cp_connect_auth2.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_char_p,
                                        ctypes.c_int]
    lib.bf_cp_bytes_len.restype = ctypes.c_int64
    lib.bf_cp_bytes_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bf_cp_put_bytes_part.restype = ctypes.c_int64
    lib.bf_cp_put_bytes_part.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.bf_cp_get_bytes_part.restype = ctypes.c_int64
    lib.bf_cp_get_bytes_part.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.bf_cp_put_bytes_striped.restype = ctypes.c_int64
    lib.bf_cp_put_bytes_striped.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_char_p,
        ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.bf_cp_get_bytes_striped.restype = ctypes.c_int64
    lib.bf_cp_get_bytes_striped.argtypes = [
        ctypes.POINTER(ctypes.c_void_p), ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    for fname in ("bf_cp_barrier", "bf_cp_lock", "bf_cp_unlock", "bf_cp_get"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    for fname in ("bf_cp_fetch_add", "bf_cp_put", "bf_cp_put_max"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    # remote per-shard counter read (sharded control plane, kStats)
    lib.bf_cp_remote_stats.restype = ctypes.c_int
    lib.bf_cp_remote_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
    for fname in ("bf_cp_append_bytes", "bf_cp_put_bytes"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                       ctypes.c_int64]
    for fname in ("bf_cp_take_bytes", "bf_cp_get_bytes"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                       ctypes.POINTER(ctypes.c_void_p),
                       ctypes.POINTER(ctypes.c_int64)]
    lib.bf_cp_free.restype = None
    lib.bf_cp_free.argtypes = [ctypes.c_void_p]
    lib.bf_cp_multi.restype = ctypes.c_int64
    lib.bf_cp_multi.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_outv.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_outv.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_outv_tagged.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_outv_tagged.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_in.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_in.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bf_cp_disconnect.restype = None
    lib.bf_cp_disconnect.argtypes = [ctypes.c_void_p]
    # incarnation fencing (r9 elastic membership)
    lib.bf_cp_attach.restype = ctypes.c_int64
    lib.bf_cp_attach.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.bf_cp_is_stale.restype = ctypes.c_int
    lib.bf_cp_is_stale.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_dedup_entries.restype = ctypes.c_longlong
    lib.bf_cp_server_dedup_entries.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_mailbox_from.restype = ctypes.c_longlong
    lib.bf_cp_server_mailbox_from.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.bf_cp_server_incarnation.restype = ctypes.c_longlong
    lib.bf_cp_server_incarnation.argtypes = [ctypes.c_void_p, ctypes.c_int]
    # telemetry counter blocks (r10 observability)
    lib.bf_cp_client_counters.restype = ctypes.c_int
    lib.bf_cp_client_counters.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
    lib.bf_cp_server_counters.restype = ctypes.c_int
    lib.bf_cp_server_counters.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
    # fault injection + dead-connection hooks (r8 fault tolerance)
    lib.bf_cp_fault.restype = None
    lib.bf_cp_fault.argtypes = [ctypes.c_longlong, ctypes.c_int,
                                ctypes.c_int, ctypes.c_longlong]
    lib.bf_cp_fault_drops.restype = ctypes.c_longlong
    lib.bf_cp_fault_drops.argtypes = []
    lib.bf_cp_fault_ops.restype = ctypes.c_longlong
    lib.bf_cp_fault_ops.argtypes = []
    lib.bf_cp_server_drop_conns.restype = None
    lib.bf_cp_server_drop_conns.argtypes = [ctypes.c_void_p]
    # transport flight ring (r12 observability)
    lib.bf_flight_ring.restype = ctypes.c_int
    lib.bf_flight_ring.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_int]
    # WAL replication + shard rejoin (r16 durable control plane)
    lib.bf_cp_server_set_successor.restype = ctypes.c_int
    lib.bf_cp_server_set_successor.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int,
        ctypes.c_int]
    lib.bf_cp_snapshot.restype = ctypes.c_int64
    lib.bf_cp_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int64)]
    lib.bf_cp_server_load_snapshot.restype = ctypes.c_longlong
    lib.bf_cp_server_load_snapshot.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int]
    lib.bf_cp_server_set_rejoin_pending.restype = None
    lib.bf_cp_server_set_rejoin_pending.argtypes = [ctypes.c_void_p]
    lib.bf_cp_set_failover.restype = None
    lib.bf_cp_set_failover.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int]
    lib.bf_cp_failed_over.restype = ctypes.c_int
    lib.bf_cp_failed_over.argtypes = [ctypes.c_void_p]
    # Quorum replication + partition injector (r20)
    lib.bf_cp_server_set_successors.restype = ctypes.c_int
    lib.bf_cp_server_set_successors.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.bf_cp_server_load_snapshot2.restype = ctypes.c_longlong
    lib.bf_cp_server_load_snapshot2.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int, ctypes.c_int]
    lib.bf_cp_server_reset_store.restype = None
    lib.bf_cp_server_reset_store.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_rejoin_done.restype = None
    lib.bf_cp_server_rejoin_done.argtypes = [ctypes.c_void_p]
    lib.bf_cp_set_failover2.restype = None
    lib.bf_cp_set_failover2.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bf_cp_client_set_group.restype = None
    lib.bf_cp_client_set_group.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.bf_cp_partition.restype = None
    lib.bf_cp_partition.argtypes = [ctypes.c_int, ctypes.c_char_p,
                                    ctypes.c_double, ctypes.c_double]
    lib.bf_cp_partition_heal.restype = None
    lib.bf_cp_partition_heal.argtypes = []
    lib.bf_cp_partition_disarm.restype = None
    lib.bf_cp_partition_disarm.argtypes = []
    lib.bf_cp_partition_active.restype = ctypes.c_int
    lib.bf_cp_partition_active.argtypes = []
    lib.bf_cp_partition_cuts.restype = ctypes.c_longlong
    lib.bf_cp_partition_cuts.argtypes = []
    return lib


# -- deterministic fault injection (BLUEFOG_CP_FAULT) -------------------------
#
# Spec grammar (comma-separated key=value, all integers, any subset):
#   drop_after=N   kill the client connection on every Nth control-plane op
#                  (alternating request-lost / reply-lost, the two classes
#                  the reconnect + dedup machinery must survive); 0 = off
#   delay_ms=M     sleep M ms inside every client op before the reply read
#                  (deterministic slow-peer emulation)
#   trunc=1        request-lost drops first write HALF the frame, so the
#                  server sees a truncated message, not a clean close
#   seed=S         shifts which ops the drop counter fires on
#   delay_edges=src>dst:ms,...
#                  per-EDGE deposit delay (ISSUE r16): sleep ms before the
#                  window deposit batch covering edge src->dst ships —
#                  deterministic bandwidth ASYMMETRY, the self-tuning
#                  controller's slow-edge fixture. Applied at the python
#                  deposit site (ops/windows.py), not inside the native
#                  client; terms after the first may ride further commas
#                  or ``;`` / ``|`` separators.
#   partition=0,1|2,3
#                  deterministic network partition (ISSUE r20): SHARD
#                  indices grouped into sides by ``|`` (bare numeric terms
#                  after the first ride the comma-separated spec). Connects
#                  and in-flight ops crossing the cut fail at the client
#                  socket layer, both directions; shards that lose their
#                  commit quorum degrade to read-only (QuorumLostError).
#                  The shard-index spec is resolved to listener ports and
#                  armed by the process that knows the port map
#                  (shard_server / cp_soak) via :func:`partition_arm`.
#   part_after=S   the cut activates S seconds after arming (float; 0 =
#                  immediately) — lets a soak arm it pre-fork and have it
#                  fire mid-run.
#   heal_after=S   the cut heals itself S seconds after activation
#                  (float; 0 = only on an explicit heal/disarm).
#
# OFF unless BLUEFOG_CP_FAULT is set (or a test arms it explicitly): the
# production path pays one relaxed atomic load per op, nothing else — the
# chaos suite asserts this default (tests/test_chaos.py).

def _parse_edge_delays(text: str) -> dict:
    """``src>dst:ms(;src>dst:ms)*`` -> {(src, dst): ms}."""
    out: dict = {}
    for term in str(text).replace("|", ";").split(";"):
        term = term.strip()
        if not term:
            continue
        try:
            edge_s, ms_s = term.rsplit(":", 1)
            src_s, dst_s = edge_s.split(">", 1)
            out[(int(src_s), int(dst_s))] = int(ms_s)
        except ValueError:
            raise ValueError(
                f"BLUEFOG_CP_FAULT: bad delay_edges term {term!r} "
                "(grammar: delay_edges=src>dst:ms,src>dst:ms,...)")
    return out


def parse_partition_groups(text: str) -> list:
    """``"0,1|2,3"`` -> ``[[0, 1], [2, 3]]`` (shard-index sides)."""
    groups = []
    for side in str(text).split("|"):
        side = side.strip()
        if not side:
            continue
        try:
            groups.append(sorted({int(t) for t in side.split(",")
                                  if t.strip()}))
        except ValueError:
            raise ValueError(
                f"BLUEFOG_CP_FAULT: bad partition side {side!r} "
                "(grammar: partition=0,1|2,3)")
    if len(groups) < 2:
        raise ValueError(
            "BLUEFOG_CP_FAULT: partition= needs at least two '|'-separated "
            "sides (grammar: partition=0,1|2,3)")
    seen: set = set()
    for g in groups:
        if seen.intersection(g):
            raise ValueError(
                "BLUEFOG_CP_FAULT: partition sides must be disjoint")
        seen.update(g)
    return groups


def parse_fault_spec(spec: str) -> dict:
    out = {"drop_after": 0, "delay_ms": 0, "trunc": 0, "seed": 0,
           "delay_edges": {}, "partition": None, "part_after": 0.0,
           "heal_after": 0.0}
    part_raw = None
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        key, sep, val = item.partition("=")
        key = key.strip()
        if sep and key == "delay_edges":
            out["delay_edges"].update(_parse_edge_delays(val))
            continue
        if not sep and ">" in item and ":" in item:
            # continuation of a comma-separated delay_edges list
            out["delay_edges"].update(_parse_edge_delays(item))
            continue
        if sep and key == "partition":
            part_raw = val.strip()
            continue
        if not sep and part_raw is not None and \
                item.replace("|", "").replace(" ", "").isdigit():
            # continuation of the comma-separated partition group spec
            part_raw += "," + item
            continue
        if not sep or key not in out or key in ("delay_edges", "partition"):
            raise ValueError(
                f"BLUEFOG_CP_FAULT: bad entry {item!r} (grammar: "
                "drop_after=N,delay_ms=M,trunc=0|1,seed=S,"
                "delay_edges=src>dst:ms,...,partition=0,1|2,3,"
                "part_after=S,heal_after=S)")
        if key in ("part_after", "heal_after"):
            out[key] = float(val.strip())
        else:
            out[key] = int(val.strip())
    if part_raw is not None:
        out["partition"] = parse_partition_groups(part_raw)
    return out


def fault_arm(spec=None, **overrides) -> dict:
    """Arm the native fault injector from a spec string / dict / kwargs.

    Resets the op and drop counters so injected drop points are
    reproducible run to run. Returns the effective spec."""
    lib = load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    cfg = parse_fault_spec(spec) if isinstance(spec, str) else \
        dict(spec or {"drop_after": 0, "delay_ms": 0, "trunc": 0, "seed": 0})
    cfg.update(overrides)
    lib.bf_cp_fault(int(cfg.get("drop_after", 0)),
                    int(cfg.get("delay_ms", 0)),
                    int(cfg.get("trunc", 0)), int(cfg.get("seed", 0)))
    global _edge_delays
    _edge_delays = dict(cfg.get("delay_edges") or {})
    return cfg


def fault_disarm() -> None:
    """Turn injection off (counters reset)."""
    global _edge_delays
    _edge_delays = {}
    lib = load()
    if lib is not None:
        lib.bf_cp_fault(0, 0, 0, 0)


# Per-edge deposit delays live python-side (the native client has no edge
# concept — a deposit is just a keyed append): lazily parsed from the env
# so they work even where the native library is unavailable, and kept in
# sync by fault_arm / fault_disarm.
_edge_delays: Optional[dict] = None


def edge_delays() -> dict:
    """{(src, dst): ms} from BLUEFOG_CP_FAULT's delay_edges clause
    (empty unless armed). ops/windows.py consults this per deposit
    batch; a malformed env spec degrades to no delays (the native arm
    path already warned)."""
    global _edge_delays
    if _edge_delays is None:
        cfg: dict = {}
        spec = os.environ.get("BLUEFOG_CP_FAULT")
        if spec:
            try:
                cfg = parse_fault_spec(spec).get("delay_edges") or {}
            except ValueError:
                cfg = {}
        _edge_delays = cfg
    return _edge_delays


def fault_stats() -> dict:
    """{'ops': client ops seen, 'drops': connections killed} since arm."""
    lib = load()
    if lib is None:
        return {"ops": 0, "drops": 0}
    return {"ops": int(lib.bf_cp_fault_ops()),
            "drops": int(lib.bf_cp_fault_drops())}


# -- deterministic partition injector (r20 quorum durability) -----------------

def partition_arm(port_groups: dict, self_group: int = -1,
                  start_after: float = 0.0, heal_after: float = 0.0) -> None:
    """Arm the native partition injector for THIS process.

    ``port_groups`` maps control-plane LISTENER ports to sides (the
    caller — shard_server, cp_soak, a test — resolves the shard-index
    spec from ``parse_fault_spec``'s ``partition`` field to ports, since
    only it knows the port map). ``self_group`` places this process's
    ordinary clients on a side (-1 = ungrouped: only server-side quorum
    gates and group-bound replicator streams enforce the cut). The cut
    activates ``start_after`` seconds from now and heals itself
    ``heal_after`` seconds after activation (0 = never / explicit only).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native runtime unavailable")
    spec = ",".join(f"{int(p)}:{int(g)}" for p, g in
                    sorted(port_groups.items()))
    lib.bf_cp_partition(int(self_group), spec.encode(),
                        float(start_after), float(heal_after))


def partition_heal() -> None:
    """Heal the armed cut now (idempotent; the cut counter survives)."""
    lib = load()
    if lib is not None:
        lib.bf_cp_partition_heal()


def partition_disarm() -> None:
    """Fully disarm the injector (port map cleared)."""
    lib = load()
    if lib is not None:
        lib.bf_cp_partition_disarm()


def partition_active() -> bool:
    """True while an armed cut is live (post-start, pre-heal)."""
    lib = load()
    return bool(lib is not None and lib.bf_cp_partition_active())


def partition_cuts() -> int:
    """Connects/ops this process failed at the injected cut since arming
    (feeds the ``cp.partitions`` counter trail)."""
    lib = load()
    return 0 if lib is None else int(lib.bf_cp_partition_cuts())


# Op-class names for the telemetry counter block: _OP_NAMES (imported
# above) is runtime/protocol.py's code->name table, the same source the
# C++ enum mirrors — one table, three consumers, bfcheck-verified.

_CL_SLOTS = 100  # 3*32 per-op triples + 4 event counters (csrc layout)


def client_stats() -> dict:
    """Cumulative native-client transport counters for this process.

    ``ops`` / ``bytes_out`` / ``bytes_in`` are keyed by op class (zero
    rows suppressed); ``redials`` counts successful transparent
    reconnects, ``redial_attempts`` every dial tried, ``stale_frames``
    incarnation-fence verdicts observed on the wire, and
    ``striped_transfers`` whole striped put/get operations. Counters are
    process-global and never reset — consumers (the metrics registry)
    report deltas against their own baseline. Empty dict when the native
    runtime is unavailable."""
    lib = load()
    if lib is None:
        return {}
    buf = (ctypes.c_longlong * _CL_SLOTS)()
    if lib.bf_cp_client_counters(buf, _CL_SLOTS) < 0:
        return {}
    ops, b_out, b_in = {}, {}, {}
    for code, name in _OP_NAMES.items():
        if buf[code]:
            ops[name] = int(buf[code])
        if buf[32 + code]:
            b_out[name] = int(buf[32 + code])
        if buf[64 + code]:
            b_in[name] = int(buf[64 + code])
    return {
        "ops": ops,
        "bytes_out": b_out,
        "bytes_in": b_in,
        "redials": int(buf[96]),
        "redial_attempts": int(buf[97]),
        "stale_frames": int(buf[98]),
        "striped_transfers": int(buf[99]),
    }


_FLIGHT_RING_MAX = 1024  # csrc kFlightCap


def flight_events() -> list:
    """The native transport's flight ring, oldest -> newest: a list of
    ``[wall_us, kind, a, b]`` rows (kinds: 1 redial attempt, 2 redial
    success, 3 stale frame, 4 per-stripe timing, 5 whole striped
    transfer, 6 failover redirect to the ring successor; a/b are
    bytes/micros for the timed kinds). Spliced into flight-recorder
    dumps (runtime/flight.py); empty when the native runtime is
    unavailable."""
    lib = load()
    if lib is None:
        return []
    buf = (ctypes.c_longlong * (4 * _FLIGHT_RING_MAX))()
    n = lib.bf_flight_ring(buf, _FLIGHT_RING_MAX)
    return [[int(buf[4 * j]), int(buf[4 * j + 1]), int(buf[4 * j + 2]),
             int(buf[4 * j + 3])] for j in range(max(0, n))]


def _arm_fault_from_env(lib) -> None:
    spec = os.environ.get("BLUEFOG_CP_FAULT")
    if not spec:
        return
    try:
        cfg = parse_fault_spec(spec)
    except ValueError as exc:
        logger.warning("ignoring BLUEFOG_CP_FAULT (%s)", exc)
        return
    lib.bf_cp_fault(cfg["drop_after"], cfg["delay_ms"], cfg["trunc"],
                    cfg["seed"])
    logger.warning("control-plane fault injection ARMED: %s "
                   "(BLUEFOG_CP_FAULT — never set this in production)", cfg)


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        so = _so_path()
        if not os.path.exists(so):
            if so != _SO:
                # an explicit BLUEFOG_NATIVE_SO that does not exist is a
                # misconfiguration, not a build trigger (sanitizer builds
                # are produced by `make tsan` / `make asan`, not lazily)
                logger.warning("BLUEFOG_NATIVE_SO=%s does not exist; "
                               "native runtime unavailable", so)
                return None
            script = os.path.join(_CSRC, "build.sh")
            if not os.path.exists(script):
                return None
            try:
                subprocess.run(["sh", script], check=True,
                               capture_output=True, timeout=120)
            except (subprocess.SubprocessError, OSError) as exc:
                logger.info("native runtime build failed (%s); "
                            "using pure-Python fallbacks", exc)
                return None
        try:
            _lib = _configure(ctypes.CDLL(so))
        except AttributeError:
            # A stale cached build predates a symbol _configure now needs
            # (the .so is gitignored; load() only builds when it's missing).
            # Rebuild once from the current sources and retry.
            logger.info("native runtime is stale (missing symbol); "
                        "rebuilding from csrc")
            try:
                subprocess.run(["sh", os.path.join(_CSRC, "build.sh")],
                               check=True, capture_output=True, timeout=120)
                _lib = _configure(ctypes.CDLL(so))
            except (subprocess.SubprocessError, OSError,
                    AttributeError) as exc:
                logger.info("native runtime rebuild failed (%s)", exc)
                _lib = None
        except OSError as exc:
            logger.info("native runtime load failed (%s)", exc)
            _lib = None
        if _lib is not None:
            _arm_fault_from_env(_lib)
        return _lib


class NativeReply:
    """A malloc'd native reply buffer exposed as a zero-copy memoryview.

    The bulk drain path hands out record views that alias the native
    buffer directly, so a 100 MB drain is parsed without the two full
    Python-side copies ``ctypes.string_at`` + per-record slicing cost.
    Callers MUST finish consuming every view before ``close()`` (the
    views dangle afterwards); close is idempotent and runs at GC as a
    backstop.
    """

    def __init__(self, lib, ptr: "ctypes.c_void_p", length: int) -> None:
        self._lib = lib
        self._ptr = ptr
        self.view = memoryview(
            (ctypes.c_char * length).from_address(ptr.value)
        ).cast("B") if length else memoryview(b"")

    def close(self) -> None:
        if self._ptr is not None:
            self.view = memoryview(b"")
            self._lib.bf_cp_free(self._ptr)
            self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # backstop only; explicit close is the contract
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


# -- striped multi-connection transport knobs (r7) ---------------------------
#
# The hosted window plane was measured STREAM-bound (PERF.md r6 fold-vs-
# stream probe: a 102 MB drain folds 4-8x faster than its socket take), so
# the transport escapes the single-TCP-stream wall the way Horovod-lineage
# systems do: a pool of BLUEFOG_CP_STREAMS authenticated connections per
# (client, server) pair, large bodies striped across it, and tunable socket
# buffers at both ends. BLUEFOG_CP_STREAMS=1 is the strict fallback: no
# extra connections are ever opened and every byte rides the single
# connection exactly as before.

def _env_streams() -> int:
    try:
        v = int(os.environ.get("BLUEFOG_CP_STREAMS", "4"))
    except ValueError:
        return 4
    return max(1, min(v, 16))


def _env_sockbuf_bytes() -> int:
    # Default 0 = keep the kernel's auto-tuned buffers. Measured on
    # loopback: pinning SO_SNDBUF/SO_RCVBUF disables Linux's buffer
    # auto-grow and LOSES ~10-15 % (PERF.md r7); the knob exists for
    # cross-host DCN paths whose bandwidth-delay product outruns the
    # auto-tuner's limits.
    try:
        mb = float(os.environ.get("BLUEFOG_CP_SOCKBUF_MB", "0"))
    except ValueError:
        mb = 0.0
    return max(0, int(mb * (1 << 20)))


def _env_stripe_min_bytes() -> int:
    try:
        mb = float(os.environ.get("BLUEFOG_CP_STRIPE_MIN_MB", "4"))
    except ValueError:
        mb = 4.0
    return max(1, int(mb * (1 << 20)))


def _blob_len(b) -> int:
    return len(b) if isinstance(b, (bytes, bytearray)) else \
        memoryview(b).nbytes


def _run_parallel(fns):
    """Run thunks on worker threads (caller runs the first); returns their
    results in order, re-raising the first failure. The native calls inside
    release the GIL, so pool connections genuinely transfer concurrently."""
    if len(fns) == 1:
        return [fns[0]()]
    results = [None] * len(fns)
    errors = []

    def run(i):
        try:
            results[i] = fns[i]()
        except BaseException as exc:  # noqa: BLE001 — re-raised below
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(i,), daemon=True,
                                name="bf-cp-stripe")
               for i in range(1, len(fns))]
    for t in threads:
        t.start()
    run(0)
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    return results


class _MultiReply:
    """Owner over several NativeReply buffers (a pooled multi-key drain).

    Exposes no aggregate ``view`` (records alias the per-connection reply
    buffers); the attribute exists empty so callers can treat any drain
    owner uniformly, and ``close()`` invalidates every sub-buffer's views
    exactly like a single :class:`NativeReply`."""

    view = memoryview(b"")

    def __init__(self, owners) -> None:
        self._owners = list(owners)

    def close(self) -> None:
        for o in self._owners:
            o.close()
        self._owners = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


_SRV_STAT_SLOTS = 53  # 32 per-op counts + 21 aggregates (csrc layout)


def _server_stats_dict(buf) -> dict:
    """Decode the 53-slot server counter block (one layout, two transports:
    the in-process bf_cp_server_counters read and the kStats wire op).
    Slots 43-47 are the WAL-replication view: ``repl_status`` is 0 when no
    successor is configured, 1 while the chain commit is live, 2 when the
    shard DEGRADED to unreplicated (`bfrun --status --strict` reports 2 as
    an under-replicated finding). Slots 48-52 are the r20 quorum view:
    ``quorum_state`` is 0 when not in quorum mode (R<=2), 1 while the
    commit quorum holds, 2 while the shard is below quorum (read-only —
    also a --strict finding); ``replica_sources`` counts distinct incoming
    WAL streams, ``repl_targets_live`` live outgoing ones."""
    ops = {name: int(buf[code]) for code, name in _OP_NAMES.items()
           if buf[code]}
    return {
        "ops": ops,
        "live_connections": int(buf[32]),
        "mailbox_records": int(buf[33]),
        "mailbox_bytes": int(buf[34]),
        "locks_held": int(buf[35]),
        "lock_force_releases": int(buf[36]),
        "barrier_withdrawals": int(buf[37]),
        "dedup_replays": int(buf[38]),
        "stale_rejects": int(buf[39]),
        "kv_entries": int(buf[40]),
        "bytes_slots": int(buf[41]),
        "bytes_slot_bytes": int(buf[42]),
        "wal_enqueued": int(buf[43]),
        "wal_acked": int(buf[44]),
        "wal_dropped": int(buf[45]),
        "repl_status": int(buf[46]),
        "repl_applied": int(buf[47]),
        "quorum_acks": int(buf[48]),
        "partition_rejects": int(buf[49]),
        "replica_sources": int(buf[50]),
        "quorum_state": int(buf[51]),
        "repl_targets_live": int(buf[52]),
    }


class ControlPlaneServer:
    """Coordinator side of the scalar control plane (one per job).

    ``secret`` (non-empty) enables the mutual HMAC-SHA256 handshake: every
    connection must prove knowledge of the job's shared secret before any
    op is served — the analog of the reference's HMAC-signed driver/task
    messages (run/horovodrun/common/util/network.py:69-86).
    ``max_mailbox_bytes`` caps each deposit mailbox (0 = unlimited) so
    depositors to a dead owner cannot grow server memory without bound.
    """

    def __init__(self, world: int, port: int = 0, secret: str = "",
                 max_mailbox_bytes: int = 0,
                 sockbuf_bytes: Optional[int] = None,
                 rejoin_pending: bool = False) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        if sockbuf_bytes is None:
            sockbuf_bytes = _env_sockbuf_bytes()
        # rejoin_pending arms the rejoin gate ATOMICALLY with the bind: a
        # restarted shard accepts connections from construction, and not
        # one op may execute against the empty store before the snapshot
        # catch-up lands (set_successor opens the gate).
        self._h = lib.bf_cp_serve_auth3(port, world, secret.encode(),
                                        int(max_mailbox_bytes),
                                        int(sockbuf_bytes),
                                        1 if rejoin_pending else 0)
        if not self._h:
            raise OSError(f"control plane failed to bind port {port}")
        self.port = lib.bf_cp_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.bf_cp_server_stop(self._h)
            self._h = None

    def drop_connections(self) -> None:
        """Fault-injection kill hook: hard-drop every live client
        connection while the server keeps running — what a network
        partition or peer restart looks like from the clients' side.
        Clients with retries enabled reconnect transparently."""
        if self._h:
            self._lib.bf_cp_server_drop_conns(self._h)

    # -- WAL replication / rejoin (r16 durable control plane) --------------

    def set_successor(self, host: str, port: int, nshards: int = 0,
                      idx: int = -1) -> None:
        """Start streaming this server's mailbox/KV/lock mutations to its
        ring successor (chain commit: client replies wait for the
        successor's ack). ``nshards``/``idx`` give the server its ring
        position — the kSnapshot filter and the scoped incarnation GC key
        off it. One-shot per server."""
        if self._lib.bf_cp_server_set_successor(
                self._h, host.encode(), int(port), int(nshards),
                int(idx)) < 0:
            raise RuntimeError("replication successor already configured")

    def set_successors(self, targets, nshards: int = 0,
                       idx: int = -1) -> None:
        """Quorum generalization of :meth:`set_successor` (R >= 3):
        ``targets`` is a list of ``(shard_idx, host, port)`` naming this
        server's R-1 ring successors. One target degenerates to the legacy
        chain (same thread, same wire — R=2 stays byte-identical); two or
        more arm quorum mode: a dedicated WAL stream per target and the
        ack-from-⌈R/2⌉ commit rule. One-shot per server."""
        spec = ";".join(f"{int(i)}:{h}:{int(p)}" for i, h, p in targets)
        r = self._lib.bf_cp_server_set_successors(
            self._h, spec.encode(), int(nshards), int(idx))
        if r == -2:
            raise ValueError(f"malformed successor spec {spec!r}")
        if r < 0:
            raise RuntimeError("replication successors already configured")

    def reset_store(self) -> None:
        """Drop the whole store and re-arm the rejoin gate — the guarded
        in-place self-rejoin a shard runs after surviving on the minority
        side of a healed partition: local state may have diverged from
        the quorum, so it rebuilds from replica snapshots like a
        restarted process would, without losing its listener."""
        self._lib.bf_cp_server_reset_store(self._h)

    def rejoin_done(self) -> None:
        """Reopen the rejoin gate after an in-place self-rejoin
        (:meth:`reset_store` + snapshot catch-up): the successor streams
        of a living process are already armed, so the legacy gate-open
        path (``set_successor``, one-shot) never runs again."""
        self._lib.bf_cp_server_rejoin_done(self._h)

    def set_rejoin_pending(self) -> None:
        """Arm the rejoin gate BEFORE pulling a snapshot: incoming WAL
        records park until :meth:`load_snapshot` (with ``set_fence``)
        clears it, so the resumed stream cannot interleave with the
        not-yet-loaded snapshot contents."""
        self._lib.bf_cp_server_set_rejoin_pending(self._h)

    def load_snapshot(self, blob: bytes, set_fence: bool = True,
                      adopt_wal: bool = False, src_idx: int = -2) -> int:
        """Apply a snapshot blob pulled from a peer shard (rejoin
        catch-up); returns the record count applied. ``set_fence`` adopts
        the blob's WAL fence so the predecessor's resumed stream skips
        records already folded into the snapshot — pass it only for a
        blob served by the ring PREDECESSOR (the fence is a position in
        its WAL). ``adopt_wal`` resumes this server's own WAL numbering
        from the fence the serving shard holds against our stream — pass
        it only for a blob served by the ring SUCCESSOR (our stream's
        receiver); restarting at zero would leave every post-rejoin
        record at or below the receiver's stale fence, silently
        dropped-and-acked. ``src_idx`` names WHICH incoming stream the
        fence belongs to under quorum replication — the serving shard's
        ring index (its stream frames carry rank -(100+src_idx)); the
        default -2 is the legacy chain stream."""
        r = int(self._lib.bf_cp_server_load_snapshot2(
            self._h, blob, len(blob), 1 if set_fence else 0,
            1 if adopt_wal else 0, int(src_idx)))
        if r < 0:
            raise RuntimeError("malformed control-plane snapshot blob")
        return r

    # -- introspection (chaos tests assert incarnation GC left nothing) ----

    def dedup_entries(self) -> int:
        """Server-side op-seq dedup table size (all clients)."""
        return int(self._lib.bf_cp_server_dedup_entries(self._h))

    def mailbox_records_from(self, origin: int) -> int:
        """Queued mailbox records whose deposit tag names ``origin``."""
        return int(self._lib.bf_cp_server_mailbox_from(self._h, origin))

    def incarnation_of(self, rank: int) -> int:
        """Registered incarnation of ``rank`` (-1 = never attached)."""
        return int(self._lib.bf_cp_server_incarnation(self._h, rank))

    _SRV_SLOTS = _SRV_STAT_SLOTS

    def stats(self) -> dict:
        """Server-side telemetry: per-op dispatch counts (zero rows
        suppressed) plus the live aggregates the health plane publishes —
        connection count, queued mailbox depth/bytes, held locks — and the
        fault/recovery event counters (lock force-releases, barrier
        withdrawals, dedup replays, fenced ops)."""
        if not self._h:
            return {}
        buf = (ctypes.c_longlong * self._SRV_SLOTS)()
        if self._lib.bf_cp_server_counters(self._h, buf,
                                           self._SRV_SLOTS) < 0:
            return {}
        return _server_stats_dict(buf)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class ControlPlaneClient:
    """Per-controller client: mutexes, counters, barriers, scalar KV.

    ``streams`` (default ``BLUEFOG_CP_STREAMS``, 4) sizes the striped
    connection pool used for large bulk bodies: the primary connection plus
    ``streams - 1`` extra authenticated connections, opened LAZILY on the
    first striped transfer (scalar-only clients — heartbeat, short-lived
    test actors — never pay for them). Each pool connection runs the full
    mutual HMAC handshake. ``streams=1`` is the strict single-connection
    fallback: no pool, and every code path below degrades to the exact r6
    wire behavior.
    """

    def __init__(self, host: str, port: int, rank: int,
                 secret: str = "", streams: Optional[int] = None,
                 sockbuf_bytes: Optional[int] = None,
                 incarnation: Optional[int] = None) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._conn = (host, port, rank, secret)
        self._sockbuf = _env_sockbuf_bytes() if sockbuf_bytes is None \
            else int(sockbuf_bytes)
        self.streams = _env_streams() if streams is None \
            else max(1, int(streams))
        self._stripe_min = _env_stripe_min_bytes()
        self._extra: list = []       # lazily-opened pool connections
        self._pool_mu = threading.Lock()
        # Incarnation fencing: None keeps the legacy unfenced wire (tests,
        # external actors). A registered client — every pool connection
        # included, re-registered on every transparent reconnect — is
        # rejected server-side once its rank attaches with a newer
        # incarnation, surfacing StaleIncarnationError instead of corrupting
        # shared state as a zombie.
        self.incarnation = None if incarnation is None else int(incarnation)
        self._h = lib.bf_cp_connect_auth2(host.encode(), port, rank,
                                          secret.encode(), self._sockbuf)
        if not self._h:
            raise OSError(
                f"control plane connect to {host}:{port} failed"
                + (" (authentication handshake rejected?)" if secret else ""))
        if self.incarnation is not None:
            self._register(self._h)

    # -- incarnation fencing -----------------------------------------------

    def _stale_message(self) -> str:
        host, port, rank, _ = self._conn
        return (
            f"control plane rank {rank} (incarnation {self.incarnation}) "
            f"was superseded at {host}:{port}: a newer incarnation of this "
            "rank has attached, so this process is a fenced zombie — its "
            "dedup records, queued deposits, and lock holdings were "
            "garbage-collected server-side. Exit instead of retrying; a "
            "legitimate restart must attach with BLUEFOG_INCARNATION "
            "bumped (bfrun --elastic does this automatically).")

    def _register(self, handle) -> None:
        r = self._lib.bf_cp_attach(handle, self.incarnation)
        if r == _STALE:
            raise StaleIncarnationError(self._stale_message())
        if r < 0:
            raise OSError("control plane incarnation registration failed "
                          "(connection lost or not authenticated)")

    def _any_stale(self) -> bool:
        if self.incarnation is None:
            return False
        for h in [self._h] + list(self._extra):
            if h and self._lib.bf_cp_is_stale(h):
                return True
        return False

    def _check_stale(self, r: int) -> None:
        """Raise typed when a -4 status is the fence verdict (the native
        layer latches a per-connection flag, so a genuine -4 scalar value
        read from the KV can never be mistaken for it)."""
        if r == _STALE and self._any_stale():
            raise StaleIncarnationError(self._stale_message())

    def _check_quorum(self, r, what: str) -> None:
        """Raise typed when a -5 status is the server's below-quorum
        rejection. Only MUTATING ops are gated server-side, so -5 from
        one of them is unambiguous (reads — which could legitimately
        return a stored -5 — are never gated and never checked)."""
        if r == _QUORUM_LOST:
            host, port, _rank, _ = self._conn
            raise QuorumLostError(
                f"{what}: shard at {host}:{port} is below its commit "
                "quorum (minority side of a partition, or too many "
                "replicas down) and has degraded to READ-ONLY; the "
                "mutation was NOT applied. Retry after the partition "
                "heals — see docs/fault_tolerance.md, 'Partitions & "
                "quorum'.")

    def _wire_error(self, message: str):
        """Map a failed native call to the right exception: typed fence
        verdict when the connection was superseded, plain OSError else."""
        if self._any_stale():
            raise StaleIncarnationError(self._stale_message())
        raise OSError(message)

    # -- striped connection pool -------------------------------------------

    def _pool_handles(self) -> list:
        """All pool connections (primary first), opening extras on demand.

        A failed extra connect degrades the pool width with a log line
        instead of failing the transfer — the primary connection always
        works (we are talking to a live server)."""
        if self.streams <= 1:
            return [self._h]
        with self._pool_mu:
            while len(self._extra) < self.streams - 1:
                host, port, rank, secret = self._conn
                h = self._lib.bf_cp_connect_auth2(
                    host.encode(), port, rank, secret.encode(),
                    self._sockbuf)
                if not h:
                    logger.warning(
                        "control plane stripe connection %d/%d to %s:%d "
                        "failed; continuing with a narrower pool",
                        len(self._extra) + 2, self.streams, host, port)
                    self.streams = len(self._extra) + 1
                    break
                if self.incarnation is not None:
                    try:
                        self._register(h)
                    except BaseException:
                        self._lib.bf_cp_disconnect(h)
                        raise
                self._extra.append(h)
            return [self._h] + list(self._extra)

    def _pool_array(self):
        handles = self._pool_handles()
        arr = (ctypes.c_void_p * len(handles))(*handles)
        return arr, len(handles)

    def barrier(self, name: str = "default") -> int:
        r = self._lib.bf_cp_barrier(self._h, name.encode())
        self._check_stale(r)
        if r == _DEAD_HOLDER:
            raise _peer_lost(
                f"barrier '{name}' abandoned: a participant never arrived "
                "within BLUEFOG_CP_BARRIER_TIMEOUT (peer crashed or "
                "partitioned)")
        if r < 0:
            raise OSError("control plane barrier failed (connection lost "
                          "or not authenticated)")
        return r

    def lock(self, name: str) -> None:
        r = self._lib.bf_cp_lock(self._h, name.encode())
        self._check_stale(r)
        self._check_quorum(r, f"lock '{name}'")
        if r == _DEAD_HOLDER:
            # the lock was left FREE: after handling the error a fresh
            # acquire succeeds — see docs/fault_tolerance.md
            raise _peer_lost(
                f"lock '{name}': the holder died while we waited (its "
                "connection closed or its BLUEFOG_CP_LOCK_LEASE expired); "
                "the lock was force-released")
        if r < 0:
            raise OSError("control plane lock failed (connection lost "
                          "or not authenticated)")

    def unlock(self, name: str) -> None:
        r = self._lib.bf_cp_unlock(self._h, name.encode())
        self._check_stale(r)
        self._check_quorum(r, f"unlock '{name}'")
        if r == _DEAD_HOLDER:
            raise _peer_lost(
                f"unlock '{name}': this client no longer held the lock — "
                "it was force-released mid-hold (lease expiry or a "
                "connection drop), so the critical section was broken")
        if r < 0:
            raise OSError("control plane unlock failed (connection lost "
                          "or not authenticated)")

    def fetch_add(self, name: str, delta: int = 1) -> int:
        """Atomic fetch-then-add; returns the pre-add value
        (MPI_Fetch_and_op semantics, mpi_controller.cc:1532-1602)."""
        r = self._lib.bf_cp_fetch_add(self._h, name.encode(), delta)
        self._check_stale(r)
        self._check_quorum(r, f"fetch_add '{name}'")
        return r

    def put(self, name: str, value: int) -> None:
        r = self._lib.bf_cp_put(self._h, name.encode(), value)
        self._check_stale(r)
        self._check_quorum(r, f"put '{name}'")
        if r < 0:
            raise OSError("control plane put failed (connection lost "
                          "or not authenticated)")

    def get(self, name: str) -> int:
        r = self._lib.bf_cp_get(self._h, name.encode())
        self._check_stale(r)
        return r

    def put_max(self, name: str, value: int) -> int:
        """Monotone merge: kv[name] = max(kv[name], value); returns the
        post-merge value. The shard router's replication write — replaying
        it (lost reply, failover re-send) can never regress the value."""
        r = self._lib.bf_cp_put_max(self._h, name.encode(), value)
        self._check_stale(r)
        self._check_quorum(r, f"put_max '{name}'")
        return r

    def set_failover(self, host: str, port: int) -> None:
        """Name the ring-successor endpoint this client may permanently
        redirect to when its primary stops answering mid-call. The
        redirect happens INSIDE the native retry loop, so the re-sent
        request keeps its kSeqPre (cid, seq) identity — on a replicated
        shard pair the successor replays the WAL-recorded reply instead
        of double-applying (exactly-once across failover)."""
        self._lib.bf_cp_set_failover(self._h, host.encode(), int(port))

    def set_failover_chain(self, targets) -> None:
        """Multi-hop generalization (quorum replication, R >= 3):
        ``targets`` is a list of ``(host, port)`` ring successors in walk
        order. Reconnect advances past runs of consecutive dead shards,
        sticky on the first entry that answers — the re-sent request
        keeps its (cid, seq) identity, so whichever replica it lands on
        replays the WAL-recorded reply (exactly-once past R-1 deaths)."""
        spec = ",".join(f"{h}:{int(p)}" for h, p in targets)
        self._lib.bf_cp_set_failover2(self._h, spec.encode())

    def set_group(self, group: int) -> None:
        """Bind this client to a partition-injector side, overriding the
        process default (in-process multi-server tests and the soak's
        worker pool place each client on its shard's side)."""
        self._lib.bf_cp_client_set_group(self._h, int(group))

    def failed_over(self) -> bool:
        """True once this client permanently redirected past its primary
        (lock-free read — safe next to a blocked op). Under a failover
        CHAIN the underlying native value is the 1-based chain index the
        client stuck to; bool-ness is preserved."""
        return bool(self._lib.bf_cp_failed_over(self._h))

    def snapshot(self, filter_shards: int = 0, filter_idx: int = 0,
                 rearm: bool = False) -> bytes:
        """Pull a point-in-time state snapshot from the connected server
        (kSnapshot; the shard-rejoin catch-up transport). With
        ``filter_shards`` > 0 only keys whose preferred shard
        (fnv64 % filter_shards) equals ``filter_idx`` are included.
        ``rearm`` declares this caller the serving shard's WAL-stream
        RECEIVER catching up: the server resumes its degraded stream
        from this exact cut. Only the rejoin protocol may set it — a
        pull whose caller does not load the cut into the receiving
        replica would turn the degrade-era drop into a silent mid-stream
        gap (diagnostic pulls must leave it False)."""
        arg = ((int(filter_shards) << 32) | (int(filter_idx) & 0xFFFFFFFF)
               if filter_shards else 0) | ((1 << 62) if rearm else 0)
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_snapshot(self._h, arg, ctypes.byref(out),
                                     ctypes.byref(out_len))
        if r < 0:
            self._wire_error("control plane snapshot pull failed")
        try:
            return ctypes.string_at(out.value, out_len.value) \
                if out_len.value else b""
        finally:
            self._lib.bf_cp_free(out)

    def server_stats(self) -> dict:
        """The server's telemetry counter block, read over the wire (the
        kStats op) — per-shard server views for external actors that do
        not own the :class:`ControlPlaneServer` handle. Empty dict when
        the server predates the op."""
        buf = (ctypes.c_longlong * _SRV_STAT_SLOTS)()
        r = self._lib.bf_cp_remote_stats(self._h, buf, _SRV_STAT_SLOTS)
        if r == _STALE:
            self._check_stale(r)
        if r < _SRV_STAT_SLOTS:
            return {}
        return _server_stats_dict(buf)

    # -- pipelined batches --------------------------------------------------

    def get_many(self, names) -> list:
        """Batched get: n keys, one round-trip's latency."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        out = (ctypes.c_int64 * n)()
        r = self._lib.bf_cp_multi(self._h, OP_CODES["get"], "\n".join(names).encode(),
                                  None, out, n)
        if r < 0:
            self._wire_error("control plane get_many failed")
        return list(out)

    def put_many(self, names, values) -> None:
        """Batched put: n (key, int64) pairs, one round-trip's latency."""
        names = list(names)
        if not names:
            return
        n = len(names)
        args = (ctypes.c_int64 * n)(*[int(v) for v in values])
        out = (ctypes.c_int64 * n)()
        if self._lib.bf_cp_multi(self._h, OP_CODES["put"], "\n".join(names).encode(),
                                 args, out, n) < 0:
            self._wire_error("control plane put_many failed")
        if _QUORUM_LOST in out:
            self._check_quorum(_QUORUM_LOST, "put_many")

    def fetch_add_many(self, names, deltas=None) -> list:
        """Batched fetch_add (default delta 1 each): pre-add values, one
        round-trip's latency — the hosted plane's version-bump hot path."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        args = (ctypes.c_int64 * n)(
            *([1] * n if deltas is None else [int(d) for d in deltas]))
        out = (ctypes.c_int64 * n)()
        if self._lib.bf_cp_multi(self._h, OP_CODES["fetch_add"], "\n".join(names).encode(),
                                 args, out, n) < 0:
            self._wire_error("control plane fetch_add_many failed")
        out = list(out)
        if _QUORUM_LOST in out:
            self._check_quorum(_QUORUM_LOST, "fetch_add_many")
        return out

    # -- bulk bytes: the host tensor transport for one-sided windows --------

    # request framing overhead (header + key) must stay under the server's
    # 1 GiB message ceiling; reject oversized payloads client-side instead of
    # poisoning the connection (the server drops it without replying)
    _MAX_PAYLOAD = (1 << 30) - 4096

    def _check_payload(self, what: str, data: bytes) -> None:
        if len(data) > self._MAX_PAYLOAD:
            raise ValueError(
                f"{what}: payload of {len(data)} bytes exceeds the control "
                f"plane's {self._MAX_PAYLOAD}-byte per-message ceiling; "
                "split the window tensor into smaller leaves")

    def append_bytes(self, name: str, data: bytes) -> int:
        """Append one deposit record to the named server mailbox; returns the
        record count after the append. One-sided: only this client blocks."""
        self._check_payload("append_bytes", data)
        r = self._lib.bf_cp_append_bytes(self._h, name.encode(), data,
                                         len(data))
        self._check_stale(r)
        self._check_quorum(r, f"append_bytes '{name}'")
        if r == -2:
            raise RuntimeError(
                f"control plane mailbox '{name}' is full (server byte cap, "
                "BLUEFOG_CP_MAILBOX_MAX_MB) — the owning controller has not "
                "drained it; it may be dead (check bf.dead_controllers())")
        if r < 0:
            raise OSError("control plane append_bytes failed")
        return int(r)

    def take_bytes(self, name: str) -> list:
        """Atomically drain the named mailbox; returns records in deposit
        order (empty list when nothing is pending)."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_take_bytes(self._h, name.encode(),
                                       ctypes.byref(out),
                                       ctypes.byref(out_len))
        self._check_quorum(r, f"take_bytes '{name}'")
        if r < 0:
            self._wire_error("control plane take_bytes failed")
        try:
            payload = ctypes.string_at(out.value, out_len.value) \
                if out_len.value else b""
        finally:
            self._lib.bf_cp_free(out)
        records = []
        off = 0
        while off < len(payload):
            (rl,) = struct.unpack_from("<I", payload, off)
            off += 4
            records.append(payload[off:off + rl])
            off += rl
        return records

    # op codes for the pipelined bytes batches — single source of truth is
    # runtime/protocol.py (mirroring csrc/bf_runtime.cc enum Op; bfcheck
    # asserts the bijection)
    _OP_APPEND_BYTES = OP_CODES["append_bytes"]
    _OP_TAKE_BYTES = OP_CODES["take_bytes"]
    _OP_PUT_BYTES = OP_CODES["put_bytes"]
    _OP_GET_BYTES = OP_CODES["get_bytes"]
    _OP_APPEND_BYTES_TAGGED = OP_CODES["append_bytes_tagged"]

    def _bytes_multi_out(self, op: int, names, blobs, tags=None,
                         handle=None) -> list:
        """Records may be ``bytes`` or any C-contiguous buffer (numpy
        views): payloads are passed by POINTER to the native scatter-gather
        write, so a 100 MB deposit costs zero Python-side copies.
        ``handle`` selects a pool connection (default: the primary)."""
        names = list(names)
        blobs = list(blobs)  # may be a generator; it's iterated twice below
        if not names:
            return []
        if handle is None:
            handle = self._h
        n = len(names)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_int64 * n)()
        keep = []  # keeps the buffers' owners alive across the call
        for i, b in enumerate(blobs):
            if isinstance(b, (bytes, bytearray)):
                self._check_payload(f"bytes batch '{names[i]}'", b)
                cb = ctypes.c_char_p(bytes(b))
                keep.append(cb)
                ptrs[i] = ctypes.cast(cb, ctypes.c_void_p).value
                lens[i] = len(b)
            else:  # buffer protocol (numpy array/view)
                mv = memoryview(b)
                if not mv.c_contiguous:
                    raise ValueError("bytes batch payloads must be "
                                     "C-contiguous")
                nbytes = mv.nbytes
                if nbytes > self._MAX_PAYLOAD:
                    raise ValueError(
                        f"bytes batch '{names[i]}': payload of {nbytes} "
                        f"bytes exceeds the {self._MAX_PAYLOAD}-byte "
                        "per-message ceiling")
                if mv.readonly:  # rare: fall back to one copy
                    cb = ctypes.c_char_p(mv.tobytes())
                    keep.append(cb)
                    ptrs[i] = ctypes.cast(cb, ctypes.c_void_p).value
                else:
                    flat = mv.cast("B") if nbytes else mv
                    keep.append(flat)
                    ptrs[i] = ctypes.addressof(
                        ctypes.c_char.from_buffer(flat)) if nbytes else 0
                lens[i] = nbytes
        out = (ctypes.c_int64 * n)()
        if tags is None:
            r = self._lib.bf_cp_bytes_multi_outv(
                handle, op, "\n".join(names).encode(), ptrs, lens, out, n)
        else:
            tag_arr = (ctypes.c_int64 * n)(*[int(t) for t in tags])
            r = self._lib.bf_cp_bytes_multi_outv_tagged(
                handle, op, "\n".join(names).encode(), ptrs, lens,
                tag_arr, out, n)
        self._check_quorum(r, "bytes batch")
        if r < 0:
            self._wire_error("control plane bytes batch failed (connection "
                             "lost or not authenticated)")
        out = list(out)
        if _STALE in out:
            self._check_stale(_STALE)
        if _QUORUM_LOST in out:
            # a below-quorum server rejects EVERY entry of a gated batch,
            # so one -5 entry means the whole mutation batch was refused
            self._check_quorum(_QUORUM_LOST, "bytes batch")
        return out

    def _bytes_multi_in_raw(self, op: int, names,
                            handle=None) -> NativeReply:
        """One pipelined bulk-reply batch; the (u64 len | payload)* reply
        stays in the native buffer, exposed as a zero-copy view."""
        n = len(names)
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_bytes_multi_in(
                self._h if handle is None else handle, op,
                "\n".join(names).encode(), n,
                ctypes.byref(out), ctypes.byref(out_len))
        self._check_quorum(r, "bulk drain")  # take_bytes batches are gated
        if r < 0:
            self._wire_error("control plane bytes batch failed (connection "
                             "lost or not authenticated)")
        return NativeReply(self._lib, out, out_len.value)

    def _bytes_multi_in(self, op: int, names) -> list:
        names = list(names)
        if not names:
            return []
        with self._bytes_multi_in_raw(op, names) as reply:
            payload = reply.view
            blobs = []
            off = 0
            for _ in range(len(names)):
                (ln,) = struct.unpack_from("<Q", payload, off)
                off += 8
                blobs.append(bytes(payload[off:off + ln]))
                off += ln
        return blobs

    def append_bytes_many(self, names, blobs) -> list:
        """Pipelined multi-append: n deposit records, one round-trip's
        latency (the hosted window data plane's wire discipline — the
        analog of the reference's chunked MPI_Put stream,
        mpi_controller.cc:932-1034). Returns per-record post-append counts;
        -2 entries mean that mailbox hit the server byte cap."""
        return self._bytes_multi_out(self._OP_APPEND_BYTES, names, blobs)

    def append_bytes_tagged_many(self, names, blobs, tags) -> list:
        """Like :meth:`append_bytes_many`, but each record's int64 tag is
        prefixed to the stored record server-side (kAppendBytesTagged).
        The window drain uses the tag — (sequence id, chunk index, chunk
        count) — to discard orphaned continuation chunks after a
        concurrent clear instead of misparsing them as headers.

        With a striped pool (``streams > 1``) and a large enough batch,
        the deposit HEADER records (tag index 0) go out first on the
        primary connection, then the payload chunk records stripe
        round-robin across the whole pool and transfer concurrently. The
        header-before-chunks server arrival order is what lets the drain
        treat a header-less chunk as a definitively orphaned deposit (a
        concurrent clear ate its prefix) rather than an early arrival;
        chunk-vs-chunk order is free because chunk tags carry their index
        and the drain places them by offset."""
        names, blobs, tags = list(names), list(blobs), list(tags)
        if (self.streams > 1 and len(names) > 1
                and sum(_blob_len(b) for b in blobs) >= self._stripe_min):
            return self._striped_append_tagged(names, blobs, tags)
        return self._bytes_multi_out(self._OP_APPEND_BYTES_TAGGED, names,
                                     blobs, tags=tags)

    def _striped_append_tagged(self, names, blobs, tags) -> list:
        op = self._OP_APPEND_BYTES_TAGGED
        hdr = [i for i, t in enumerate(tags) if (int(t) & 0xFFFFFF) == 0]
        chunk = [i for i, t in enumerate(tags) if (int(t) & 0xFFFFFF) != 0]
        out = [0] * len(names)

        def scatter(idxs, replies):
            for i, r in zip(idxs, replies):
                out[i] = r

        if hdr:  # phase 1: all headers, appended before any chunk streams
            scatter(hdr, self._bytes_multi_out(
                op, [names[i] for i in hdr], [blobs[i] for i in hdr],
                tags=[tags[i] for i in hdr]))
        if chunk:  # phase 2: chunks round-robin over the pool, concurrent
            pool = self._pool_handles()
            ngroups = min(len(pool), len(chunk))
            groups = [chunk[g::ngroups] for g in range(ngroups)]
            replies = _run_parallel([
                lambda h=pool[g], idxs=groups[g]: self._bytes_multi_out(
                    op, [names[i] for i in idxs], [blobs[i] for i in idxs],
                    tags=[tags[i] for i in idxs], handle=h)
                for g in range(ngroups)])
            for g in range(ngroups):
                scatter(groups[g], replies[g])
        return out

    def put_bytes_many(self, names, blobs) -> None:
        """Pipelined multi-put of bytes slots (batched self publishes).

        Bodies at or above the stripe threshold transfer as concurrent
        byte-range stripes over the connection pool (each body saturates
        the pool in turn); smaller ones ride one pipelined batch on the
        primary connection, exactly as before."""
        names, blobs = list(names), list(blobs)
        small_idx, large_idx = [], []
        for i, b in enumerate(blobs):
            (large_idx if self.streams > 1
             and _blob_len(b) >= self._stripe_min else small_idx).append(i)
        for i in large_idx:
            self._put_bytes_striped(names[i], blobs[i])
        if small_idx:
            for r in self._bytes_multi_out(
                    self._OP_PUT_BYTES, [names[i] for i in small_idx],
                    [blobs[i] for i in small_idx]):
                if r < 0:
                    self._check_stale(r)
                    self._check_quorum(r, "put_bytes_many")
                    raise OSError("control plane put_bytes_many failed")

    def _put_bytes_striped(self, name: str, blob) -> None:
        # zero-copy pointer extraction, same discipline as _bytes_multi_out
        if isinstance(blob, (bytes, bytearray)):
            keep = ctypes.c_char_p(bytes(blob))
            ptr = ctypes.cast(keep, ctypes.c_void_p)
            nbytes = len(blob)
        else:
            mv = memoryview(blob).cast("B")
            if mv.readonly:
                keep = ctypes.c_char_p(mv.tobytes())
                ptr = ctypes.cast(keep, ctypes.c_void_p)
            else:
                keep = mv
                ptr = ctypes.c_void_p(ctypes.addressof(
                    ctypes.c_char.from_buffer(mv)) if mv.nbytes else 0)
            nbytes = mv.nbytes
        if nbytes > self._MAX_PAYLOAD:
            raise ValueError(
                f"put_bytes: payload of {nbytes} bytes exceeds the "
                f"{self._MAX_PAYLOAD}-byte per-message ceiling")
        arr, nh = self._pool_array()
        r = self._lib.bf_cp_put_bytes_striped(arr, nh, name.encode(),
                                              ptr, nbytes)
        del keep
        self._check_quorum(r, f"striped put_bytes '{name}'")
        if r < 0:
            self._wire_error("control plane striped put_bytes failed "
                             "(connection lost or not authenticated)")

    @staticmethod
    def _parse_take_reply(payload) -> list:
        records = []
        off = 0
        while off < len(payload):
            (rl,) = struct.unpack_from("<I", payload, off)
            off += 4
            records.append(payload[off:off + rl])
            off += rl
        return records

    def take_bytes_many(self, names) -> list:
        """Pipelined multi-drain: per-key record lists, one round-trip's
        latency. Each key's drain is individually atomic and bounded by the
        server's per-reply cap, exactly like take_bytes."""
        out = []
        for payload in self._bytes_multi_in(self._OP_TAKE_BYTES, names):
            out.append(self._parse_take_reply(payload))
        return out

    @staticmethod
    def _parse_multi_in(payload, n) -> list:
        out = []
        off = 0
        for _ in range(n):
            (ln,) = struct.unpack_from("<Q", payload, off)
            off += 8
            out.append(ControlPlaneClient._parse_take_reply(
                payload[off:off + ln]))
            off += ln
        return out

    def take_bytes_many_views(self, names, pooled: bool = True):
        """Zero-copy multi-drain: ``(per-key record lists, owner)``.

        Records are memoryview slices aliasing the native reply buffers —
        a 100+ MB drain is parsed without the full-payload copies
        :meth:`take_bytes_many` pays (``string_at`` + per-record bytes
        slices). The caller must finish consuming every record view and
        then ``owner.close()`` (use as a context manager); this is the
        hosted window drain's hot path.

        With a striped pool (and ``pooled=True``) the keys split
        round-robin across the connections and every sub-drain streams
        concurrently — the win_update per-in-neighbor sweep issues on the
        whole pool at once instead of serializing source after source.
        Each key is still drained by exactly one connection per sweep, so
        per-key record order is preserved. ``pooled=False`` keeps the
        sweep on one pipelined connection — callers pass it when the
        expected haul is small (a pooled sweep's extra round-trips and
        threads cost more than they parallelize there; the window drain
        adapts per round on the previous round's byte count)."""
        names = list(names)
        if not names:
            return [], NativeReply(self._lib, ctypes.c_void_p(), 0)
        pool = self._pool_handles() if pooled and self.streams > 1 \
            and len(names) > 1 else [self._h]
        if len(pool) == 1:
            owner = self._bytes_multi_in_raw(self._OP_TAKE_BYTES, names)
            return self._parse_multi_in(owner.view, len(names)), owner
        ngroups = min(len(pool), len(names))
        groups = [list(range(g, len(names), ngroups))
                  for g in range(ngroups)]
        owners = _run_parallel([
            lambda h=pool[g], idxs=groups[g]: self._bytes_multi_in_raw(
                self._OP_TAKE_BYTES, [names[i] for i in idxs], handle=h)
            for g in range(ngroups)])
        out = [None] * len(names)
        for g in range(ngroups):
            for i, recs in zip(groups[g], self._parse_multi_in(
                    owners[g].view, len(groups[g]))):
                out[i] = recs
        return out, _MultiReply(owners)

    def get_bytes_many(self, names) -> list:
        """Pipelined multi-read of bytes slots (batched win_get pulls)."""
        return self._bytes_multi_in(self._OP_GET_BYTES, names)

    def box_bytes_many(self, names) -> list:
        """Pipelined read of pending payload bytes per mailbox — the
        origin-side pre-check that keeps a multi-record deposit from being
        torn by the server byte cap (safe: each deposit mailbox has exactly
        one writer, and the owner's drain only shrinks it)."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        out = (ctypes.c_int64 * n)()
        if self._lib.bf_cp_multi(self._h, OP_CODES["box_bytes"], "\n".join(names).encode(),
                                 None, out, n) < 0:
            self._wire_error("control plane box_bytes_many failed")
        return list(out)

    def put_bytes(self, name: str, data: bytes) -> None:
        """Overwrite the named bytes slot (the 'exposed window' copy).
        Large bodies stripe across the connection pool (readers only ever
        observe complete values: stripes assemble server-side and swap in
        atomically)."""
        if self.streams > 1 and _blob_len(data) >= self._stripe_min:
            return self._put_bytes_striped(name, data)
        self._check_payload("put_bytes", data)
        r = self._lib.bf_cp_put_bytes(self._h, name.encode(), data,
                                      len(data))
        self._check_quorum(r, f"put_bytes '{name}'")
        if r < 0:
            self._wire_error("control plane put_bytes failed")

    def bytes_len(self, name: str) -> int:
        """Current byte length of the named bytes slot (0 when never put)."""
        r = self._lib.bf_cp_bytes_len(self._h, name.encode())
        if r < 0:
            self._wire_error("control plane bytes_len failed")
        return int(r)

    def get_bytes_view(self, name: str):
        """Read a bytes slot as ``(memoryview, owner)`` with zero Python
        copies; large bodies are fetched as concurrent byte-range stripes
        over the pool. The caller consumes the view, then ``owner.close()``
        (the win_get hot path)."""
        if self.streams > 1:
            ln = self.bytes_len(name)
            if ln >= self._stripe_min:
                arr, nh = self._pool_array()
                out = ctypes.c_void_p()
                out_len = ctypes.c_int64()
                if self._lib.bf_cp_get_bytes_striped(
                        arr, nh, name.encode(), ctypes.byref(out),
                        ctypes.byref(out_len)) < 0:
                    self._wire_error("control plane striped get_bytes "
                                     "failed (connection lost or value "
                                     "churning)")
                owner = NativeReply(self._lib, out, out_len.value)
                return owner.view, owner
        owner = self._bytes_multi_in_raw(self._OP_GET_BYTES, [name])
        (ln,) = struct.unpack_from("<Q", owner.view, 0)
        return owner.view[8:8 + ln], owner

    def get_bytes(self, name: str) -> bytes:
        """Read the named bytes slot (empty when never put)."""
        if self.streams > 1 and \
                self.bytes_len(name) >= self._stripe_min:
            view, owner = self.get_bytes_view(name)
            try:
                return bytes(view)
            finally:
                owner.close()
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_get_bytes(self._h, name.encode(),
                                      ctypes.byref(out),
                                      ctypes.byref(out_len))
        if r < 0:
            self._wire_error("control plane get_bytes failed")
        try:
            return ctypes.string_at(out.value, out_len.value) \
                if out_len.value else b""
        finally:
            self._lib.bf_cp_free(out)

    def close(self) -> None:
        with self._pool_mu:
            for h in self._extra:
                self._lib.bf_cp_disconnect(h)
            self._extra = []
        if self._h:
            self._lib.bf_cp_disconnect(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
