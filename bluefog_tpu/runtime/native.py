"""ctypes bindings for the native host-runtime extension (csrc/bf_runtime.cc).

The native library provides the C++ subsystems of the rebuild (the analog of
the reference's C++ core, cf. SURVEY.md §2.1): the timeline writer
(timeline.cc) and the control-plane scalar protocols (distributed mutex /
fetch-and-op / barrier — mpi_controller.cc:1532-1602's window mutexes and
version counters, served over TCP for multi-controller deployments).

Built lazily with g++ on first use; every consumer must degrade gracefully
when the toolchain is unavailable (``load()`` returns None).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import Optional

from .logging import logger

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_SO = os.path.join(_CSRC, "build", "libbf_runtime.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.bf_timeline_open.restype = ctypes.c_void_p
    lib.bf_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.bf_timeline_event.restype = None
    lib.bf_timeline_event.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char,
        ctypes.c_int64, ctypes.c_int,
    ]
    lib.bf_timeline_close.restype = None
    lib.bf_timeline_close.argtypes = [ctypes.c_void_p]

    lib.bf_cp_serve.restype = ctypes.c_void_p
    lib.bf_cp_serve.argtypes = [ctypes.c_int, ctypes.c_int]
    lib.bf_cp_serve_auth.restype = ctypes.c_void_p
    lib.bf_cp_serve_auth.argtypes = [ctypes.c_int, ctypes.c_int,
                                     ctypes.c_char_p, ctypes.c_int64]
    lib.bf_cp_server_port.restype = ctypes.c_int
    lib.bf_cp_server_port.argtypes = [ctypes.c_void_p]
    lib.bf_cp_server_stop.restype = None
    lib.bf_cp_server_stop.argtypes = [ctypes.c_void_p]
    lib.bf_cp_connect.restype = ctypes.c_void_p
    lib.bf_cp_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.bf_cp_connect_auth.restype = ctypes.c_void_p
    lib.bf_cp_connect_auth.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int, ctypes.c_char_p]
    for fname in ("bf_cp_barrier", "bf_cp_lock", "bf_cp_unlock", "bf_cp_get"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    for fname in ("bf_cp_fetch_add", "bf_cp_put"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    for fname in ("bf_cp_append_bytes", "bf_cp_put_bytes"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                       ctypes.c_int64]
    for fname in ("bf_cp_take_bytes", "bf_cp_get_bytes"):
        fn = getattr(lib, fname)
        fn.restype = ctypes.c_int64
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                       ctypes.POINTER(ctypes.c_void_p),
                       ctypes.POINTER(ctypes.c_int64)]
    lib.bf_cp_free.restype = None
    lib.bf_cp_free.argtypes = [ctypes.c_void_p]
    lib.bf_cp_multi.restype = ctypes.c_int64
    lib.bf_cp_multi.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_outv.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_outv.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_outv_tagged.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_outv_tagged.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
    ]
    lib.bf_cp_bytes_multi_in.restype = ctypes.c_int64
    lib.bf_cp_bytes_multi_in.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int64),
    ]
    lib.bf_cp_disconnect.restype = None
    lib.bf_cp_disconnect.argtypes = [ctypes.c_void_p]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO):
            script = os.path.join(_CSRC, "build.sh")
            if not os.path.exists(script):
                return None
            try:
                subprocess.run(["sh", script], check=True,
                               capture_output=True, timeout=120)
            except (subprocess.SubprocessError, OSError) as exc:
                logger.info("native runtime build failed (%s); "
                            "using pure-Python fallbacks", exc)
                return None
        try:
            _lib = _configure(ctypes.CDLL(_SO))
        except AttributeError:
            # A stale cached build predates a symbol _configure now needs
            # (the .so is gitignored; load() only builds when it's missing).
            # Rebuild once from the current sources and retry.
            logger.info("native runtime is stale (missing symbol); "
                        "rebuilding from csrc")
            try:
                subprocess.run(["sh", os.path.join(_CSRC, "build.sh")],
                               check=True, capture_output=True, timeout=120)
                _lib = _configure(ctypes.CDLL(_SO))
            except (subprocess.SubprocessError, OSError,
                    AttributeError) as exc:
                logger.info("native runtime rebuild failed (%s)", exc)
                _lib = None
        except OSError as exc:
            logger.info("native runtime load failed (%s)", exc)
            _lib = None
        return _lib


class NativeReply:
    """A malloc'd native reply buffer exposed as a zero-copy memoryview.

    The bulk drain path hands out record views that alias the native
    buffer directly, so a 100 MB drain is parsed without the two full
    Python-side copies ``ctypes.string_at`` + per-record slicing cost.
    Callers MUST finish consuming every view before ``close()`` (the
    views dangle afterwards); close is idempotent and runs at GC as a
    backstop.
    """

    def __init__(self, lib, ptr: "ctypes.c_void_p", length: int) -> None:
        self._lib = lib
        self._ptr = ptr
        self.view = memoryview(
            (ctypes.c_char * length).from_address(ptr.value)
        ).cast("B") if length else memoryview(b"")

    def close(self) -> None:
        if self._ptr is not None:
            self.view = memoryview(b"")
            self._lib.bf_cp_free(self._ptr)
            self._ptr = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def __del__(self):  # backstop only; explicit close is the contract
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class ControlPlaneServer:
    """Coordinator side of the scalar control plane (one per job).

    ``secret`` (non-empty) enables the mutual HMAC-SHA256 handshake: every
    connection must prove knowledge of the job's shared secret before any
    op is served — the analog of the reference's HMAC-signed driver/task
    messages (run/horovodrun/common/util/network.py:69-86).
    ``max_mailbox_bytes`` caps each deposit mailbox (0 = unlimited) so
    depositors to a dead owner cannot grow server memory without bound.
    """

    def __init__(self, world: int, port: int = 0, secret: str = "",
                 max_mailbox_bytes: int = 0) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.bf_cp_serve_auth(port, world, secret.encode(),
                                       int(max_mailbox_bytes))
        if not self._h:
            raise OSError(f"control plane failed to bind port {port}")
        self.port = lib.bf_cp_server_port(self._h)

    def stop(self) -> None:
        if self._h:
            self._lib.bf_cp_server_stop(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class ControlPlaneClient:
    """Per-controller client: mutexes, counters, barriers, scalar KV."""

    def __init__(self, host: str, port: int, rank: int,
                 secret: str = "") -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.bf_cp_connect_auth(host.encode(), port, rank,
                                         secret.encode())
        if not self._h:
            raise OSError(
                f"control plane connect to {host}:{port} failed"
                + (" (authentication handshake rejected?)" if secret else ""))

    def barrier(self, name: str = "default") -> int:
        r = self._lib.bf_cp_barrier(self._h, name.encode())
        if r < 0:
            raise OSError("control plane barrier failed (connection lost "
                          "or not authenticated)")
        return r

    def lock(self, name: str) -> None:
        if self._lib.bf_cp_lock(self._h, name.encode()) < 0:
            raise OSError("control plane lock failed (connection lost "
                          "or not authenticated)")

    def unlock(self, name: str) -> None:
        if self._lib.bf_cp_unlock(self._h, name.encode()) < 0:
            raise OSError("control plane unlock failed (connection lost "
                          "or not authenticated)")

    def fetch_add(self, name: str, delta: int = 1) -> int:
        """Atomic fetch-then-add; returns the pre-add value
        (MPI_Fetch_and_op semantics, mpi_controller.cc:1532-1602)."""
        return self._lib.bf_cp_fetch_add(self._h, name.encode(), delta)

    def put(self, name: str, value: int) -> None:
        if self._lib.bf_cp_put(self._h, name.encode(), value) < 0:
            raise OSError("control plane put failed (connection lost "
                          "or not authenticated)")

    def get(self, name: str) -> int:
        return self._lib.bf_cp_get(self._h, name.encode())

    # -- pipelined batches --------------------------------------------------

    def get_many(self, names) -> list:
        """Batched get: n keys, one round-trip's latency."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        out = (ctypes.c_int64 * n)()
        r = self._lib.bf_cp_multi(self._h, 6, "\n".join(names).encode(),
                                  None, out, n)
        if r < 0:
            raise OSError("control plane get_many failed")
        return list(out)

    def put_many(self, names, values) -> None:
        """Batched put: n (key, int64) pairs, one round-trip's latency."""
        names = list(names)
        if not names:
            return
        n = len(names)
        args = (ctypes.c_int64 * n)(*[int(v) for v in values])
        if self._lib.bf_cp_multi(self._h, 5, "\n".join(names).encode(),
                                 args, None, n) < 0:
            raise OSError("control plane put_many failed")

    def fetch_add_many(self, names, deltas=None) -> list:
        """Batched fetch_add (default delta 1 each): pre-add values, one
        round-trip's latency — the hosted plane's version-bump hot path."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        args = (ctypes.c_int64 * n)(
            *([1] * n if deltas is None else [int(d) for d in deltas]))
        out = (ctypes.c_int64 * n)()
        if self._lib.bf_cp_multi(self._h, 4, "\n".join(names).encode(),
                                 args, out, n) < 0:
            raise OSError("control plane fetch_add_many failed")
        return list(out)

    # -- bulk bytes: the host tensor transport for one-sided windows --------

    # request framing overhead (header + key) must stay under the server's
    # 1 GiB message ceiling; reject oversized payloads client-side instead of
    # poisoning the connection (the server drops it without replying)
    _MAX_PAYLOAD = (1 << 30) - 4096

    def _check_payload(self, what: str, data: bytes) -> None:
        if len(data) > self._MAX_PAYLOAD:
            raise ValueError(
                f"{what}: payload of {len(data)} bytes exceeds the control "
                f"plane's {self._MAX_PAYLOAD}-byte per-message ceiling; "
                "split the window tensor into smaller leaves")

    def append_bytes(self, name: str, data: bytes) -> int:
        """Append one deposit record to the named server mailbox; returns the
        record count after the append. One-sided: only this client blocks."""
        self._check_payload("append_bytes", data)
        r = self._lib.bf_cp_append_bytes(self._h, name.encode(), data,
                                         len(data))
        if r == -2:
            raise RuntimeError(
                f"control plane mailbox '{name}' is full (server byte cap, "
                "BLUEFOG_CP_MAILBOX_MAX_MB) — the owning controller has not "
                "drained it; it may be dead (check bf.dead_controllers())")
        if r < 0:
            raise OSError("control plane append_bytes failed")
        return int(r)

    def take_bytes(self, name: str) -> list:
        """Atomically drain the named mailbox; returns records in deposit
        order (empty list when nothing is pending)."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_take_bytes(self._h, name.encode(),
                                       ctypes.byref(out),
                                       ctypes.byref(out_len))
        if r < 0:
            raise OSError("control plane take_bytes failed")
        try:
            payload = ctypes.string_at(out.value, out_len.value) \
                if out_len.value else b""
        finally:
            self._lib.bf_cp_free(out)
        records = []
        off = 0
        while off < len(payload):
            (rl,) = struct.unpack_from("<I", payload, off)
            off += 4
            records.append(payload[off:off + rl])
            off += rl
        return records

    # op codes for the pipelined bytes batches (csrc/bf_runtime.cc enum Op)
    _OP_APPEND_BYTES = 8
    _OP_TAKE_BYTES = 9
    _OP_PUT_BYTES = 10
    _OP_GET_BYTES = 11
    _OP_APPEND_BYTES_TAGGED = 13

    def _bytes_multi_out(self, op: int, names, blobs, tags=None) -> list:
        """Records may be ``bytes`` or any C-contiguous buffer (numpy
        views): payloads are passed by POINTER to the native scatter-gather
        write, so a 100 MB deposit costs zero Python-side copies."""
        names = list(names)
        blobs = list(blobs)  # may be a generator; it's iterated twice below
        if not names:
            return []
        n = len(names)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_int64 * n)()
        keep = []  # keeps the buffers' owners alive across the call
        for i, b in enumerate(blobs):
            if isinstance(b, (bytes, bytearray)):
                self._check_payload(f"bytes batch '{names[i]}'", b)
                cb = ctypes.c_char_p(bytes(b))
                keep.append(cb)
                ptrs[i] = ctypes.cast(cb, ctypes.c_void_p).value
                lens[i] = len(b)
            else:  # buffer protocol (numpy array/view)
                mv = memoryview(b)
                if not mv.c_contiguous:
                    raise ValueError("bytes batch payloads must be "
                                     "C-contiguous")
                nbytes = mv.nbytes
                if nbytes > self._MAX_PAYLOAD:
                    raise ValueError(
                        f"bytes batch '{names[i]}': payload of {nbytes} "
                        f"bytes exceeds the {self._MAX_PAYLOAD}-byte "
                        "per-message ceiling")
                if mv.readonly:  # rare: fall back to one copy
                    cb = ctypes.c_char_p(mv.tobytes())
                    keep.append(cb)
                    ptrs[i] = ctypes.cast(cb, ctypes.c_void_p).value
                else:
                    flat = mv.cast("B") if nbytes else mv
                    keep.append(flat)
                    ptrs[i] = ctypes.addressof(
                        ctypes.c_char.from_buffer(flat)) if nbytes else 0
                lens[i] = nbytes
        out = (ctypes.c_int64 * n)()
        if tags is None:
            r = self._lib.bf_cp_bytes_multi_outv(
                self._h, op, "\n".join(names).encode(), ptrs, lens, out, n)
        else:
            tag_arr = (ctypes.c_int64 * n)(*[int(t) for t in tags])
            r = self._lib.bf_cp_bytes_multi_outv_tagged(
                self._h, op, "\n".join(names).encode(), ptrs, lens,
                tag_arr, out, n)
        if r < 0:
            raise OSError("control plane bytes batch failed (connection "
                          "lost or not authenticated)")
        return list(out)

    def _bytes_multi_in_raw(self, op: int, names) -> NativeReply:
        """One pipelined bulk-reply batch; the (u64 len | payload)* reply
        stays in the native buffer, exposed as a zero-copy view."""
        n = len(names)
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        if self._lib.bf_cp_bytes_multi_in(
                self._h, op, "\n".join(names).encode(), n,
                ctypes.byref(out), ctypes.byref(out_len)) < 0:
            raise OSError("control plane bytes batch failed (connection "
                          "lost or not authenticated)")
        return NativeReply(self._lib, out, out_len.value)

    def _bytes_multi_in(self, op: int, names) -> list:
        names = list(names)
        if not names:
            return []
        with self._bytes_multi_in_raw(op, names) as reply:
            payload = reply.view
            blobs = []
            off = 0
            for _ in range(len(names)):
                (ln,) = struct.unpack_from("<Q", payload, off)
                off += 8
                blobs.append(bytes(payload[off:off + ln]))
                off += ln
        return blobs

    def append_bytes_many(self, names, blobs) -> list:
        """Pipelined multi-append: n deposit records, one round-trip's
        latency (the hosted window data plane's wire discipline — the
        analog of the reference's chunked MPI_Put stream,
        mpi_controller.cc:932-1034). Returns per-record post-append counts;
        -2 entries mean that mailbox hit the server byte cap."""
        return self._bytes_multi_out(self._OP_APPEND_BYTES, names, blobs)

    def append_bytes_tagged_many(self, names, blobs, tags) -> list:
        """Like :meth:`append_bytes_many`, but each record's int64 tag is
        prefixed to the stored record server-side (kAppendBytesTagged).
        The window drain uses the tag — (sequence id, chunk index, chunk
        count) — to discard orphaned continuation chunks after a
        concurrent clear instead of misparsing them as headers."""
        return self._bytes_multi_out(self._OP_APPEND_BYTES_TAGGED, names,
                                     blobs, tags=tags)

    def put_bytes_many(self, names, blobs) -> None:
        """Pipelined multi-put of bytes slots (batched self publishes)."""
        for r in self._bytes_multi_out(self._OP_PUT_BYTES, names, blobs):
            if r < 0:
                raise OSError("control plane put_bytes_many failed")

    @staticmethod
    def _parse_take_reply(payload) -> list:
        records = []
        off = 0
        while off < len(payload):
            (rl,) = struct.unpack_from("<I", payload, off)
            off += 4
            records.append(payload[off:off + rl])
            off += rl
        return records

    def take_bytes_many(self, names) -> list:
        """Pipelined multi-drain: per-key record lists, one round-trip's
        latency. Each key's drain is individually atomic and bounded by the
        server's per-reply cap, exactly like take_bytes."""
        out = []
        for payload in self._bytes_multi_in(self._OP_TAKE_BYTES, names):
            out.append(self._parse_take_reply(payload))
        return out

    def take_bytes_many_views(self, names):
        """Zero-copy multi-drain: ``(per-key record lists, owner)``.

        Records are memoryview slices aliasing ONE native reply buffer —
        a 100+ MB drain is parsed without the full-payload copies
        :meth:`take_bytes_many` pays (``string_at`` + per-record bytes
        slices). The caller must finish consuming every record view and
        then ``owner.close()`` (use as a context manager); this is the
        hosted window drain's hot path."""
        names = list(names)
        if not names:
            return [], NativeReply(self._lib, ctypes.c_void_p(), 0)
        owner = self._bytes_multi_in_raw(self._OP_TAKE_BYTES, names)
        payload = owner.view
        out = []
        off = 0
        for _ in range(len(names)):
            (ln,) = struct.unpack_from("<Q", payload, off)
            off += 8
            out.append(self._parse_take_reply(payload[off:off + ln]))
            off += ln
        return out, owner

    def get_bytes_many(self, names) -> list:
        """Pipelined multi-read of bytes slots (batched win_get pulls)."""
        return self._bytes_multi_in(self._OP_GET_BYTES, names)

    def box_bytes_many(self, names) -> list:
        """Pipelined read of pending payload bytes per mailbox — the
        origin-side pre-check that keeps a multi-record deposit from being
        torn by the server byte cap (safe: each deposit mailbox has exactly
        one writer, and the owner's drain only shrinks it)."""
        names = list(names)
        if not names:
            return []
        n = len(names)
        out = (ctypes.c_int64 * n)()
        if self._lib.bf_cp_multi(self._h, 12, "\n".join(names).encode(),
                                 None, out, n) < 0:
            raise OSError("control plane box_bytes_many failed")
        return list(out)

    def put_bytes(self, name: str, data: bytes) -> None:
        """Overwrite the named bytes slot (the 'exposed window' copy)."""
        self._check_payload("put_bytes", data)
        if self._lib.bf_cp_put_bytes(self._h, name.encode(), data,
                                     len(data)) < 0:
            raise OSError("control plane put_bytes failed")

    def get_bytes(self, name: str) -> bytes:
        """Read the named bytes slot (empty when never put)."""
        out = ctypes.c_void_p()
        out_len = ctypes.c_int64()
        r = self._lib.bf_cp_get_bytes(self._h, name.encode(),
                                      ctypes.byref(out),
                                      ctypes.byref(out_len))
        if r < 0:
            raise OSError("control plane get_bytes failed")
        try:
            return ctypes.string_at(out.value, out_len.value) \
                if out_len.value else b""
        finally:
            self._lib.bf_cp_free(out)

    def close(self) -> None:
        if self._h:
            self._lib.bf_cp_disconnect(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
