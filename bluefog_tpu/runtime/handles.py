"""Nonblocking-op handles: poll / wait / synchronize.

Analog of BlueFog's ``HandleManager`` + per-op handles with
``poll/synchronize/wait`` (reference: torch/handle_manager.{h,cc},
torch/mpi_ops.py:823-869). JAX dispatch is already asynchronous — a collective
returns immediately with futures backing the output arrays — so a handle here
wraps the dispatched output pytree; ``synchronize`` blocks until the device
work is done and the stall watchdog tracks handles that never complete
(reference: CheckForStalledTensors, operations.cc:387-432).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax

_counter = itertools.count(1)
_lock = threading.Lock()
_handle_map: Dict[int, Tuple[str, float, Any]] = {}  # handle -> (name, t0, outputs)

# Fire-and-forget callers (win_put in a long gossip loop) never synchronize
# their handles; bound the table so completed entries don't pin device arrays
# for the life of the process. Oldest *finished* entries are evicted first.
_MAX_RETAINED = 4096


def _evict_completed_locked() -> None:
    if len(_handle_map) <= _MAX_RETAINED:
        return
    for handle in sorted(_handle_map):
        _, _, outputs = _handle_map[handle]
        leaves = jax.tree_util.tree_leaves(outputs)
        if all(l.is_ready() if hasattr(l, "is_ready") else True for l in leaves):
            del _handle_map[handle]
            if len(_handle_map) <= _MAX_RETAINED:
                return


def allocate(name: str, outputs: Any) -> int:
    """Register dispatched outputs; returns an integer handle."""
    handle = next(_counter)
    with _lock:
        _evict_completed_locked()
        _handle_map[handle] = (name, time.monotonic(), outputs)
    return handle


def clear() -> None:
    """Drop all handles (called by shutdown)."""
    with _lock:
        _handle_map.clear()


def poll(handle: int) -> bool:
    """True when the op backing ``handle`` has finished executing."""
    with _lock:
        entry = _handle_map.get(handle)
    if entry is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    _, _, outputs = entry
    leaves = jax.tree_util.tree_leaves(outputs)
    return all(
        leaf.is_ready() if hasattr(leaf, "is_ready") else True for leaf in leaves
    )


def synchronize(handle: int, timeout: Optional[float] = None) -> Any:
    """Block until the op completes and return its output pytree.

    ``timeout`` (seconds; default from ``BLUEFOG_SYNC_TIMEOUT``, unset =
    wait forever) bounds the wait: on expiry the handle stays valid for a
    retry and a RuntimeError is raised carrying the failure detector's
    diagnosis — in a multi-controller job a dead peer (heartbeat silence)
    is named instead of the op hanging forever on the corpse. The reference
    only *warns* about stalls (CheckForStalledTensors, operations.cc:
    387-432); this makes the stall a first-class, attributable failure.
    """
    if timeout is None:
        env = os.environ.get("BLUEFOG_SYNC_TIMEOUT")
        timeout = float(env) if env else None
    # atomic pop: concurrent synchronize calls on one handle keep the
    # consume-once contract (exactly one wins; the other gets ValueError)
    with _lock:
        entry = _handle_map.pop(handle, None)
    if entry is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    name, t0, outputs = entry
    if timeout is None:
        return jax.block_until_ready(outputs)

    deadline = time.monotonic() + timeout
    leaves = jax.tree_util.tree_leaves(outputs)

    def ready() -> bool:
        return all(leaf.is_ready() if hasattr(leaf, "is_ready") else True
                   for leaf in leaves)

    while True:
        # readiness check runs at least once and once more AFTER the
        # deadline: an op finishing during the final sleep (or timeout=0,
        # the "poll once" form) returns instead of raising spuriously
        if ready():
            return jax.block_until_ready(outputs)
        if time.monotonic() >= deadline:
            break
        time.sleep(0.01)

    # timed out: re-register under the same id so the caller can retry
    with _lock:
        _handle_map[handle] = entry

    from .heartbeat import dead_controllers
    dead = dead_controllers()
    diagnosis = (
        f"controller process(es) {sorted(dead)} are DEAD (heartbeat "
        "silence) — the collective can never complete; abandon the handle "
        "and tear down" if dead else
        "no peer is reported dead — the op may be slow, the job "
        "overloaded, or a peer controller may not have dispatched the "
        "same op (see enable_topo_check / the stall watchdog)")
    raise RuntimeError(
        f"synchronize('{name}', handle {handle}) exceeded the "
        f"{timeout:.1f}s deadline after {time.monotonic() - t0:.1f}s in "
        f"flight: {diagnosis}")


def wait(handle: int, timeout: Optional[float] = None) -> Any:
    """Alias of synchronize (reference: mpi_ops.py:857-869)."""
    return synchronize(handle, timeout)


def outstanding() -> Dict[int, Tuple[str, float]]:
    """Snapshot of unfinished handles: handle -> (op name, age seconds)."""
    now = time.monotonic()
    out = {}
    with _lock:
        items = list(_handle_map.items())
    for handle, (name, t0, outputs) in items:
        leaves = jax.tree_util.tree_leaves(outputs)
        done = all(
            leaf.is_ready() if hasattr(leaf, "is_ready") else True
            for leaf in leaves
        )
        if not done:
            out[handle] = (name, now - t0)
    return out
