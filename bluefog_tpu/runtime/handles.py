"""Nonblocking-op handles: poll / wait / synchronize.

Analog of BlueFog's ``HandleManager`` + per-op handles with
``poll/synchronize/wait`` (reference: torch/handle_manager.{h,cc},
torch/mpi_ops.py:823-869). JAX dispatch is already asynchronous — a collective
returns immediately with futures backing the output arrays — so a handle here
wraps the dispatched output pytree; ``synchronize`` blocks until the device
work is done and the stall watchdog tracks handles that never complete
(reference: CheckForStalledTensors, operations.cc:387-432).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax

_counter = itertools.count(1)
_lock = threading.Lock()
_handle_map: Dict[int, Tuple[str, float, Any]] = {}  # handle -> (name, t0, outputs)

def _ready(outputs) -> bool:
    """All device work backing this pytree has finished."""
    return all(
        leaf.is_ready() if hasattr(leaf, "is_ready") else True
        for leaf in jax.tree_util.tree_leaves(outputs)
    )


# Per-op COMMUNICATE spans (reference phase attribution,
# mpi_controller.cc:276-292): opened at dispatch, closed when the op's
# outputs become ready — by poll/synchronize, or by the stall watchdog's
# sweep for fire-and-forget handles nobody waits on.
# handle -> (op name, tid lane). Lanes come from a free-list so concurrent
# spans never share a tid (trace viewers pair E with the latest B on a
# tid, so a collision would swap op durations); a lane is recycled only
# after its span closes.
_open_spans: Dict[int, Tuple[str, int]] = {}
_free_lanes: list = []
_lane_counter = itertools.count(1000)


def _open_span(handle: int, name: str) -> None:
    from .timeline import timeline_start_activity

    with _lock:
        tid = _free_lanes.pop() if _free_lanes else next(_lane_counter)
        _open_spans[handle] = (name, tid)
    if not timeline_start_activity(name, "COMMUNICATE", tid):
        with _lock:  # timeline off: nothing to close later
            _open_spans.pop(handle, None)
            _free_lanes.append(tid)


def _take_span(handle: int) -> Optional[Tuple[str, int]]:
    """Claim the span (e.g. synchronize owns its completion event from here
    on; the watchdog sweep can no longer touch it)."""
    with _lock:
        return _open_spans.pop(handle, None)


def _restore_span(handle: int, span: Optional[Tuple[str, int]]) -> None:
    if span is not None:
        with _lock:
            _open_spans[handle] = span


def _emit_span_end(span: Optional[Tuple[str, int]]) -> None:
    if span is None:
        return
    from .timeline import timeline_end_activity

    name, tid = span
    timeline_end_activity(name, tid)
    with _lock:
        _free_lanes.append(tid)


def _close_span(handle: int) -> None:
    _emit_span_end(_take_span(handle))


def sweep_completed_spans() -> None:
    """Close COMMUNICATE spans of finished handles nobody polled (called by
    the stall watchdog's cycle). Spans claimed by an in-flight synchronize
    are no longer in the table, so the sweep cannot cut them short."""
    with _lock:
        candidates = [(h, _handle_map.get(h)) for h in list(_open_spans)]
    for h, entry in candidates:
        if entry is None or _ready(entry[2]):
            _close_span(h)


def close_all_spans() -> None:
    """Emit the closing edge of every open span (shutdown path — runs
    BEFORE the timeline closes so the trace stays balanced)."""
    with _lock:
        spans = list(_open_spans.values())
        _open_spans.clear()
    for span in spans:
        _emit_span_end(span)


# Fire-and-forget callers (win_put in a long gossip loop) never synchronize
# their handles; bound the table so completed entries don't pin device arrays
# for the life of the process. Oldest *finished* entries are evicted first.
_MAX_RETAINED = 4096


def _evict_completed_locked() -> None:
    if len(_handle_map) <= _MAX_RETAINED:
        return
    for handle in sorted(_handle_map):
        if _ready(_handle_map[handle][2]):
            del _handle_map[handle]
            if len(_handle_map) <= _MAX_RETAINED:
                return


def allocate(name: str, outputs: Any) -> int:
    """Register dispatched outputs; returns an integer handle."""
    handle = next(_counter)
    with _lock:
        _evict_completed_locked()
        _handle_map[handle] = (name, time.monotonic(), outputs)
    _open_span(handle, name)
    return handle


def clear() -> None:
    """Drop all handles (called by shutdown)."""
    close_all_spans()
    with _lock:
        _handle_map.clear()


def poll(handle: int) -> bool:
    """True when the op backing ``handle`` has finished executing."""
    with _lock:
        entry = _handle_map.get(handle)
    if entry is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    done = _ready(entry[2])
    if done:
        _close_span(handle)
    return done


def synchronize(handle: int, timeout: Optional[float] = None) -> Any:
    """Block until the op completes and return its output pytree.

    ``timeout`` (seconds; default from ``BLUEFOG_SYNC_TIMEOUT``, unset =
    wait forever) bounds the wait: on expiry the handle stays valid for a
    retry and a RuntimeError is raised carrying the failure detector's
    diagnosis — in a multi-controller job a dead peer (heartbeat silence)
    is named instead of the op hanging forever on the corpse. The reference
    only *warns* about stalls (CheckForStalledTensors, operations.cc:
    387-432); this makes the stall a first-class, attributable failure.
    """
    if timeout is None:
        env = os.environ.get("BLUEFOG_SYNC_TIMEOUT")
        timeout = float(env) if env else None
    # atomic pop: concurrent synchronize calls on one handle keep the
    # consume-once contract (exactly one wins; the other gets ValueError)
    with _lock:
        entry = _handle_map.pop(handle, None)
    if entry is None:
        raise ValueError(f"unknown or already-synchronized handle {handle}")
    name, t0, outputs = entry
    # claim the COMMUNICATE span: this call owns its completion edge now,
    # so the watchdog sweep (which treats a missing handle entry as done)
    # cannot cut the span short while we block
    span = _take_span(handle)
    if timeout is None:
        out = jax.block_until_ready(outputs)
        _emit_span_end(span)
        return out

    deadline = time.monotonic() + timeout

    while True:
        # readiness check runs at least once and once more AFTER the
        # deadline: an op finishing during the final sleep (or timeout=0,
        # the "poll once" form) returns instead of raising spuriously
        if _ready(outputs):
            out = jax.block_until_ready(outputs)
            _emit_span_end(span)
            return out
        if time.monotonic() >= deadline:
            break
        time.sleep(0.01)

    # timed out: re-register under the same id (span included) so the
    # caller can retry
    with _lock:
        _handle_map[handle] = entry
    _restore_span(handle, span)

    from .heartbeat import dead_controllers
    dead = dead_controllers()
    diagnosis = (
        f"controller process(es) {sorted(dead)} are DEAD (heartbeat "
        "silence) — the collective can never complete; abandon the handle "
        "and tear down" if dead else
        "no peer is reported dead — the op may be slow, the job "
        "overloaded, or a peer controller may not have dispatched the "
        "same op (see enable_topo_check / the stall watchdog)")
    msg = (f"synchronize('{name}', handle {handle}) exceeded the "
           f"{timeout:.1f}s deadline after {time.monotonic() - t0:.1f}s in "
           f"flight: {diagnosis}")
    if dead:
        # typed: callers distinguish "peer is gone, degrade the topology"
        # (PeerLostError, a RuntimeError subclass so existing handlers
        # keep working) from a plain slow-op timeout
        from .native import PeerLostError

        exc = PeerLostError(msg)
        # black-box dump before the caller decides what to do with the
        # dead peer: the ring's tail is the evidence of what hung
        from . import flight as _flight

        _flight.fatal("synchronize", exc)
        raise exc
    raise RuntimeError(msg)


def wait(handle: int, timeout: Optional[float] = None) -> Any:
    """Alias of synchronize (reference: mpi_ops.py:857-869)."""
    return synchronize(handle, timeout)


def outstanding() -> Dict[int, Tuple[str, float]]:
    """Snapshot of unfinished handles: handle -> (op name, age seconds)."""
    now = time.monotonic()
    out = {}
    with _lock:
        items = list(_handle_map.items())
    for handle, (name, t0, outputs) in items:
        if not _ready(outputs):
            out[handle] = (name, now - t0)
    return out
