"""Environment-variable configuration surface.

BlueFog configures itself exclusively through ``BLUEFOG_*`` environment
variables and function arguments (reference: docs/env_variable.rst,
operations.cc:42-47). We keep the same names where the concept survives the
move to TPU and document the ones XLA subsumes.

Knobs kept:
  BLUEFOG_LOG_LEVEL        trace/debug/info/warn/error/fatal (logging.h:56-80)
  BLUEFOG_LOG_HIDE_TIME    hide timestamps in log lines
  BLUEFOG_TIMELINE         path prefix -> enable the chrome-tracing timeline
  BLUEFOG_FUSION_THRESHOLD bytes; leaf-batching threshold for pytree fusion
                           (analog of the fusion buffer, tensor_queue.cc:127-155)
  BLUEFOG_CYCLE_TIME       ms; poll cadence of the host watchdog thread (the
                           background-loop cadence in operations.cc:459-464)
  BLUEFOG_STALL_WARNING_TIME seconds between stall warnings (operations.cc:46)
  BLUEFOG_SKIP_NEGOTIATE   '1' skips eager cross-rank validation (the analog
                           of bf.set_skip_negotiate_stage, basics.py:293-306;
                           under jit there is never a negotiation stage)
  BLUEFOG_SIMULATE_DEVICES N -> init() ranks over N forced-CPU devices even
                           when an accelerator is present (bfrun --simulate)
  BLUEFOG_WIN_HOST_PLANE   '1'/'0' forces the hosted (host-tensor-transport)
                           window data plane on/off; default: on for
                           multi-controller jobs (one-sided gossip across
                           controllers), off for single-controller (the
                           compiled ppermute plane is faster on-device)
  BLUEFOG_CP_HOST/PORT/RANK/WORLD/DISABLE/SERVE/CONNECT_TIMEOUT
                           control-plane wiring (runtime/control_plane.py);
                           auto-derived from the jax.distributed coordinator
                           in multi-controller jobs

Knobs with no TPU meaning (accepted, ignored, logged once at init):
  BLUEFOG_*_BY_MPI routing, BLUEFOG_OPS_ON_CPU, BLUEFOG_WIN_ON_GPU,
  BLUEFOG_NUM_FINALIZER_THREADS, BLUEFOG_SLEEP_USEC_FOR_WIN_PASSIVE,
  BLUEFOG_MPI_THREAD_LEVEL — all are MPI/NCCL/CUDA transport details; XLA
  owns transport on TPU. (BLUEFOG_MAX_WIN_SENT_LENGTH is LIVE since r5: it
  sizes hosted-window deposit chunks, ops/windows.py.)
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

_IGNORED_KNOBS = (
    "BLUEFOG_ALLREDUCE_BY_MPI",
    "BLUEFOG_BROADCAST_BY_MPI",
    "BLUEFOG_ALLGATHER_BY_MPI",
    "BLUEFOG_NEIGHBOR_ALLREDUCE_BY_MPI",
    "BLUEFOG_NEIGHBOR_ALLGATHER_BY_MPI",
    "BLUEFOG_WIN_OPS_BY_MPI",
    "BLUEFOG_OPS_ON_CPU",
    "BLUEFOG_WIN_ON_GPU",
    "BLUEFOG_NUM_FINALIZER_THREADS",
    "BLUEFOG_SLEEP_USEC_FOR_WIN_PASSIVE",
    "BLUEFOG_MPI_THREAD_LEVEL",
)


@dataclasses.dataclass
class Config:
    log_level: str = "warn"
    log_hide_time: bool = False
    timeline_prefix: Optional[str] = None
    fusion_threshold_bytes: int = 8 * 1024 * 1024
    cycle_time_ms: float = 0.5
    stall_warning_sec: float = 60.0
    skip_negotiate: bool = False
    simulate_devices: int = 0
    ignored_set: tuple = ()

    @classmethod
    def from_env(cls) -> "Config":
        env = os.environ
        cfg = cls(
            log_level=env.get("BLUEFOG_LOG_LEVEL", "warn").lower(),
            log_hide_time=env.get("BLUEFOG_LOG_HIDE_TIME", "0") == "1",
            timeline_prefix=env.get("BLUEFOG_TIMELINE") or None,
            fusion_threshold_bytes=int(
                env.get("BLUEFOG_FUSION_THRESHOLD", 8 * 1024 * 1024)
            ),
            cycle_time_ms=float(env.get("BLUEFOG_CYCLE_TIME", 0.5)),
            stall_warning_sec=float(env.get("BLUEFOG_STALL_WARNING_TIME", 60.0)),
            skip_negotiate=env.get("BLUEFOG_SKIP_NEGOTIATE", "0") == "1",
            simulate_devices=int(env.get("BLUEFOG_SIMULATE_DEVICES", 0)),
            ignored_set=tuple(k for k in _IGNORED_KNOBS if k in env),
        )
        return cfg


def timeout_from_env(var: str, default: float) -> float:
    """Seconds from env ``var``; warn (never raise) on a malformed value.

    Shared by the driver-facing entry scripts (``bench.py``'s backend
    probe, ``__graft_entry__``'s dryrun deadline) so their fail-fast knobs
    parse identically. Callers interpret ``<= 0`` (the opt-out convention)
    themselves.
    """
    import sys

    raw = os.environ.get(var)
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        print(f"{var}={raw!r} is not a number of seconds; "
              f"using {default:g}", file=sys.stderr)
        return default


def example_devices(n: int = 8):
    """Device list for examples/scripts run OUTSIDE ``bfrun``.

    Convention shared by every example: an explicitly EMPTY ``JAX_PLATFORMS``
    means "development CPU mesh with the accelerator plugin also registered"
    — prefer ``n`` CPU ranks over the (often 1-device) default backend.
    Returns None otherwise, letting ``bf.init`` use its defaults (which
    already honor ``bfrun --simulate`` via BLUEFOG_SIMULATE_DEVICES).
    """
    if os.environ.get("JAX_PLATFORMS", None) == "" and \
            not os.environ.get("BLUEFOG_SIMULATE_DEVICES"):
        import jax

        return jax.devices("cpu")[:n]
    return None
