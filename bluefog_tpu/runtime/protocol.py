"""Canonical wire-protocol op table for the native control plane.

This module is the single Python-side source of truth for the control
plane's op codes and their retry-safety classification. The C++ side
(``csrc/bf_runtime.cc``: ``enum Op`` and ``Client::IsDedupOp``) mirrors it
by hand — and ``scripts/bfcheck`` (the ``protocol`` analyzer, run by
``make check`` and tier-1 via ``tests/test_bfcheck.py``) parses the C++
and asserts both mirrors stay a bijection, so a new op cannot ship with a
missing mirror or a silently retry-unsafe classification.

To add an op:
  1. add an ``OpSpec`` row here (pick the next free code; decide
     ``idempotent`` deliberately — ``False`` means a retry after a lost
     reply must be served from the server's dedup table, so the client
     annotates it with ``kSeqPre``),
  2. add the enumerator to ``enum Op`` in csrc/bf_runtime.cc (numeric
     order) and, when not idempotent, to ``Client::IsDedupOp``,
  3. run ``make check`` — the analyzer verifies the bijection and the
     retry-set equality for you.

Import discipline: this module must stay dependency-free (stdlib only,
no jax, no sibling imports) — it is imported by ``runtime/native.py``
and parsed by ``scripts/bfcheck`` fixtures.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Tuple


@dataclasses.dataclass(frozen=True)
class OpSpec:
    """One wire op: Python name, wire code, C++ enumerator, retry class.

    ``idempotent=True`` ops are retried raw after a wire failure (applying
    them twice is harmless). ``idempotent=False`` ops must be applied
    exactly once: the client prefixes them with a ``kSeqPre`` annotation
    and the server records/replays their replies (docs/fault_tolerance.md).
    """

    name: str
    code: int
    cxx: str
    idempotent: bool
    doc: str = ""


OPS: Tuple[OpSpec, ...] = (
    OpSpec("barrier", 1, "kBarrier", False,
           "blocking rendezvous; a drop-and-retry must not double-count "
           "this client's arrival"),
    OpSpec("lock", 2, "kLock", True,
           "blocking acquire; a redundant re-grant is absorbed by per-rank "
           "re-entrancy and a dropped holder is force-released server-side"),
    OpSpec("unlock", 3, "kUnlock", False,
           "double-applied it would release the NEXT holder's acquisition"),
    OpSpec("fetch_add", 4, "kFetchAdd", False,
           "atomic read-modify-write; double-applied it drifts the counter"),
    OpSpec("put", 5, "kPut", True, "last-writer-wins scalar write"),
    OpSpec("get", 6, "kGet", True, "pure read"),
    OpSpec("shutdown", 7, "kShutdown", True,
           "server stop request; repeating it is a no-op"),
    OpSpec("append_bytes", 8, "kAppendBytes", False,
           "double-applied it duplicates a mailbox deposit record"),
    OpSpec("take_bytes", 9, "kTakeBytes", False,
           "destructive drain; a retry must replay the recorded haul, not "
           "drain again"),
    OpSpec("put_bytes", 10, "kPutBytes", True,
           "last-writer-wins bulk slot overwrite"),
    OpSpec("get_bytes", 11, "kGetBytes", True, "pure bulk read"),
    OpSpec("box_bytes", 12, "kBoxBytes", True,
           "pure read of a mailbox's pending byte count"),
    OpSpec("append_bytes_tagged", 13, "kAppendBytesTagged", False,
           "tagged deposit append; same exactly-once contract as "
           "append_bytes"),
    OpSpec("put_bytes_part", 14, "kPutBytesPart", False,
           "striped-put byte range into a staging buffer; a duplicate part "
           "re-arms assembly bookkeeping"),
    OpSpec("bytes_len", 15, "kBytesLen", True, "pure read of a slot's size"),
    OpSpec("get_bytes_part", 16, "kGetBytesPart", True,
           "pure ranged bulk read"),
    OpSpec("seq_pre", 17, "kSeqPre", True,
           "the reply-less dedup annotation itself; re-sending it re-arms "
           "the same (client, seq) batch"),
    OpSpec("attach", 18, "kAttach", True,
           "incarnation registration; re-registering the same incarnation "
           "is a no-op (every reconnect re-sends it)"),
    OpSpec("put_max", 19, "kPutMax", True,
           "monotone merge (kv[key] = max(kv[key], arg)) — the shard "
           "router's replication write for membership-critical keys; "
           "commutative and idempotent by construction, so replaying it "
           "after a lost reply (or onto a failover replica) cannot regress "
           "the value"),
    OpSpec("stats", 20, "kStats", True,
           "pure read of the server's telemetry counter block — how an "
           "external actor merges per-shard views without owning the "
           "server handle"),
    OpSpec("repl_apply", 21, "kReplApply", False,
           "one WAL record streamed shard-to-shard by the replicator "
           "thread (durable control plane); the record key rides the "
           "body length-prefixed (a '\\n' in a user-derived key must not "
           "corrupt the batch key framing); double-applied it would "
           "duplicate a replicated deposit or double-advance a replicated "
           "counter, so the inter-shard stream rides kSeqPre dedup like "
           "any other non-idempotent op"),
    OpSpec("snapshot", 22, "kSnapshot", True,
           "point-in-time state dump (shard rejoin catch-up); re-reading "
           "it merely re-serializes the store, and the receiver-flagged "
           "variant's stream re-arm is idempotent too (already-live "
           "streams are untouched)"),
)

# name -> wire code (the table every Python-side consumer keys off)
OP_CODES: Dict[str, int] = {o.name: o.code for o in OPS}

# code -> name (telemetry counter rows, diagnostics)
OP_NAMES: Dict[int, str] = {o.code: o.name for o in OPS}

# Ops whose effect must be applied exactly once: the client's kSeqPre
# retry set (mirrors Client::IsDedupOp in csrc/bf_runtime.cc).
RETRY_UNSAFE: FrozenSet[str] = frozenset(
    o.name for o in OPS if not o.idempotent)


def spec(name: str) -> OpSpec:
    """The OpSpec for ``name`` (KeyError on an unknown op)."""
    for o in OPS:
        if o.name == name:
            return o
    raise KeyError(f"unknown control-plane op {name!r}")
