"""One control-plane shard server, as a standalone OS process.

The sharded control plane (docs/fault_tolerance.md, "Control-plane
sharding & failover") runs N of these; clients route keys across them with
:class:`bluefog_tpu.runtime.router.ShardRouter`. Launched by
``bfrun --cp-shards N``, by ``scripts/cp_soak.py``, and by the chaos tests
(which SIGKILL it mid-job on purpose):

    python bluefog_tpu/runtime/shard_server.py --port P --world W [--shard I]

Run BY FILE PATH it bootstraps lean — the relative imports below resolve
without executing ``bluefog_tpu/__init__`` (which imports jax): a shard
server must start in milliseconds, hold no accelerator state, and cost a
few MB of RSS, because the churn soak starts and kills them in bulk.
Importable normally (``bluefog_tpu.runtime.shard_server``) for in-process
use.

Prints ``BF_SHARD_READY <port>`` on stdout once serving (the spawn-side
readiness handshake), then blocks until SIGTERM/SIGINT. The job secret
rides ``BLUEFOG_CP_SECRET`` exactly as for the single-server plane, and
the server self-publishes its effective mailbox cap under
``bf.cp.mailbox_cap_bytes`` so attach-time agreement checks can reject a
mixed-cap cluster loudly (every shard must publish its OWN value — a
router must never write this key, or a mismatch would be masked).

Durable-plane peer wiring (r16): with ``--expect-peers`` the handshake is
two-phase — the server prints ``BF_SHARD_PORT <port>`` first, the spawner
collects every shard's port and writes one ``BF_SHARD_PEERS
host:port,host:port,...`` line to each shard's stdin, and only then does
the server configure its ring successor (WAL replication,
``BLUEFOG_CP_REPLICATION``) and print the READY line. Ephemeral ports
(``--port 0``) therefore need no pre-agreed port plan. ``--rejoin``
additionally pulls a state snapshot from the ring successor, loads it,
publishes the next EVEN liveness generation under ``bf.cp.shard_dead.<i>``
so every router moves the keyspace back, and publishes its CURRENT
endpoint under ``bf.cp.shard_addr.<i>`` (generation-stamped put_max) so a
rejoin on a NEW host:port (``--port 0`` included) is re-dialed too — the
r16 "must reuse its old endpoint" limit is lifted for the router plane.
(The ring PREDECESSOR's WAL successor stream is still pinned to the old
endpoint — ``set_successor`` is one-shot native-side — so replication to
a moved shard stays degraded until the ring is restarted; routed traffic
and catch-up are unaffected.)
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__" and __package__ in (None, ""):
    # Lean bootstrap: register dummy parent packages so the relative
    # imports below resolve WITHOUT executing bluefog_tpu/__init__ (jax)
    # or bluefog_tpu/runtime/__init__ (state -> jax).
    import types

    _here = os.path.dirname(os.path.abspath(__file__))
    _pkg = os.path.dirname(_here)
    # replace sys.path[0] (this script's directory — it would shadow the
    # stdlib `logging` with runtime/logging.py) with the repo root
    sys.path[0] = os.path.dirname(_pkg)
    for _name, _path in (("bluefog_tpu", _pkg),
                         ("bluefog_tpu.runtime", _here)):
        if _name not in sys.modules:
            _mod = types.ModuleType(_name)
            _mod.__path__ = [_path]
            sys.modules[_name] = _mod
    __package__ = "bluefog_tpu.runtime"

import argparse
import signal
import threading
import time

from .config import knob_env
from .logging import logger
from .native import ControlPlaneClient, ControlPlaneServer

READY_MARKER = "BF_SHARD_READY"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bf-shard-server",
        description="Serve one shard of the bluefog control plane.")
    p.add_argument("--port", type=int, default=0,
                   help="TCP port to bind (0 = ephemeral, reported on the "
                        "READY line)")
    p.add_argument("--world", type=int, default=1,
                   help="number of controller processes in the job "
                        "(barrier arity; must match every shard)")
    p.add_argument("--shard", type=int, default=0,
                   help="this shard's index (logging only; routing is "
                        "decided client-side by key hash)")
    p.add_argument("--mailbox-max-mb", type=float, default=None,
                   help="per-mailbox byte cap (default: the "
                        "BLUEFOG_CP_MAILBOX_MAX_MB registry knob)")
    p.add_argument("--expect-peers", action="store_true",
                   help="two-phase start: print BF_SHARD_PORT, read one "
                        "'BF_SHARD_PEERS host:port,...' line from stdin, "
                        "wire the ring successor (WAL replication), then "
                        "print the READY line")
    p.add_argument("--peers", default=None, metavar="HOST:PORT,...",
                   help="explicit ring endpoint list (all shards, in "
                        "index order) when ports are known up front; "
                        "alternative to --expect-peers")
    p.add_argument("--rejoin", action="store_true",
                   help="restarted-shard catch-up: pull a state snapshot "
                        "from the ring successor, load it, and publish "
                        "the next even liveness generation plus this "
                        "server's current endpoint (bf.cp.shard_addr.<i>) "
                        "before READY (requires a peer list; a new port — "
                        "--port 0 included — is fine, routers re-dial it)")
    p.add_argument("--advertise-host", default=None,
                   help="host routers should re-dial after a rejoin "
                        "(default: this shard's entry in the peer list)")
    return p


def _parse_peers(spec: str):
    out = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        host, _, port = item.rpartition(":")
        out.append((host, int(port)))
    return out


def _published_addr(peers, idx: int, secret: str, skip: int = -1):
    """Best-effort: shard ``idx``'s CURRENT endpoint per the replicated
    ``bf.cp.shard_addr.<idx>`` key (None when never moved / no peer
    reachable). Lets a rejoiner catch up from a ring peer that itself
    rejoined on a new port earlier. ``skip`` names the CALLING shard:
    a same-port rejoiner must never dial its own listed endpoint — the
    op would park on its own still-closed rejoin gate (deadlock)."""
    from .router import SHARD_ADDR_FMT, unpack_shard_addr

    best = 0
    for j, (h, p) in enumerate(peers):
        if j == idx or j == skip:
            continue
        try:
            cl = ControlPlaneClient(h, p, 0, secret=secret, streams=1)
            try:
                best = max(best,
                           int(cl.get(SHARD_ADDR_FMT.format(idx=idx))))
            finally:
                cl.close()
        except (OSError, RuntimeError):
            continue
    dec = unpack_shard_addr(best)
    return (dec[1], dec[2]) if dec else None


def _rejoin_catch_up(srv, idx: int, peers, secret: str) -> None:
    """Restarted-shard catch-up, two pulls with distinct roles:

    1. From the ring SUCCESSOR — this shard's own keyspace, which the
       successor replicated and has been serving since the death. The
       load also RESUMES this shard's WAL numbering (``adopt_wal``) from
       the fence the successor holds against this shard's stream: a
       restart back at zero would leave every post-rejoin record at or
       below that stale fence — silently dropped-and-acked by the
       successor, i.e. lost on this shard's next death.
    2. From the ring PREDECESSOR — ITS keyspace (this shard's replica
       role). The pull carries the receiver flag (``rearm``): serving it
       re-arms the predecessor's degraded stream from that exact cut,
       and ``set_fence`` adopts the cut's fence so the resumed stream
       skips records already folded in — gap-free.

    For a two-shard ring both roles are the same endpoint, so one
    unfiltered receiver-flagged pull carries everything at a single cut
    (two filtered pulls would open a gap between their cuts)."""
    n = len(peers)
    succ = (idx + 1) % n
    pred = (idx - 1) % n
    deadline = time.monotonic() + float(knob_env("BLUEFOG_CP_REJOIN_TIMEOUT"))
    last = None
    while True:
        try:
            # a ring peer may itself have moved in an earlier rejoin; its
            # published address supersedes the static peer list
            host, port = _published_addr(peers, succ, secret, skip=idx) \
                or peers[succ]
            cl = ControlPlaneClient(host, port, 0, secret=secret, streams=1)
            try:
                if n <= 2:
                    # successor == predecessor: one cut carries both the
                    # served keyspace and the replica keyspace; the fence,
                    # the WAL resume, and the stream re-arm all anchor to
                    # that single cut
                    srv.load_snapshot(cl.snapshot(rearm=True),
                                      set_fence=True, adopt_wal=True)
                else:
                    srv.load_snapshot(cl.snapshot(n, idx), set_fence=False,
                                      adopt_wal=True)
                    ph, pp = _published_addr(peers, pred, secret,
                                             skip=idx) or peers[pred]
                    pcl = ControlPlaneClient(ph, pp, 0, secret=secret,
                                             streams=1)
                    try:
                        srv.load_snapshot(pcl.snapshot(n, pred, rearm=True),
                                          set_fence=True)
                    finally:
                        pcl.close()
            finally:
                cl.close()
            logger.warning("shard %d: rejoin catch-up complete (snapshot "
                           "from shard %d)", idx, succ)
            return
        except (OSError, RuntimeError) as exc:
            last = exc
            if time.monotonic() >= deadline:
                raise RuntimeError(
                    f"shard {idx}: rejoin catch-up failed within "
                    f"BLUEFOG_CP_REJOIN_TIMEOUT: {last}") from last
            time.sleep(0.2)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    max_mb = args.mailbox_max_mb
    if max_mb is None:
        max_mb = float(knob_env("BLUEFOG_CP_MAILBOX_MAX_MB"))
    cap = int(max_mb * (1 << 20))
    secret = os.environ.get("BLUEFOG_CP_SECRET", "")
    # --rejoin arms the rejoin gate ATOMICALLY with the bind: any op
    # served against the not-yet-loaded store would lose records now and
    # resurrect them out of order later. The cap self-publish is skipped
    # in that case — a loopback put would park on the gate, and the
    # snapshot restores the key anyway.
    srv = ControlPlaneServer(args.world, args.port, secret=secret,
                             max_mailbox_bytes=cap,
                             rejoin_pending=args.rejoin)
    if not args.rejoin:
        # Self-publish the effective cap (value + 1 so 0 still means "not
        # published") through a loopback client; origins size deposit
        # pre-checks against the SERVING side's cap, and the attach-time
        # agreement check compares every shard's copy.
        try:
            cl = ControlPlaneClient("127.0.0.1", srv.port, 0, secret=secret,
                                    streams=1)
            cl.put("bf.cp.mailbox_cap_bytes", cap + 1)
            cl.close()
        except OSError as exc:  # serve anyway; attach falls back to knob
            logger.warning("shard %d: mailbox-cap self-publish failed (%s)",
                           args.shard, exc)

    peers = _parse_peers(args.peers) if args.peers else None
    if args.expect_peers:
        # two-phase: report the bound port, then wait for the full ring
        print(f"BF_SHARD_PORT {srv.port}", flush=True)
        line = sys.stdin.readline()
        if not line.startswith("BF_SHARD_PEERS"):
            print(f"shard_server: expected a BF_SHARD_PEERS line, got "
                  f"{line!r}", file=sys.stderr)
            srv.stop()
            return 2
        peers = _parse_peers(line.split(None, 1)[1])
    if args.rejoin and not (
            peers and len(peers) > 1
            and int(knob_env("BLUEFOG_CP_REPLICATION"))):
        print("shard_server: --rejoin requires a peer ring with "
              "BLUEFOG_CP_REPLICATION enabled (the gate would never "
              "open)", file=sys.stderr)
        srv.stop()
        return 2
    addr_val = None
    if peers and len(peers) > 1 and int(knob_env("BLUEFOG_CP_REPLICATION")):
        succ_idx = (args.shard + 1) % len(peers)
        if args.rejoin:
            _rejoin_catch_up(srv, args.shard, peers, secret)
        sh, sp = (_published_addr(peers, succ_idx, secret, skip=args.shard)
                  if args.rejoin else None) or peers[succ_idx]
        srv.set_successor(sh, sp, len(peers), args.shard)
        logger.info("shard %d: WAL replication to ring successor %s:%d",
                    args.shard, sh, sp)
        if args.rejoin:
            # Announce alive ONLY NOW — after our own WAL stream is armed.
            # Routers flip traffic back the moment they see the even
            # generation, and an op served before set_successor would be
            # acked UNREPLICATED (a split-brain seed the soak caught as
            # counter-era violations). Monotone put_max + the successor's
            # WAL propagate the flag to every shard. The next even
            # generation also stamps bf.cp.shard_addr.<i> with THIS
            # server's endpoint — the key routers consult before the
            # rejoin re-dial, which is what lets a restart land on a new
            # host:port (--port 0 included).
            from .router import pack_shard_addr

            adv_host = args.advertise_host or \
                (peers[args.shard][0] if args.shard < len(peers)
                 else "127.0.0.1")
            try:
                cl = ControlPlaneClient(sh, sp, 0, secret=secret,
                                        streams=1)
                flag = f"bf.cp.shard_dead.{args.shard}"
                cur = cl.put_max(flag, 0)
                # odd (dead) -> next even; even -> next even AGAIN so the
                # generation stamped into the address key is strictly
                # fresher than any earlier rejoin's (put_max can then
                # never keep a stale endpoint)
                new_gen = cur + 1 if cur % 2 == 1 else cur + 2
                cl.put_max(flag, new_gen)
                addr_val = pack_shard_addr(new_gen, adv_host, srv.port)
                cl.put_max(f"bf.cp.shard_addr.{args.shard}", addr_val)
                cl.close()
            except OSError as exc:
                logger.warning("shard %d: alive-generation publish failed "
                               "(%s); routers will not re-route until an "
                               "operator republishes it", args.shard, exc)

    print(f"{READY_MARKER} {srv.port}", flush=True)
    logger.info("control-plane shard %d serving on port %d (world %d, "
                "mailbox cap %d bytes)", args.shard, srv.port, args.world,
                cap)

    done = threading.Event()
    if peers and len(peers) > 1 and int(knob_env("BLUEFOG_CP_REPLICATION")):
        # Alive keeper: a router whose redirect-verify dial loses a race
        # under a connect storm can FALSELY publish an odd (dead)
        # liveness generation for this perfectly live shard — and nothing
        # else would ever re-even it (the rejoin publish is one-shot).
        # While this process lives, it periodically re-asserts the next
        # even generation through its ring successor (whose WAL chains
        # the monotone put_max around the ring), so a false death claim
        # self-corrects within a poll interval; a real death stops the
        # keeper with the process.
        flag = f"bf.cp.shard_dead.{args.shard}"
        addr_key = f"bf.cp.shard_addr.{args.shard}"

        def _alive_keeper() -> None:
            from .router import pack_shard_addr

            cl = None
            while not done.wait(2.0):
                try:
                    if cl is None:
                        ah, ap = _published_addr(
                            peers, (args.shard + 1) % len(peers), secret,
                            skip=args.shard) \
                            or peers[(args.shard + 1) % len(peers)]
                        cl = ControlPlaneClient(ah, ap, 0, secret=secret,
                                                streams=1)
                    cur = cl.put_max(flag, 0)
                    if cur < 0:
                        # transport-level failure surfaces as -1, not an
                        # exception: the successor died (possibly to come
                        # back on a NEW port) — drop the client and
                        # re-resolve its published address next tick
                        cl.close()
                        cl = None
                        continue
                    if cur % 2 == 1:
                        cl.put_max(flag, cur + 1)
                        if addr_val is not None:
                            # a moved shard's endpoint must outlive false
                            # death claims: restamp it at the new even gen
                            cl.put_max(addr_key,
                                       pack_shard_addr(
                                           cur + 1,
                                           args.advertise_host
                                           or peers[args.shard][0],
                                           srv.port))
                        logger.warning(
                            "shard %d: re-asserted ALIVE (liveness "
                            "generation %d -> %d; a peer's death claim "
                            "was spurious)", args.shard, cur, cur + 1)
                    elif addr_val is not None:
                        cl.put_max(addr_key, addr_val)
                except OSError:
                    if cl is not None:
                        cl.close()
                    cl = None  # successor briefly away; redial next tick
            if cl is not None:
                cl.close()

        # bfcheck: ok-daemon-no-join (keeper must die WITH the process —
        # its whole job is that a real death stops the re-assertions; the
        # `done` event stops it on graceful SIGTERM teardown)
        threading.Thread(target=_alive_keeper, daemon=True,
                         name="bf-shard-alive").start()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    done.wait()
    srv.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
